"""Paged KV cache for generation serving: block pool, block tables,
and the tiled block-table-gathered streaming attention step.

The dense serving cache (`serving.LlamaDecodeEngine`) burns HBM
proportional to *capacity*: every slot owns `max_seq` K/V rows per
layer whether it holds a 4-token prompt or a full context. This module
replaces those rows with a **shared per-layer block pool**
``[num_blocks, block_size, KVH, D]`` plus per-slot **block tables**
mapping logical block index -> physical block, so HBM scales with
*active tokens* and a pool sized for N dense slots admits far more
short requests (the vLLM design; here grounded in the
FlashAttention-2/CUTLASS memory-streaming tiling of PAPERS.md).

Three pieces live here, deliberately factored apart:

- :class:`PagedKVCache` — the HOST side: a free-list block allocator
  with admission-time budget *reservations* (a request is admitted
  only if its worst-case block count fits, so extension at step
  boundaries can never fail mid-decode), per-slot block tables, and
  the block-pool telemetry (``serving.blocks_free`` /
  ``blocks_used`` gauges, ``block_evictions_total`` counter, flight
  events for alloc/free/exhaustion). With
  ``FLAGS_serving_prefix_cache`` (default on) it additionally keeps a
  **content-addressed radix tree** over committed prompt blocks:
  nodes are keyed by ``block_size``-token id chunks and own
  refcounted physical blocks, so admission can alias a hot prefix
  into a new slot's table instead of re-prefilling it (see
  :class:`_PrefixNode` and ``PagedKVCache.admit``'s ``token_ids``).
  Released prefixes stay cached at refcount 0 and are LRU-evicted
  when the free list runs dry (``block_evictions_total``, flight
  ``prefix_evict``).
- :func:`paged_attention` — the DEVICE side: a tiled, online-softmax
  streaming attention step that walks a slot's block list one
  ``block_size`` tile at a time, never materializing a dense
  ``[S, max_seq]`` score or cache view. Pure jnp on the tier-1/CPU
  path; the tiling is factored as one function with a flat
  (q, pools, tables, positions) signature precisely so a Pallas TPU
  kernel can drop in behind the same seam (ROADMAP item 3's
  block-table-aware variant).
- :func:`write_kv_tokens` / :func:`absmax_quantize` — the scatter of
  freshly computed K/V rows into (physical block, offset) cells, with
  optional int8 block storage using the same symmetric absmax math as
  ``quantization/quantize.py``'s ``quant_absmax`` (dynamic per-token
  per-head scales, calibration-free because decode K/V are visible).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .observability import flight as _flight
from .observability import metrics as _om

__all__ = ["PagedKVCache", "paged_attention", "write_kv_tokens",
           "absmax_quantize", "use_kernel_default", "copy_block"]

_M = _om.scope("serving")
_G_blocks_free = _M.gauge(
    "blocks_free",
    "Paged KV pool blocks available for admission (free minus "
    "outstanding budget reservations)")
_G_blocks_used = _M.gauge(
    "blocks_used", "Paged KV pool blocks physically mapped to slots")
_M_evictions = _M.counter(
    "block_evictions_total",
    "Paged KV blocks reclaimed from expired/failed/cancelled requests "
    "(normal completion frees blocks without counting here)")


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


class _PrefixNode:
    """One radix-tree node: the edge from ``parent`` is labeled by a
    full ``block_size``-token id chunk (``key``) and owns exactly one
    physical block holding that chunk's K/V rows. ``ref`` counts the
    slot tables currently aliasing the block (NOT including the cache
    itself): ref 0 means *cached* — still matchable, reclaimable by
    the LRU eviction pass when the free list runs dry. ``stamp`` is a
    monotonic last-release tick, so eviction is leaf-first
    least-recently-released.

    Invariant (every match/release refs the WHOLE path root->node):
    ``parent.ref >= child.ref`` — a ref-0 node's entire subtree is
    ref 0, so counting ref-0 nodes counts exactly the reclaimable
    supply."""

    __slots__ = ("key", "parent", "children", "block", "ref", "stamp")

    def __init__(self, key: Optional[tuple], parent: "_PrefixNode",
                 block: int = -1):
        self.key = key
        self.parent = parent
        self.children: Dict[tuple, "_PrefixNode"] = {}
        self.block = block
        self.ref = 0
        self.stamp = 0


class PagedKVCache:
    """Host-side paged-KV bookkeeping: free-list allocator + block
    tables + budget reservations.

    The invariant that makes mid-decode exhaustion impossible:
    ``len(free) >= reserved_total`` at all times. ``admit`` only
    succeeds when the request's WORST-CASE block count (prompt +
    generation budget) fits into ``free - reserved_total``; blocks
    for the prompt are mapped immediately, the rest stay *reserved*
    and are materialized one at a time by ``ensure_token`` as decode
    crosses block boundaries. ``release`` returns both.

    Thread safety: mutations are guarded by an instrumented lock
    (``analysis.locks.make_lock``) — the server loop is the only
    writer in production, but tests and direct engine use may churn
    from other threads.
    """

    def __init__(self, max_slots: int, max_seq: int, block_size: int,
                 num_blocks: int,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_blocks: Optional[int] = None):
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.max_blocks_per_slot = _ceil_div(max_seq, self.block_size)
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        # logical block index -> physical block id; -1 = unmapped. The
        # decode step receives this (as a device array) every step and
        # drops writes/reads through unmapped entries.
        self.block_tables = np.full(
            (int(max_slots), self.max_blocks_per_slot), -1, np.int32)
        # LIFO free list popping block 0 first (stable tests/debug)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._owned: Dict[int, List[int]] = {}
        self._reserved: Dict[int, int] = {}
        self._reserved_total = 0
        self.evictions = 0
        # -- prefix radix cache (FLAGS_serving_prefix_cache) ----------
        from .core.flags import flag_value
        self.prefix_enabled = bool(
            flag_value("serving_prefix_cache") if prefix_cache is None
            else prefix_cache)
        self.prefix_cap = int(
            flag_value("serving_prefix_cache_blocks")
            if prefix_cache_blocks is None else prefix_cache_blocks)
        self._root = _PrefixNode(None, None)  # type: ignore[arg-type]
        self._by_block: Dict[int, _PrefixNode] = {}
        self._evictable = 0                # tree nodes at ref 0
        self._stamp = itertools.count(1)   # LRU release ticks
        self._shared: Dict[int, List[int]] = {}   # slot -> aliased blocks
        self._tail: Dict[int, _PrefixNode] = {}   # slot -> deepest node
        self._matched: Dict[int, int] = {}        # slot -> skip tokens
        self._cow_pending: Dict[int, Tuple[int, int]] = {}
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        from .analysis.locks import make_lock
        self._lock = make_lock("serving.kv_pool")
        self._sync_gauges()

    # -- accounting ---------------------------------------------------------
    def available_blocks(self) -> int:
        """Blocks an admission may still claim: free plus the ref-0
        cached prefix blocks the LRU pass can reclaim, minus
        outstanding reservations. Shared (aliased) blocks count
        exactly once — aliasing a cached prefix consumes no supply."""
        return len(self._free) + self._evictable - self._reserved_total

    def used_blocks(self) -> int:
        """Blocks doing LIVE work — held privately by a slot or
        aliased by at least one (ref > 0). Ref-0 cached prefix blocks
        are NOT used: they are reclaimable supply the LRU pass hands
        back under pressure (``blocks_cached`` counts them)."""
        return self.num_blocks - len(self._free) - self._evictable

    def cached_blocks(self) -> int:
        """Blocks held by the prefix radix tree (shared + ref-0)."""
        return len(self._by_block)

    def occupied_slots(self) -> int:
        """Slots currently holding blocks (private or aliased)."""
        return len(set(self._owned) | set(self._shared))

    def stats(self) -> Dict[str, int]:
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_free": len(self._free),
                "blocks_available": self.available_blocks(),
                "blocks_used": self.used_blocks(),
                "blocks_reserved": self._reserved_total,
                "blocks_cached": len(self._by_block),
                "blocks_evictable": self._evictable,
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_reused": self.prefix_tokens_reused,
                "evictions": self.evictions}

    def _sync_gauges(self) -> None:
        _G_blocks_free.set(self.available_blocks())
        _G_blocks_used.set(self.used_blocks())

    # -- prefix radix tree (lock held for every _-helper) -------------------
    def _incref(self, node: _PrefixNode) -> None:
        if node.ref == 0:
            self._evictable -= 1
        node.ref += 1

    def _decref(self, node: _PrefixNode) -> None:
        node.ref -= 1
        assert node.ref >= 0, "prefix refcount underflow"
        if node.ref == 0:
            node.stamp = next(self._stamp)
            self._evictable += 1

    def _match_path(self, token_ids) -> List[_PrefixNode]:
        """Walk the tree with consecutive full-block token chunks;
        returns the matched node path (possibly empty)."""
        ids = [int(t) for t in token_ids]
        node, path = self._root, []
        for i in range(len(ids) // self.block_size):
            child = node.children.get(
                tuple(ids[i * self.block_size:(i + 1) * self.block_size]))
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def _evict_one(self) -> Optional[int]:
        """Reclaim the least-recently-released ref-0 LEAF (children
        keep their parent's block reachable; the parent becomes a leaf
        once they go). Returns the freed physical block, or None when
        nothing is evictable."""
        best = None
        for node in self._by_block.values():
            if node.ref == 0 and not node.children and \
                    (best is None or node.stamp < best.stamp):
                best = node
        if best is None:
            return None
        del best.parent.children[best.key]
        del self._by_block[best.block]
        self._evictable -= 1
        self.evictions += 1
        _M_evictions.inc()
        _flight.record("serving", "prefix_evict", block=best.block,
                       depth_key_tokens=len(best.key))
        return best.block

    def _pop_block(self) -> int:
        """One free block, evicting a cached prefix block if the free
        list is dry. Exhaustion here is a caller bug — every draw is
        covered by an admission-time reservation, and reservations are
        only granted against ``free + evictable``."""
        if self._free:
            return self._free.pop()
        b = self._evict_one()
        if b is None:
            raise RuntimeError(
                "KV block pool over-drawn: no free block and no "
                "evictable cached prefix — a reservation was granted "
                "against supply that no longer exists")
        return b

    # -- allocator ----------------------------------------------------------
    def admit(self, slot: int, prompt_tokens: int,
              total_tokens: int, token_ids=None) -> bool:
        """Admit a request into ``slot``: map blocks for its
        ``prompt_tokens`` now and reserve the rest of its
        ``total_tokens`` worst case. Returns False (request should
        wait) when the pool cannot cover the reservation; raises
        ValueError when it NEVER could (need exceeds the whole pool),
        so an impossible request fails loudly instead of queueing
        forever.

        With ``token_ids`` (the prompt) and the prefix cache on, the
        prompt is first matched against the radix tree: matched blocks
        are ALIASED into the slot's table with refcount bumps and the
        admission charges only the unshared remainder — the caller
        reads ``matched_tokens(slot)`` to skip their prefill. A match
        covering the whole (block-aligned) prompt keeps its last block
        only as a copy-on-write source: prefill must still produce the
        first generated token from position n-1, whose K/V write may
        not land in a shared block — the boundary block is copied at
        admission (one extra charged block; ``take_cow`` hands the
        (src, dst) pair to the engine's device-copy seam) and the
        match is credited as n-1 tokens."""
        slot = int(slot)
        prompt_tokens = int(prompt_tokens)
        now = _ceil_div(max(prompt_tokens, 1), self.block_size)
        total = min(max(_ceil_div(total_tokens, self.block_size), now),
                    self.max_blocks_per_slot)
        with self._lock:
            if total > self.num_blocks:
                raise ValueError(
                    f"request needs {total} KV blocks "
                    f"({total_tokens} tokens at block_size "
                    f"{self.block_size}) but the pool holds only "
                    f"{self.num_blocks}; raise FLAGS_serving_num_blocks "
                    f"or shrink the request")
            if slot in self._owned or slot in self._shared:
                raise ValueError(f"slot {slot} already holds KV blocks")
            path: List[_PrefixNode] = []
            if self.prefix_enabled and token_ids is not None:
                path = self._match_path(token_ids)
            matched = len(path)
            # a full block-aligned match still re-runs the LAST prompt
            # token (its logits seed generation), so the boundary block
            # needs a private copy-on-write clone
            cow = matched > 0 and matched * self.block_size \
                >= prompt_tokens
            # incref BEFORE allocating: the allocation below may evict
            # ref-0 nodes, which must never include our matched path
            for node in path:
                self._incref(node)
            reserved = total - now
            need_now = now - matched + (1 if cow else 0)
            if need_now + reserved > len(self._free) + self._evictable \
                    - self._reserved_total:
                avail = len(self._free) + self._evictable \
                    - self._reserved_total
                for node in path:
                    self._decref(node)
            else:
                blocks = [self._pop_block() for _ in range(need_now)]
                shared = [n.block for n in path]
                if cow:
                    # remap the boundary to its fresh clone; the engine
                    # device-copies src -> dst before any write
                    src = shared.pop()
                    self._decref(path[-1])
                    self._cow_pending[slot] = (src, blocks[0])
                for i, b in enumerate(shared):
                    self.block_tables[slot, i] = b
                for i, b in enumerate(blocks):
                    self.block_tables[slot, len(shared) + i] = b
                self._owned[slot] = list(blocks)
                self._shared[slot] = shared
                self._tail[slot] = path[len(shared) - 1] if shared \
                    else self._root
                skip = (prompt_tokens - 1) if cow \
                    else matched * self.block_size
                self._matched[slot] = skip
                if skip:
                    self.prefix_hits += 1
                    self.prefix_tokens_reused += skip
                self._reserved[slot] = reserved
                self._reserved_total += reserved
                self._sync_gauges()
                avail = None
        if avail is not None:
            _flight.record("serving", "block_exhausted", slot=slot,
                           need=need_now + reserved, available=avail)
            return False
        _flight.record("serving", "block_alloc", slot=slot,
                       blocks=need_now, shared=matched,
                       reserved=total - now,
                       available=self.available_blocks())
        return True

    def matched_tokens(self, slot: int) -> int:
        """Prompt tokens admission matched for ``slot`` — the prefill
        may start at this offset (positions below it are already
        resident in aliased / copied blocks)."""
        return self._matched.get(int(slot), 0)

    def take_cow(self, slot: int) -> Optional[Tuple[int, int]]:
        """Pop the pending boundary copy-on-write ``(src, dst)`` pair
        recorded by ``admit`` (None when the match was not
        block-aligned). The caller MUST device-copy block ``src`` ->
        ``dst`` in every pool leaf before the slot's next write."""
        return self._cow_pending.pop(int(slot), None)

    def cow_for_write(self, slot: int, pos: int) -> \
            Optional[Tuple[int, int]]:
        """Defensive copy-on-write seam for decode/speculative writers:
        if the block covering position ``pos`` of ``slot`` is a SHARED
        prefix block, detach it — allocate a clone, remap the table,
        decref the tree node — and return ``(src, dst)`` for the
        caller's device copy. Returns None on the (universal in
        production) private-block path: admission caps matches below
        the prompt length, so every write position >= len(prompt)
        lands past the shared prefix by construction."""
        slot, pos = int(slot), int(pos)
        shared = self._shared.get(slot)
        if not shared:
            return None
        bidx = pos // self.block_size
        with self._lock:
            shared = self._shared.get(slot)
            if not shared or bidx >= len(shared):
                return None
            if bidx != len(shared) - 1:
                raise RuntimeError(
                    f"write at pos {pos} targets block {bidx} INSIDE "
                    f"slot {slot}'s shared prefix ({len(shared)} "
                    f"blocks) — only the boundary block may be "
                    f"copy-on-written; truncate the slot first")
            src = shared.pop()
            node = self._by_block[src]
            dst = self._pop_block()
            self._decref(node)
            self._tail[slot] = node.parent
            self.block_tables[slot, bidx] = dst
            self._owned.setdefault(slot, []).append(dst)
            self._sync_gauges()
        return src, dst

    def commit_prefix(self, slot: int, token_ids,
                      tokens_written: int) -> int:
        """Publish ``slot``'s fully-written prompt blocks into the
        radix tree (called after each prefill chunk, so hot prefixes
        become matchable while their first writer is still
        prefilling). Only FULL blocks whose every token is already
        written commit — a half-written block must never be aliased.
        Private blocks become tree nodes (ownership transfers, the
        slot keeps an aliased ref); a block whose key already exists
        in the tree dedupes — the slot remaps onto the cached block
        and its private copy returns to the free list. Returns the
        number of blocks committed."""
        if not self.prefix_enabled:
            return 0
        slot = int(slot)
        ids = [int(t) for t in token_ids]
        full = min(int(tokens_written), len(ids)) // self.block_size
        done = 0
        with self._lock:
            shared = self._shared.get(slot)
            owned = self._owned.get(slot)
            if shared is None or owned is None:
                return 0
            tail = self._tail.get(slot, self._root)
            for bidx in range(len(shared), full):
                key = tuple(ids[bidx * self.block_size:
                               (bidx + 1) * self.block_size])
                b = int(self.block_tables[slot, bidx])
                node = tail.children.get(key)
                if node is not None:
                    # dedupe: a concurrent writer (or this slot's own
                    # COW clone) re-created cached content — alias the
                    # tree's block, free the private duplicate
                    self._incref(node)
                    owned.remove(b)
                    self._free.append(b)
                    self.block_tables[slot, bidx] = node.block
                else:
                    if self.prefix_cap and \
                            len(self._by_block) >= self.prefix_cap:
                        freed = self._evict_one()
                        if freed is None:
                            break  # bound hit, nothing reclaimable:
                            # the suffix simply stays private
                        self._free.append(freed)
                    node = _PrefixNode(key, tail, b)
                    tail.children[key] = node
                    node.ref = 1
                    self._by_block[b] = node
                    owned.remove(b)
                shared.append(node.block)
                tail = node
                done += 1
            self._tail[slot] = tail
            if done:
                self._sync_gauges()
        return done

    def reset_prefix_cache(self) -> int:
        """Drop the whole radix tree, returning every cached block to
        the free list — the crash-recovery (`reset_state`) seam: the
        device pools are rebuilt as zeros, so cached content is no
        longer backed by real K/V. Requires every slot released first
        (a live alias would dangle). Returns the blocks reclaimed."""
        with self._lock:
            if any(n.ref for n in self._by_block.values()):
                raise RuntimeError(
                    "reset_prefix_cache with live shared blocks — "
                    "release every slot first (reset_state does)")
            n = len(self._by_block)
            self._free.extend(sorted(self._by_block, reverse=True))
            self._by_block.clear()
            self._root.children.clear()
            self._evictable = 0
            self._shared.clear()
            self._tail.clear()
            self._matched.clear()
            self._cow_pending.clear()
            self._sync_gauges()
        if n:
            _flight.record("serving", "prefix_evict", block=-1,
                           reset=True, blocks=n)
        return n

    def ensure_token(self, slot: int, pos: int) -> None:
        """Map the block covering position ``pos`` of ``slot`` if it
        is not mapped yet, drawing down the slot's admission-time
        reservation (step-boundary extension). A RuntimeError here is
        a caller bug: the budget passed to ``admit`` was too small."""
        slot, pos = int(slot), int(pos)
        bidx = pos // self.block_size
        if bidx >= self.max_blocks_per_slot:
            raise ValueError(
                f"position {pos} is past the cache capacity "
                f"({self.max_blocks_per_slot * self.block_size} tokens)")
        if self.block_tables[slot, bidx] >= 0:
            return
        with self._lock:
            if self.block_tables[slot, bidx] >= 0:
                return  # raced: another thread mapped it first — a
                # double-pop here would orphan a block AND over-draw
                # the reservation (the check above is lock-free)
            if self._reserved.get(slot, 0) <= 0:
                raise RuntimeError(
                    f"slot {slot} has no KV reservation left at pos "
                    f"{pos} — the generation budget passed at admission "
                    f"was too small")
            b = self._pop_block()
            self._reserved[slot] -= 1
            self._reserved_total -= 1
            self._owned[slot].append(b)
            self.block_tables[slot, bidx] = b
            self._sync_gauges()
        _flight.record("serving", "block_alloc", slot=slot, blocks=1,
                       block_index=bidx,
                       available=self.available_blocks())

    def reserve_through(self, slot: int, pos: int) -> None:
        """Materialize every block covering positions [0, pos] — the
        decode-window pre-extension (``decode_steps`` needs a block
        table that stays valid for the whole device-resident loop)."""
        last = min(int(pos) // self.block_size,
                   self.max_blocks_per_slot - 1)
        for bidx in range(last + 1):
            if self.block_tables[int(slot), bidx] < 0:
                self.ensure_token(slot, bidx * self.block_size)

    def truncate(self, slot: int, tokens: int) -> int:
        """Roll back ``slot``'s mapping to its first ``tokens``
        positions: blocks past the last kept position are returned to
        the free list and RE-CREDITED to the slot's reservation — the
        speculative-decode rollback seam (a rejected draft's tokens
        are just extra block writes; un-mapping them restores the
        admission-time budget so the next window's pre-extension can
        draw the same blocks again). Returns the block count rolled
        back."""
        slot, tokens = int(slot), int(tokens)
        keep = _ceil_div(tokens, self.block_size) if tokens > 0 else 0
        rolled = unshared = 0
        with self._lock:
            owned = self._owned.get(slot)
            if owned is None:
                return 0
            shared = self._shared.get(slot, [])
            if keep < len(shared):
                # rolling back INTO the shared prefix (never the spec
                # path — committed streams cover the whole prompt —
                # but direct truncate may): decref, don't free, and do
                # NOT re-credit the reservation (aliased blocks were
                # never charged against it)
                for b in shared[keep:]:
                    self._decref(self._by_block[b])
                    unshared += 1
                self.block_tables[slot, keep:len(shared)] = -1
                del shared[keep:]
                tail = self._root
                for b in shared:
                    tail = self._by_block[b]
                self._tail[slot] = tail
                self._matched[slot] = min(
                    self._matched.get(slot, 0),
                    keep * self.block_size)
            for bidx in range(max(keep, len(shared)),
                              self.max_blocks_per_slot):
                b = int(self.block_tables[slot, bidx])
                if b < 0:
                    continue
                self.block_tables[slot, bidx] = -1
                owned.remove(b)
                self._free.append(b)
                rolled += 1
            if rolled:
                # invariant preserved: free and reserved_total grow by
                # the same amount, so free >= reserved_total still holds
                self._reserved[slot] = self._reserved.get(slot, 0) \
                    + rolled
                self._reserved_total += rolled
            if rolled or unshared:
                self._sync_gauges()
        if rolled or unshared:
            _flight.record("serving", "block_rollback", slot=slot,
                           blocks=rolled, unshared=unshared,
                           kept_tokens=tokens,
                           available=self.available_blocks())
        return rolled

    def release(self, slot: int, evicted: bool = False) -> int:
        """Return all of ``slot``'s private blocks, decref its shared
        prefix (the tree KEEPS those blocks cached at ref 0, where
        they stay matchable until LRU pressure reclaims them) and
        cancel its reservation. ``evicted=True`` marks a reclaim
        (deadline expiry, failure, cancellation) and bumps
        ``serving.block_evictions_total`` for the private blocks;
        normal completion leaves the counter alone."""
        slot = int(slot)
        with self._lock:
            blocks = self._owned.pop(slot, [])
            shared = self._shared.pop(slot, [])
            for b in shared:
                self._decref(self._by_block[b])
            self._tail.pop(slot, None)
            self._matched.pop(slot, None)
            self._cow_pending.pop(slot, None)
            resv = self._reserved.pop(slot, 0)
            self._reserved_total -= resv
            self._free.extend(blocks)
            self.block_tables[slot, :] = -1
            if evicted and blocks:
                self.evictions += len(blocks)
            self._sync_gauges()
        if evicted and blocks:
            _M_evictions.inc(len(blocks))
        if blocks or shared or resv:
            _flight.record("serving", "block_free", slot=slot,
                           blocks=len(blocks), unshared=len(shared),
                           evicted=bool(evicted),
                           available=self.available_blocks())
        return len(blocks)

    def check_invariants(self) -> None:
        """Assert the allocator's global invariants (the tests'
        step-boundary probe; not on any hot path):

        - free / privately-owned / tree blocks PARTITION the pool;
        - every node's refcount equals the number of slots aliasing
          its block, and never exceeds its parent's;
        - the evictable count equals the ref-0 node count;
        - each slot's shared blocks are a contiguous table prefix;
        - ``free + evictable - reserved_total >= 0`` (reservations
          can always be honored without touching a live block).
        """
        with self._lock:
            free = list(self._free)
            owned_all = [b for bs in self._owned.values() for b in bs]
            tree = list(self._by_block)
            assert len(set(free)) == len(free), "free-list duplicates"
            assert len(set(owned_all)) == len(owned_all), \
                "block owned by two slots"
            union = free + owned_all + tree
            assert sorted(union) == list(range(self.num_blocks)), (
                f"pool partition broken: free={sorted(free)} "
                f"owned={sorted(owned_all)} tree={sorted(tree)}")
            want_ref: Dict[int, int] = {}
            for slot, shared in self._shared.items():
                for i, b in enumerate(shared):
                    assert int(self.block_tables[slot, i]) == b, \
                        f"slot {slot} shared prefix not contiguous"
                    want_ref[b] = want_ref.get(b, 0) + 1
            zero = 0
            for b, node in self._by_block.items():
                assert node.block == b
                assert node.ref == want_ref.get(b, 0), (
                    f"block {b}: ref {node.ref} != "
                    f"{want_ref.get(b, 0)} aliasing slots")
                assert node.parent is self._root \
                    or node.parent.ref >= node.ref, \
                    f"block {b}: child outrefs its parent"
                zero += node.ref == 0
            assert zero == self._evictable, \
                f"evictable count {self._evictable} != {zero} ref-0 nodes"
            assert self._reserved_total == sum(self._reserved.values())
            assert len(free) + zero - self._reserved_total >= 0, (
                f"reservation invariant broken: free={len(free)} "
                f"evictable={zero} reserved={self._reserved_total}")

    def active_tokens(self, pos: np.ndarray,
                      active: np.ndarray) -> int:
        """Tokens currently resident across active slots (the paged
        roofline's cache-traffic term: O(active tokens), not
        O(slots x max_seq))."""
        return int(sum(int(p) for p, a in zip(pos, active) if a))


# ---------------------------------------------------------------------------
# device side: quantized block writes + tiled streaming attention
# ---------------------------------------------------------------------------

def absmax_quantize(x, bits: int = 8):
    """Symmetric per-(token, head) absmax int8 of K/V rows
    ``[N, KVH, D]`` -> ``(codes int8 [N, KVH, D], scale f32 [N, KVH])``
    — the ``quantization.quantize.quant_absmax`` step computation
    (dynamic absmax over the head dim, qmax = 2^(bits-1) - 1), kept
    raw-code-valued here because the pool STORES the codes and the
    attention tiles dequantize on gather."""
    qmax = float(2 ** (bits - 1) - 1)
    a = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(a), axis=-1), 1e-8) / qmax
    codes = jnp.clip(jnp.round(a / scale[..., None]),
                     -qmax, qmax).astype(jnp.int8)
    return codes, scale


def copy_block(pool, src, dst):
    """Device-copy one whole physical block (all ``block_size`` rows)
    ``pool[src] -> pool[dst]`` — the copy-on-write data move, riding
    the same scatter seam as :func:`write_kv_tokens` (an ``.at[]``
    update the engine runs with the pool donated, so the copy lands in
    place in HBM)."""
    return pool.at[dst].set(pool[src])


def write_kv_tokens(pool, phys, off, vals):
    """Scatter ``vals [N, ...]`` into ``pool[phys[i], off[i]]`` cells;
    rows whose ``phys`` is out of range (the caller maps invalid rows
    to ``num_blocks``) are dropped, so padded prefill rows and
    inactive decode slots never touch a real block."""
    return pool.at[phys, off].set(vals.astype(pool.dtype), mode="drop")


# [T, H, D] f32 bytes above which the Pallas kernel's per-program
# VMEM working set (acc scratch + q/out tiles, each T*H*D*4) risks the
# ~16 MB/core budget — such calls fall back to the jnp walk
_KERNEL_Q_VMEM_BUDGET = 4 * 1024 * 1024


def use_kernel_default() -> bool:
    """The seam's path decision: the Pallas block-table kernel when
    ``FLAGS_paged_attention_kernel`` is on AND the backend supports it;
    the pure-jnp tiled walk (the numerics oracle) otherwise. One
    function so engines can count the live path per step without
    re-deriving the policy."""
    from .core.flags import flag_value
    if not flag_value("paged_attention_kernel"):
        return False
    from .ops.pallas import paged_attention as _pk
    return _pk.kernel_available()


def paged_attention(q, k_pool, v_pool, tables, positions, *,
                    block_size: int, n_rep: int, n_tiles=None,
                    k_scale=None, v_scale=None, use_kernel=None):
    """Block-table-gathered streaming attention for one layer.

    ``q [S, T, H, D]`` attends to the K/V history of its slot, stored
    as pool blocks ``[num_blocks, block_size, KVH, D]`` addressed
    through ``tables [S, max_blocks]`` (entry < 0 = unmapped). Row
    ``(s, t)`` may attend every column ``c <= positions[s, t]``.

    The walk is an online-softmax loop over ``block_size`` tiles
    (``jax.lax.fori_loop``, so ``n_tiles`` — typically
    ``max(positions)//block_size + 1`` — may be a traced value and
    short sequences pay only their own tiles): per tile it gathers one
    block per slot, forms ``[S, ., T, block_size]`` scores, and folds
    them into running (max, denominator, accumulator) carries. No
    ``[S, max_seq]`` score or cache view ever exists — peak extra
    memory is one tile, which is what lets a Pallas TPU kernel replace
    this function behind the same signature.

    GQA runs against the UNEXPANDED pools (grouped contraction, the
    dense engine's trick): ``n_rep = H // KVH`` query heads share each
    KV head. ``k_scale/v_scale [num_blocks, block_size, KVH]`` switch
    the gather to int8-dequant mode (absmax codes in the pools).

    ``use_kernel`` selects the implementation behind this ONE seam:
    None (default) follows ``FLAGS_paged_attention_kernel`` + backend
    availability, True forces the Pallas TPU kernel
    (``ops.pallas.paged_attention``), False forces the jnp walk below
    — which stays the numerics ORACLE the kernel is parity-pinned
    against (tests/test_serving_spec.py runs the kernel through the
    Pallas interpreter on CPU and asserts same-numerics).
    """
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if use_kernel and q.shape[1] * q.shape[2] * q.shape[3] * 4 \
            > _KERNEL_Q_VMEM_BUDGET:
        # the kernel's f32 accumulator scratch (and its q/out tiles)
        # scale with T*H*D: decode (T=1), spec verify (T=k+1) and
        # chunked prefill all fit easily, but the DENSE engine's
        # un-chunked whole-prompt prefill can exceed per-core VMEM —
        # those calls take the jnp walk, same numerics
        use_kernel = False
    if use_kernel:
        from .ops.pallas import paged_attention as _pk
        return _pk.paged_attention_kernel(
            q, k_pool, v_pool, tables, positions,
            block_size=block_size, n_rep=n_rep, n_tiles=n_tiles,
            k_scale=k_scale, v_scale=v_scale)
    S, T, H, D = q.shape
    K = k_pool.shape[2]
    R = int(n_rep)
    assert K * R == H, (K, R, H)
    if n_tiles is None:
        n_tiles = tables.shape[1]
    q5 = q.reshape(S, T, K, R, D)
    inv_sqrt_d = 1.0 / np.sqrt(D)
    cols0 = jnp.arange(block_size)
    m0 = jnp.full((S, K, R, T), -1e30, jnp.float32)
    l0 = jnp.zeros((S, K, R, T), jnp.float32)
    a0 = jnp.zeros((S, K, R, T, D), jnp.float32)

    def tile(i, carry):
        m, l, acc = carry
        phys = jnp.maximum(tables[:, i], 0)            # [S]
        k_t = k_pool[phys]                             # [S, bs, K, D]
        v_t = v_pool[phys]
        if k_scale is not None:
            k_t = (k_t.astype(jnp.float32)
                   * k_scale[phys][..., None]).astype(q.dtype)
            v_t = (v_t.astype(jnp.float32)
                   * v_scale[phys][..., None]).astype(q.dtype)
        # RECYCLED blocks may hold non-finite garbage from a previous
        # request (a pathological prompt can drive activations to
        # NaN/inf). Masked columns must contribute EXACTLY zero, but
        # 0 * NaN = NaN in the PV contraction below — sanitize the
        # gathered tile so one request's garbage can never leak into
        # another request sharing the pool (the dense engine's
        # stale rows are at worst slot-local; the pool's must be
        # inert everywhere)
        k_t = jnp.nan_to_num(k_t)
        v_t = jnp.nan_to_num(v_t)
        s = jnp.einsum("stkrd,sbkd->skrtb", q5, k_t,
                       preferred_element_type=jnp.float32) * inv_sqrt_d
        # [S, T, bs] -> broadcast over (K, R); also masks unmapped
        # blocks (cols of tile i all exceed positions that never
        # reached it) and clamped phys-0 garbage for inactive slots
        ok = (i * block_size + cols0)[None, None, :] \
            <= positions[:, :, None]
        okb = ok[:, None, None, :, :]
        s = jnp.where(okb, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # a fully-masked row has s == m_new == -1e30: exp() gives 1,
        # so re-mask p to zero its contribution exactly
        p = jnp.where(okb, jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("skrtb,sbkd->skrtd", p.astype(v_t.dtype), v_t,
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr[..., None] + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_tiles, tile, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(S, T, H, D).astype(
        q.dtype)
