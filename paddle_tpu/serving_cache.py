"""Paged KV cache for generation serving: block pool, block tables,
and the tiled block-table-gathered streaming attention step.

The dense serving cache (`serving.LlamaDecodeEngine`) burns HBM
proportional to *capacity*: every slot owns `max_seq` K/V rows per
layer whether it holds a 4-token prompt or a full context. This module
replaces those rows with a **shared per-layer block pool**
``[num_blocks, block_size, KVH, D]`` plus per-slot **block tables**
mapping logical block index -> physical block, so HBM scales with
*active tokens* and a pool sized for N dense slots admits far more
short requests (the vLLM design; here grounded in the
FlashAttention-2/CUTLASS memory-streaming tiling of PAPERS.md).

Three pieces live here, deliberately factored apart:

- :class:`PagedKVCache` — the HOST side: a free-list block allocator
  with admission-time budget *reservations* (a request is admitted
  only if its worst-case block count fits, so extension at step
  boundaries can never fail mid-decode), per-slot block tables, and
  the block-pool telemetry (``serving.blocks_free`` /
  ``blocks_used`` gauges, ``block_evictions_total`` counter, flight
  events for alloc/free/exhaustion).
- :func:`paged_attention` — the DEVICE side: a tiled, online-softmax
  streaming attention step that walks a slot's block list one
  ``block_size`` tile at a time, never materializing a dense
  ``[S, max_seq]`` score or cache view. Pure jnp on the tier-1/CPU
  path; the tiling is factored as one function with a flat
  (q, pools, tables, positions) signature precisely so a Pallas TPU
  kernel can drop in behind the same seam (ROADMAP item 3's
  block-table-aware variant).
- :func:`write_kv_tokens` / :func:`absmax_quantize` — the scatter of
  freshly computed K/V rows into (physical block, offset) cells, with
  optional int8 block storage using the same symmetric absmax math as
  ``quantization/quantize.py``'s ``quant_absmax`` (dynamic per-token
  per-head scales, calibration-free because decode K/V are visible).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .observability import flight as _flight
from .observability import metrics as _om

__all__ = ["PagedKVCache", "paged_attention", "write_kv_tokens",
           "absmax_quantize", "use_kernel_default"]

_M = _om.scope("serving")
_G_blocks_free = _M.gauge(
    "blocks_free",
    "Paged KV pool blocks available for admission (free minus "
    "outstanding budget reservations)")
_G_blocks_used = _M.gauge(
    "blocks_used", "Paged KV pool blocks physically mapped to slots")
_M_evictions = _M.counter(
    "block_evictions_total",
    "Paged KV blocks reclaimed from expired/failed/cancelled requests "
    "(normal completion frees blocks without counting here)")


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


class PagedKVCache:
    """Host-side paged-KV bookkeeping: free-list allocator + block
    tables + budget reservations.

    The invariant that makes mid-decode exhaustion impossible:
    ``len(free) >= reserved_total`` at all times. ``admit`` only
    succeeds when the request's WORST-CASE block count (prompt +
    generation budget) fits into ``free - reserved_total``; blocks
    for the prompt are mapped immediately, the rest stay *reserved*
    and are materialized one at a time by ``ensure_token`` as decode
    crosses block boundaries. ``release`` returns both.

    Thread safety: mutations are guarded by an instrumented lock
    (``analysis.locks.make_lock``) — the server loop is the only
    writer in production, but tests and direct engine use may churn
    from other threads.
    """

    def __init__(self, max_slots: int, max_seq: int, block_size: int,
                 num_blocks: int):
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.max_blocks_per_slot = _ceil_div(max_seq, self.block_size)
        self.num_blocks = int(num_blocks)
        if self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        # logical block index -> physical block id; -1 = unmapped. The
        # decode step receives this (as a device array) every step and
        # drops writes/reads through unmapped entries.
        self.block_tables = np.full(
            (int(max_slots), self.max_blocks_per_slot), -1, np.int32)
        # LIFO free list popping block 0 first (stable tests/debug)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._owned: Dict[int, List[int]] = {}
        self._reserved: Dict[int, int] = {}
        self._reserved_total = 0
        self.evictions = 0
        from .analysis.locks import make_lock
        self._lock = make_lock("serving.kv_pool")
        self._sync_gauges()

    # -- accounting ---------------------------------------------------------
    def available_blocks(self) -> int:
        """Blocks an admission may still claim (free minus reserved)."""
        return len(self._free) - self._reserved_total

    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def stats(self) -> Dict[str, int]:
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_free": len(self._free),
                "blocks_available": self.available_blocks(),
                "blocks_used": self.used_blocks(),
                "blocks_reserved": self._reserved_total,
                "evictions": self.evictions}

    def _sync_gauges(self) -> None:
        _G_blocks_free.set(self.available_blocks())
        _G_blocks_used.set(self.used_blocks())

    # -- allocator ----------------------------------------------------------
    def admit(self, slot: int, prompt_tokens: int,
              total_tokens: int) -> bool:
        """Admit a request into ``slot``: map blocks for its
        ``prompt_tokens`` now and reserve the rest of its
        ``total_tokens`` worst case. Returns False (request should
        wait) when the pool cannot cover the reservation; raises
        ValueError when it NEVER could (need exceeds the whole pool),
        so an impossible request fails loudly instead of queueing
        forever."""
        slot = int(slot)
        now = _ceil_div(max(int(prompt_tokens), 1), self.block_size)
        total = min(max(_ceil_div(total_tokens, self.block_size), now),
                    self.max_blocks_per_slot)
        with self._lock:
            if total > self.num_blocks:
                raise ValueError(
                    f"request needs {total} KV blocks "
                    f"({total_tokens} tokens at block_size "
                    f"{self.block_size}) but the pool holds only "
                    f"{self.num_blocks}; raise FLAGS_serving_num_blocks "
                    f"or shrink the request")
            if slot in self._owned:
                raise ValueError(f"slot {slot} already holds KV blocks")
            if total > self.available_blocks():
                avail = self.available_blocks()
            else:
                blocks = [self._free.pop() for _ in range(now)]
                self._owned[slot] = blocks
                self._reserved[slot] = total - now
                self._reserved_total += total - now
                self.block_tables[slot, :now] = blocks
                self._sync_gauges()
                avail = None
        if avail is not None:
            _flight.record("serving", "block_exhausted", slot=slot,
                           need=total, available=avail)
            return False
        _flight.record("serving", "block_alloc", slot=slot,
                       blocks=now, reserved=total - now,
                       available=self.available_blocks())
        return True

    def ensure_token(self, slot: int, pos: int) -> None:
        """Map the block covering position ``pos`` of ``slot`` if it
        is not mapped yet, drawing down the slot's admission-time
        reservation (step-boundary extension). A RuntimeError here is
        a caller bug: the budget passed to ``admit`` was too small."""
        slot, pos = int(slot), int(pos)
        bidx = pos // self.block_size
        if bidx >= self.max_blocks_per_slot:
            raise ValueError(
                f"position {pos} is past the cache capacity "
                f"({self.max_blocks_per_slot * self.block_size} tokens)")
        if self.block_tables[slot, bidx] >= 0:
            return
        with self._lock:
            if self.block_tables[slot, bidx] >= 0:
                return  # raced: another thread mapped it first — a
                # double-pop here would orphan a block AND over-draw
                # the reservation (the check above is lock-free)
            if self._reserved.get(slot, 0) <= 0:
                raise RuntimeError(
                    f"slot {slot} has no KV reservation left at pos "
                    f"{pos} — the generation budget passed at admission "
                    f"was too small")
            b = self._free.pop()
            self._reserved[slot] -= 1
            self._reserved_total -= 1
            self._owned[slot].append(b)
            self.block_tables[slot, bidx] = b
            self._sync_gauges()
        _flight.record("serving", "block_alloc", slot=slot, blocks=1,
                       block_index=bidx,
                       available=self.available_blocks())

    def reserve_through(self, slot: int, pos: int) -> None:
        """Materialize every block covering positions [0, pos] — the
        decode-window pre-extension (``decode_steps`` needs a block
        table that stays valid for the whole device-resident loop)."""
        last = min(int(pos) // self.block_size,
                   self.max_blocks_per_slot - 1)
        for bidx in range(last + 1):
            if self.block_tables[int(slot), bidx] < 0:
                self.ensure_token(slot, bidx * self.block_size)

    def truncate(self, slot: int, tokens: int) -> int:
        """Roll back ``slot``'s mapping to its first ``tokens``
        positions: blocks past the last kept position are returned to
        the free list and RE-CREDITED to the slot's reservation — the
        speculative-decode rollback seam (a rejected draft's tokens
        are just extra block writes; un-mapping them restores the
        admission-time budget so the next window's pre-extension can
        draw the same blocks again). Returns the block count rolled
        back."""
        slot, tokens = int(slot), int(tokens)
        keep = _ceil_div(tokens, self.block_size) if tokens > 0 else 0
        rolled = 0
        with self._lock:
            owned = self._owned.get(slot)
            if owned is None:
                return 0
            for bidx in range(keep, self.max_blocks_per_slot):
                b = int(self.block_tables[slot, bidx])
                if b < 0:
                    continue
                self.block_tables[slot, bidx] = -1
                owned.remove(b)
                self._free.append(b)
                rolled += 1
            if rolled:
                # invariant preserved: free and reserved_total grow by
                # the same amount, so free >= reserved_total still holds
                self._reserved[slot] = self._reserved.get(slot, 0) \
                    + rolled
                self._reserved_total += rolled
                self._sync_gauges()
        if rolled:
            _flight.record("serving", "block_rollback", slot=slot,
                           blocks=rolled, kept_tokens=tokens,
                           available=self.available_blocks())
        return rolled

    def release(self, slot: int, evicted: bool = False) -> int:
        """Return all of ``slot``'s blocks and cancel its reservation.
        ``evicted=True`` marks a reclaim (deadline expiry, failure,
        cancellation) and bumps ``serving.block_evictions_total``;
        normal completion leaves the counter alone."""
        slot = int(slot)
        with self._lock:
            blocks = self._owned.pop(slot, [])
            resv = self._reserved.pop(slot, 0)
            self._reserved_total -= resv
            self._free.extend(blocks)
            self.block_tables[slot, :] = -1
            if evicted and blocks:
                self.evictions += len(blocks)
            self._sync_gauges()
        if evicted and blocks:
            _M_evictions.inc(len(blocks))
        if blocks or resv:
            _flight.record("serving", "block_free", slot=slot,
                           blocks=len(blocks), evicted=bool(evicted),
                           available=self.available_blocks())
        return len(blocks)

    def active_tokens(self, pos: np.ndarray,
                      active: np.ndarray) -> int:
        """Tokens currently resident across active slots (the paged
        roofline's cache-traffic term: O(active tokens), not
        O(slots x max_seq))."""
        return int(sum(int(p) for p, a in zip(pos, active) if a))


# ---------------------------------------------------------------------------
# device side: quantized block writes + tiled streaming attention
# ---------------------------------------------------------------------------

def absmax_quantize(x, bits: int = 8):
    """Symmetric per-(token, head) absmax int8 of K/V rows
    ``[N, KVH, D]`` -> ``(codes int8 [N, KVH, D], scale f32 [N, KVH])``
    — the ``quantization.quantize.quant_absmax`` step computation
    (dynamic absmax over the head dim, qmax = 2^(bits-1) - 1), kept
    raw-code-valued here because the pool STORES the codes and the
    attention tiles dequantize on gather."""
    qmax = float(2 ** (bits - 1) - 1)
    a = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(a), axis=-1), 1e-8) / qmax
    codes = jnp.clip(jnp.round(a / scale[..., None]),
                     -qmax, qmax).astype(jnp.int8)
    return codes, scale


def write_kv_tokens(pool, phys, off, vals):
    """Scatter ``vals [N, ...]`` into ``pool[phys[i], off[i]]`` cells;
    rows whose ``phys`` is out of range (the caller maps invalid rows
    to ``num_blocks``) are dropped, so padded prefill rows and
    inactive decode slots never touch a real block."""
    return pool.at[phys, off].set(vals.astype(pool.dtype), mode="drop")


# [T, H, D] f32 bytes above which the Pallas kernel's per-program
# VMEM working set (acc scratch + q/out tiles, each T*H*D*4) risks the
# ~16 MB/core budget — such calls fall back to the jnp walk
_KERNEL_Q_VMEM_BUDGET = 4 * 1024 * 1024


def use_kernel_default() -> bool:
    """The seam's path decision: the Pallas block-table kernel when
    ``FLAGS_paged_attention_kernel`` is on AND the backend supports it;
    the pure-jnp tiled walk (the numerics oracle) otherwise. One
    function so engines can count the live path per step without
    re-deriving the policy."""
    from .core.flags import flag_value
    if not flag_value("paged_attention_kernel"):
        return False
    from .ops.pallas import paged_attention as _pk
    return _pk.kernel_available()


def paged_attention(q, k_pool, v_pool, tables, positions, *,
                    block_size: int, n_rep: int, n_tiles=None,
                    k_scale=None, v_scale=None, use_kernel=None):
    """Block-table-gathered streaming attention for one layer.

    ``q [S, T, H, D]`` attends to the K/V history of its slot, stored
    as pool blocks ``[num_blocks, block_size, KVH, D]`` addressed
    through ``tables [S, max_blocks]`` (entry < 0 = unmapped). Row
    ``(s, t)`` may attend every column ``c <= positions[s, t]``.

    The walk is an online-softmax loop over ``block_size`` tiles
    (``jax.lax.fori_loop``, so ``n_tiles`` — typically
    ``max(positions)//block_size + 1`` — may be a traced value and
    short sequences pay only their own tiles): per tile it gathers one
    block per slot, forms ``[S, ., T, block_size]`` scores, and folds
    them into running (max, denominator, accumulator) carries. No
    ``[S, max_seq]`` score or cache view ever exists — peak extra
    memory is one tile, which is what lets a Pallas TPU kernel replace
    this function behind the same signature.

    GQA runs against the UNEXPANDED pools (grouped contraction, the
    dense engine's trick): ``n_rep = H // KVH`` query heads share each
    KV head. ``k_scale/v_scale [num_blocks, block_size, KVH]`` switch
    the gather to int8-dequant mode (absmax codes in the pools).

    ``use_kernel`` selects the implementation behind this ONE seam:
    None (default) follows ``FLAGS_paged_attention_kernel`` + backend
    availability, True forces the Pallas TPU kernel
    (``ops.pallas.paged_attention``), False forces the jnp walk below
    — which stays the numerics ORACLE the kernel is parity-pinned
    against (tests/test_serving_spec.py runs the kernel through the
    Pallas interpreter on CPU and asserts same-numerics).
    """
    if use_kernel is None:
        use_kernel = use_kernel_default()
    if use_kernel and q.shape[1] * q.shape[2] * q.shape[3] * 4 \
            > _KERNEL_Q_VMEM_BUDGET:
        # the kernel's f32 accumulator scratch (and its q/out tiles)
        # scale with T*H*D: decode (T=1), spec verify (T=k+1) and
        # chunked prefill all fit easily, but the DENSE engine's
        # un-chunked whole-prompt prefill can exceed per-core VMEM —
        # those calls take the jnp walk, same numerics
        use_kernel = False
    if use_kernel:
        from .ops.pallas import paged_attention as _pk
        return _pk.paged_attention_kernel(
            q, k_pool, v_pool, tables, positions,
            block_size=block_size, n_rep=n_rep, n_tiles=n_tiles,
            k_scale=k_scale, v_scale=v_scale)
    S, T, H, D = q.shape
    K = k_pool.shape[2]
    R = int(n_rep)
    assert K * R == H, (K, R, H)
    if n_tiles is None:
        n_tiles = tables.shape[1]
    q5 = q.reshape(S, T, K, R, D)
    inv_sqrt_d = 1.0 / np.sqrt(D)
    cols0 = jnp.arange(block_size)
    m0 = jnp.full((S, K, R, T), -1e30, jnp.float32)
    l0 = jnp.zeros((S, K, R, T), jnp.float32)
    a0 = jnp.zeros((S, K, R, T, D), jnp.float32)

    def tile(i, carry):
        m, l, acc = carry
        phys = jnp.maximum(tables[:, i], 0)            # [S]
        k_t = k_pool[phys]                             # [S, bs, K, D]
        v_t = v_pool[phys]
        if k_scale is not None:
            k_t = (k_t.astype(jnp.float32)
                   * k_scale[phys][..., None]).astype(q.dtype)
            v_t = (v_t.astype(jnp.float32)
                   * v_scale[phys][..., None]).astype(q.dtype)
        # RECYCLED blocks may hold non-finite garbage from a previous
        # request (a pathological prompt can drive activations to
        # NaN/inf). Masked columns must contribute EXACTLY zero, but
        # 0 * NaN = NaN in the PV contraction below — sanitize the
        # gathered tile so one request's garbage can never leak into
        # another request sharing the pool (the dense engine's
        # stale rows are at worst slot-local; the pool's must be
        # inert everywhere)
        k_t = jnp.nan_to_num(k_t)
        v_t = jnp.nan_to_num(v_t)
        s = jnp.einsum("stkrd,sbkd->skrtb", q5, k_t,
                       preferred_element_type=jnp.float32) * inv_sqrt_d
        # [S, T, bs] -> broadcast over (K, R); also masks unmapped
        # blocks (cols of tile i all exceed positions that never
        # reached it) and clamped phys-0 garbage for inactive slots
        ok = (i * block_size + cols0)[None, None, :] \
            <= positions[:, :, None]
        okb = ok[:, None, None, :, :]
        s = jnp.where(okb, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # a fully-masked row has s == m_new == -1e30: exp() gives 1,
        # so re-mask p to zero its contribution exactly
        p = jnp.where(okb, jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("skrtb,sbkd->skrtd", p.astype(v_t.dtype), v_t,
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr[..., None] + pv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_tiles, tile, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(S, T, H, D).astype(
        q.dtype)
