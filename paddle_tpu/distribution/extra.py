"""Extended distribution zoo.

ref: python/paddle/distribution/{beta,gamma,chi2,dirichlet,geometric,
poisson,binomial,multinomial,student_t,cauchy,multivariate_normal,
independent,transform,transformed_distribution}.py — same API surface,
implemented over jax.random (gamma/dirichlet samplers carry implicit
reparameterization gradients, so rsample is differentiable where the
reference's is).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import betaln, digamma, gammaln

from ..core import random as random_mod
from ..core.autograd import apply_op
from ..core.tensor import Tensor
from .distributions import (Distribution, _shape, _t, kl_divergence,
                            register_kl)

__all__ = [
    "Beta", "Gamma", "Chi2", "Dirichlet", "Geometric", "Poisson",
    "Binomial", "Multinomial", "StudentT", "Cauchy", "MultivariateNormal",
    "Independent", "TransformedDistribution", "Transform",
    "AffineTransform", "ExpTransform", "SigmoidTransform", "TanhTransform",
    "AbsTransform", "PowerTransform", "ChainTransform",
]


class Gamma(Distribution):
    """ref: gamma.py Gamma(concentration, rate)."""

    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(jnp.broadcast_shapes(
            self.concentration._data.shape, self.rate._data.shape))

    @property
    def mean(self):
        return apply_op(lambda a, r: jnp.broadcast_to(a / r,
                                                      self.batch_shape),
                        self.concentration, self.rate, op_name="gamma_mean")

    @property
    def variance(self):
        return apply_op(lambda a, r: jnp.broadcast_to(a / r ** 2,
                                                      self.batch_shape),
                        self.concentration, self.rate, op_name="gamma_var")

    def rsample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(a, r):
            g = jax.random.gamma(key, jnp.broadcast_to(a, shp))
            return g / r
        return apply_op(f, self.concentration, self.rate,
                        op_name="gamma_rsample")

    def log_prob(self, value):
        def f(v, a, r):
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - gammaln(a))
        return apply_op(f, _t(value), self.concentration, self.rate,
                        op_name="gamma_log_prob")

    def entropy(self):
        def f(a, r):
            out = a - jnp.log(r) + gammaln(a) + (1 - a) * digamma(a)
            return jnp.broadcast_to(out, self.batch_shape)
        return apply_op(f, self.concentration, self.rate,
                        op_name="gamma_entropy")


class Chi2(Gamma):
    """ref: chi2.py Chi2(df) == Gamma(df/2, 1/2)."""

    def __init__(self, df):
        self.df = _t(df)
        super().__init__(apply_op(lambda d: d / 2, self.df,
                                  op_name="chi2_df"), _t(0.5))


class Beta(Distribution):
    """ref: beta.py Beta(alpha, beta); sampled as Ga/(Ga+Gb)."""

    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha._data.shape,
                                              self.beta._data.shape))

    @property
    def mean(self):
        return apply_op(lambda a, b: jnp.broadcast_to(a / (a + b),
                                                      self.batch_shape),
                        self.alpha, self.beta, op_name="beta_mean")

    @property
    def variance(self):
        def f(a, b):
            t = a + b
            return jnp.broadcast_to(a * b / (t * t * (t + 1)),
                                    self.batch_shape)
        return apply_op(f, self.alpha, self.beta, op_name="beta_var")

    def rsample(self, shape=()):
        k1, k2 = random_mod.next_key(), random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(a, b):
            ga = jax.random.gamma(k1, jnp.broadcast_to(a, shp))
            gb = jax.random.gamma(k2, jnp.broadcast_to(b, shp))
            return ga / (ga + gb)
        return apply_op(f, self.alpha, self.beta, op_name="beta_rsample")

    def log_prob(self, value):
        def f(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - betaln(a, b))
        return apply_op(f, _t(value), self.alpha, self.beta,
                        op_name="beta_log_prob")

    def entropy(self):
        def f(a, b):
            t = a + b
            out = (betaln(a, b) - (a - 1) * digamma(a)
                   - (b - 1) * digamma(b) + (t - 2) * digamma(t))
            return jnp.broadcast_to(out, self.batch_shape)
        return apply_op(f, self.alpha, self.beta, op_name="beta_entropy")


class Dirichlet(Distribution):
    """ref: dirichlet.py Dirichlet(concentration)."""

    def __init__(self, concentration):
        self.concentration = _t(concentration)
        shape = self.concentration._data.shape
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return apply_op(lambda c: c / jnp.sum(c, -1, keepdims=True),
                        self.concentration, op_name="dirichlet_mean")

    @property
    def variance(self):
        def f(c):
            c0 = jnp.sum(c, -1, keepdims=True)
            m = c / c0
            return m * (1 - m) / (c0 + 1)
        return apply_op(f, self.concentration, op_name="dirichlet_var")

    def rsample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape + self.event_shape

        def f(c):
            g = jax.random.gamma(key, jnp.broadcast_to(c, shp))
            return g / jnp.sum(g, -1, keepdims=True)
        return apply_op(f, self.concentration, op_name="dirichlet_rsample")

    def log_prob(self, value):
        def f(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))
        return apply_op(f, _t(value), self.concentration,
                        op_name="dirichlet_log_prob")

    def entropy(self):
        def f(c):
            c0 = jnp.sum(c, -1)
            k = c.shape[-1]
            return (jnp.sum(gammaln(c), -1) - gammaln(c0)
                    + (c0 - k) * digamma(c0)
                    - jnp.sum((c - 1) * digamma(c), -1))
        return apply_op(f, self.concentration, op_name="dirichlet_entropy")


class Geometric(Distribution):
    """ref: geometric.py Geometric(probs): failures before first success,
    pmf (1-p)^k p, support k in {0, 1, ...}."""

    def __init__(self, probs):
        self.probs = _t(probs)
        super().__init__(self.probs._data.shape)

    @property
    def mean(self):
        return apply_op(lambda p: (1 - p) / p, self.probs,
                        op_name="geometric_mean")

    @property
    def variance(self):
        return apply_op(lambda p: (1 - p) / p ** 2, self.probs,
                        op_name="geometric_var")

    def sample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(key, shp, minval=1e-7, maxval=1.0)

        def f(p):
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return apply_op(f, self.probs, op_name="geometric_sample").detach()

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            return v * jnp.log1p(-p) + jnp.log(p)
        return apply_op(f, _t(value), self.probs,
                        op_name="geometric_log_prob")

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return apply_op(f, self.probs, op_name="geometric_entropy")


class Poisson(Distribution):
    """ref: poisson.py Poisson(rate)."""

    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate._data.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(r):
            return jax.random.poisson(key, jnp.broadcast_to(r, shp)
                                      ).astype(jnp.float32)
        return apply_op(f, self.rate, op_name="poisson_sample").detach()

    def log_prob(self, value):
        def f(v, r):
            return v * jnp.log(r) - r - gammaln(v + 1)
        return apply_op(f, _t(value), self.rate, op_name="poisson_log_prob")

    def entropy(self):
        """Series approximation (matches the reference's approach for
        moderate rates)."""
        def f(r):
            return r * (1 - jnp.log(r)) + 0.5 * jnp.log(
                2 * math.pi * jnp.e * r)
        return apply_op(f, self.rate, op_name="poisson_entropy")


class Binomial(Distribution):
    """ref: binomial.py Binomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(
            self.total_count._data.shape, self.probs._data.shape))

    @property
    def mean(self):
        return apply_op(lambda n, p: n * p, self.total_count, self.probs,
                        op_name="binomial_mean")

    @property
    def variance(self):
        return apply_op(lambda n, p: n * p * (1 - p), self.total_count,
                        self.probs, op_name="binomial_var")

    def sample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(n, p):
            return jax.random.binomial(key, jnp.broadcast_to(n, shp),
                                       jnp.broadcast_to(p, shp)
                                       ).astype(jnp.float32)
        return apply_op(f, self.total_count, self.probs,
                        op_name="binomial_sample").detach()

    def log_prob(self, value):
        def f(v, n, p):
            log_comb = (gammaln(n + 1) - gammaln(v + 1)
                        - gammaln(n - v + 1))
            return log_comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return apply_op(f, _t(value), self.total_count, self.probs,
                        op_name="binomial_log_prob")


class Multinomial(Distribution):
    """ref: multinomial.py Multinomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shape = self.probs._data.shape
        super().__init__(shape[:-1], shape[-1:])

    def sample(self, shape=()):
        key = random_mod.next_key()
        n_cat = self.probs._data.shape[-1]
        shp = _shape(shape) + self.batch_shape

        def f(p):
            logits = jnp.log(jnp.broadcast_to(p, shp + (n_cat,)))
            draws = jax.random.categorical(
                key, logits[..., None, :].repeat(self.total_count, -2))
            return jax.nn.one_hot(draws, n_cat).sum(-2)
        return apply_op(f, self.probs, op_name="multinomial_sample"
                        ).detach()

    def log_prob(self, value):
        def f(v, p):
            return (gammaln(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(p), -1))
        return apply_op(f, _t(value), self.probs,
                        op_name="multinomial_log_prob")


class StudentT(Distribution):
    """ref: student_t.py StudentT(df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df._data.shape, self.loc._data.shape,
            self.scale._data.shape))

    @property
    def mean(self):
        return apply_op(
            lambda d, l: jnp.where(d > 1, jnp.broadcast_to(
                l, self.batch_shape), jnp.nan),
            self.df, self.loc, op_name="studentt_mean")

    @property
    def variance(self):
        def f(d, s):
            v = jnp.where(d > 2, s ** 2 * d / (d - 2), jnp.inf)
            return jnp.broadcast_to(jnp.where(d > 1, v, jnp.nan),
                                    self.batch_shape)
        return apply_op(f, self.df, self.scale, op_name="studentt_var")

    def rsample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(d, l, s):
            t = jax.random.t(key, jnp.broadcast_to(d, shp), shape=shp)
            return l + s * t
        return apply_op(f, self.df, self.loc, self.scale,
                        op_name="studentt_rsample")

    def log_prob(self, value):
        def f(v, d, l, s):
            z = (v - l) / s
            return (gammaln((d + 1) / 2) - gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(s)
                    - (d + 1) / 2 * jnp.log1p(z ** 2 / d))
        return apply_op(f, _t(value), self.df, self.loc, self.scale,
                        op_name="studentt_log_prob")


class Cauchy(Distribution):
    """ref: cauchy.py Cauchy(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    def rsample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape

        def f(l, s):
            return l + s * jax.random.cauchy(key, shp)
        return apply_op(f, self.loc, self.scale, op_name="cauchy_rsample")

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z ** 2))
        return apply_op(f, _t(value), self.loc, self.scale,
                        op_name="cauchy_log_prob")

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(jnp.log(4 * math.pi * s),
                                       self.batch_shape),
            self.scale, op_name="cauchy_entropy")


class MultivariateNormal(Distribution):
    """ref: multivariate_normal.py MultivariateNormal(loc,
    covariance_matrix=...)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = _t(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                "pass exactly one of covariance_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        else:
            cov = _t(covariance_matrix)
            self.scale_tril = apply_op(jnp.linalg.cholesky, cov,
                                       op_name="mvn_chol")
        d = self.loc._data.shape[-1]
        super().__init__(self.loc._data.shape[:-1], (d,))

    @property
    def mean(self):
        return self.loc

    def rsample(self, shape=()):
        key = random_mod.next_key()
        shp = _shape(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(key, shp)

        def f(l, st):
            return l + jnp.einsum("...ij,...j->...i", st, eps)
        return apply_op(f, self.loc, self.scale_tril, op_name="mvn_rsample")

    def log_prob(self, value):
        def f(v, l, st):
            d = v.shape[-1]
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(st, diff.shape[:-1] + st.shape[-2:]),
                diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(sol ** 2, -1)
            logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(st, axis1=-2,
                                                      axis2=-1)), -1)
            return -0.5 * (maha + d * math.log(2 * math.pi) + logdet)
        return apply_op(f, _t(value), self.loc, self.scale_tril,
                        op_name="mvn_log_prob")

    def entropy(self):
        def f(st):
            d = st.shape[-1]
            logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(st, axis1=-2,
                                                      axis2=-1)), -1)
            out = 0.5 * (d * (1 + math.log(2 * math.pi)) + logdet)
            return jnp.broadcast_to(out, self.batch_shape)
        return apply_op(f, self.scale_tril, op_name="mvn_entropy")


class Independent(Distribution):
    """ref: independent.py — reinterpret batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        b = base.batch_shape
        super().__init__(b[:len(b) - self.rank],
                         b[len(b) - self.rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        axes = tuple(range(-self.rank, 0))
        return apply_op(lambda x: jnp.sum(x, axes), lp,
                        op_name="independent_log_prob")

    def entropy(self):
        e = self.base.entropy()
        axes = tuple(range(-self.rank, 0))
        return apply_op(lambda x: jnp.sum(x, axes), e,
                        op_name="independent_entropy")


# --------------------------- transforms -----------------------------------

class Transform:
    """ref: transform.py Transform ABC (forward / inverse /
    forward_log_det_jacobian)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return apply_op(lambda a: -a,
                        self.forward_log_det_jacobian(self.inverse(y)),
                        op_name="ildj")

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return apply_op(lambda v, l, s: l + s * v, _t(x), self.loc,
                        self.scale, op_name="affine_fwd")

    def inverse(self, y):
        return apply_op(lambda v, l, s: (v - l) / s, _t(y), self.loc,
                        self.scale, op_name="affine_inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v, s: jnp.broadcast_to(jnp.log(jnp.abs(s)), v.shape),
            _t(x), self.scale, op_name="affine_ldj")


class ExpTransform(Transform):
    def forward(self, x):
        return apply_op(jnp.exp, _t(x), op_name="exp_fwd")

    def inverse(self, y):
        return apply_op(jnp.log, _t(y), op_name="exp_inv")

    def forward_log_det_jacobian(self, x):
        return _t(x)  # d/dx exp(x) = exp(x); log of that is x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return apply_op(lambda v, p: jnp.power(v, p), _t(x), self.power,
                        op_name="power_fwd")

    def inverse(self, y):
        return apply_op(lambda v, p: jnp.power(v, 1.0 / p), _t(y),
                        self.power, op_name="power_inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v, p: jnp.log(jnp.abs(p * jnp.power(v, p - 1))),
            _t(x), self.power, op_name="power_ldj")


class SigmoidTransform(Transform):
    def forward(self, x):
        return apply_op(jax.nn.sigmoid, _t(x), op_name="sigmoid_fwd")

    def inverse(self, y):
        return apply_op(lambda v: jnp.log(v) - jnp.log1p(-v), _t(y),
                        op_name="sigmoid_inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v), _t(x),
            op_name="sigmoid_ldj")


class TanhTransform(Transform):
    def forward(self, x):
        return apply_op(jnp.tanh, _t(x), op_name="tanh_fwd")

    def inverse(self, y):
        return apply_op(jnp.arctanh, _t(y), op_name="tanh_inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(
            lambda v: 2.0 * (math.log(2.0) - v - jax.nn.softplus(-2.0 * v)),
            _t(x), op_name="tanh_ldj")


class AbsTransform(Transform):
    def forward(self, x):
        return apply_op(jnp.abs, _t(x), op_name="abs_fwd")

    def inverse(self, y):
        return _t(y)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else total + ldj
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """ref: transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        ildj = apply_op(lambda a: -a,
                        self.transform.forward_log_det_jacobian(x),
                        op_name="neg")
        return self.base.log_prob(x) + ildj


# ------------------------------ KL pairs -----------------------------------

@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p: Gamma, q: Gamma):
    def f(pa, pr, qa, qr):
        return ((pa - qa) * digamma(pa) - gammaln(pa) + gammaln(qa)
                + qa * (jnp.log(pr) - jnp.log(qr)) + pa * (qr - pr) / pr)
    return apply_op(f, p.concentration, p.rate, q.concentration, q.rate,
                    op_name="kl_gamma")


@register_kl(Beta, Beta)
def _kl_beta_beta(p: Beta, q: Beta):
    def f(pa, pb, qa, qb):
        pt = pa + pb
        return (betaln(qa, qb) - betaln(pa, pb)
                + (pa - qa) * digamma(pa) + (pb - qb) * digamma(pb)
                + (qa - pa + qb - pb) * digamma(pt))
    return apply_op(f, p.alpha, p.beta, q.alpha, q.beta, op_name="kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p: Dirichlet, q: Dirichlet):
    def f(pc, qc):
        p0 = jnp.sum(pc, -1)
        return (gammaln(p0) - jnp.sum(gammaln(pc), -1)
                - gammaln(jnp.sum(qc, -1)) + jnp.sum(gammaln(qc), -1)
                + jnp.sum((pc - qc) * (digamma(pc)
                                       - digamma(p0[..., None])), -1))
    return apply_op(f, p.concentration, q.concentration,
                    op_name="kl_dirichlet")


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p: Poisson, q: Poisson):
    def f(pr, qr):
        return pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr
    return apply_op(f, p.rate, q.rate, op_name="kl_poisson")


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p: Geometric, q: Geometric):
    def f(pp, qp):
        return ((1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp))
                + jnp.log(pp) - jnp.log(qp))
    return apply_op(f, p.probs, q.probs, op_name="kl_geometric")
