"""Distribution long tail: ContinuousBernoulli, ExponentialFamily,
LKJCholesky (ref: python/paddle/distribution/continuous_bernoulli.py,
exponential_family.py, lkj_cholesky.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as random_mod
from ..core.autograd import apply_op
from ..core.tensor import Tensor
from .distributions import Distribution

__all__ = ["ContinuousBernoulli", "ExponentialFamily", "LKJCholesky"]


def _t(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, jnp.float32))


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (ref:
    exponential_family.py): subclasses provide ``_natural_parameters``
    and ``_log_normalizer``; entropy comes from the Bregman-divergence
    identity H = log A(θ) - <θ, ∇A(θ)> - E[carrier]."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nat = [p._data if isinstance(p, Tensor) else jnp.asarray(p)
               for p in self._natural_parameters]

        def f(*np_):
            # grad of the SUMMED log-normalizer gives per-element
            # partials (each output depends on its own parameters), so
            # the entropy stays per-distribution over the batch shape
            grads = jax.grad(
                lambda ps: jnp.sum(self._log_normalizer(*ps)))(
                    tuple(np_))
            log_norm = self._log_normalizer(*np_)
            ent = log_norm - sum(t * g for t, g in zip(np_, grads))
            return ent - self._mean_carrier_measure
        return apply_op(f, *[Tensor(n) for n in nat],
                        op_name="ef_entropy")


class ContinuousBernoulli(Distribution):
    """Continuous Bernoulli on [0, 1] (ref: continuous_bernoulli.py;
    Loaiza-Ganem & Cunningham 2019): density
    C(p) * p^x (1-p)^(1-x), C(p) = 2 atanh(1-2p)/(1-2p) with a Taylor
    patch inside ``lims`` around p=0.5."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(self.probs._data.shape)

    def _stable(self, p):
        lo, hi = self._lims
        return jnp.where((p > lo) & (p < hi), jnp.float32(lo), p)

    def _log_C(self, p):
        # log C: Taylor around 0.5 inside lims (atanh(1-2p)/(1-2p) -> 2)
        safe = self._stable(p)
        x = 1.0 - 2.0 * safe
        exact = jnp.log(2.0 * jnp.arctanh(x) / x)
        mid = jnp.log(2.0) + jnp.log1p(
            (1.0 - 2.0 * p) ** 2 / 3.0)  # 2(1 + x^2/3 + ...)
        lo, hi = self._lims
        return jnp.where((p > lo) & (p < hi), mid, exact)

    @property
    def mean(self):
        def f(p):
            safe = self._stable(p)
            exact = safe / (2.0 * safe - 1.0) + \
                1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            mid = 0.5 + (p - 0.5) / 3.0  # Taylor at p=0.5
            lo, hi = self._lims
            return jnp.where((p > lo) & (p < hi), mid, exact)
        return apply_op(f, self.probs, op_name="cb_mean")

    @property
    def variance(self):
        def f(p):
            safe = self._stable(p)
            x = 1.0 - 2.0 * safe
            exact = safe * (safe - 1.0) / (x * x) + \
                1.0 / (2.0 * jnp.arctanh(x)) ** 2
            mid = jnp.float32(1.0 / 12.0) - (p - 0.5) ** 2 / 3.0
            lo, hi = self._lims
            return jnp.where((p > lo) & (p < hi), mid, exact)
        return apply_op(f, self.probs, op_name="cb_variance")

    def log_prob(self, value):
        def f(v, p):
            return (self._log_C(p) + v * jnp.log(p)
                    + (1.0 - v) * jnp.log1p(-p))
        return apply_op(f, _t(value), self.probs, op_name="cb_log_prob")

    def prob(self, value):
        return apply_op(lambda lp: jnp.exp(lp), self.log_prob(value),
                        op_name="cb_prob")

    def cdf(self, value):
        def f(v, p):
            safe = self._stable(p)
            num = safe ** v * (1.0 - safe) ** (1.0 - v) + safe - 1.0
            exact = num / (2.0 * safe - 1.0)
            lo, hi = self._lims
            out = jnp.where((p > lo) & (p < hi), v, exact)
            return jnp.clip(out, 0.0, 1.0)
        return apply_op(f, _t(value), self.probs, op_name="cb_cdf")

    def icdf(self, value):
        def f(u, p):
            safe = self._stable(p)
            exact = (jnp.log1p((2.0 * safe - 1.0) * u / (1.0 - safe))
                     / (jnp.log(safe) - jnp.log1p(-safe)))
            lo, hi = self._lims
            return jnp.where((p > lo) & (p < hi), u, exact)
        return apply_op(f, _t(value), self.probs, op_name="cb_icdf")

    def sample(self, shape=()):
        key = random_mod.next_key()
        shp = tuple(shape) + tuple(self.batch_shape)
        u = jax.random.uniform(key, shp, jnp.float32)
        return self.icdf(Tensor(u))

    def rsample(self, shape=()):
        return self.sample(shape)

    def entropy(self):
        def f(p):
            # mean recomputed from p INSIDE the trace: pulling the
            # cached self.mean in as a constant silently zeroes the
            # entropy's gradient w.r.t. probs
            safe = self._stable(p)
            mean = safe / (2.0 * safe - 1.0) + \
                1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            mid = 0.5 + (p - 0.5) / 3.0
            lo, hi = self._lims
            mean = jnp.where((p > lo) & (p < hi), mid, mean)
            return -(self._log_C(p) + mean * jnp.log(p)
                     + (1.0 - mean) * jnp.log1p(-p))
        return apply_op(f, self.probs, op_name="cb_entropy")


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (ref:
    lkj_cholesky.py; Lewandowski-Kurowicka-Joe 2009). ``sample`` uses
    the onion construction; ``log_prob`` evaluates the exact density of
    the lower-triangular parametrization by inverting that construction
    (y_i = |row_i|^2 ~ Beta(i/2, eta + (d-1-i)/2), direction uniform on
    the sphere, polar-coordinates Jacobian)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("LKJCholesky needs dim >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError(f"unknown sample_method {sample_method!r}")
        self.dim = int(dim)
        self.concentration = _t(concentration)
        self.sample_method = sample_method
        super().__init__(self.concentration._data.shape)

    def _beta_params(self):
        d = self.dim
        eta = self.concentration._data
        rows = jnp.arange(1, d, dtype=jnp.float32)       # i = 1..d-1
        a = rows / 2.0
        b = eta + (d - 1.0 - rows) / 2.0
        return a, b

    def sample(self, shape=()):
        d = self.dim
        key = random_mod.next_key()
        shp = tuple(shape)
        a, b = self._beta_params()
        k1, k2 = jax.random.split(key)
        y = jax.random.beta(k1, a, b, shp + (d - 1,))     # row norms^2
        normal = jax.random.normal(k2, shp + (d - 1, d - 1), jnp.float32)
        L = jnp.zeros(shp + (d, d), jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            u = normal[..., i - 1, :i]
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            r = jnp.sqrt(y[..., i - 1])
            L = L.at[..., i, :i].set(r[..., None] * u)
            L = L.at[..., i, i].set(jnp.sqrt(1.0 - y[..., i - 1]))
        return Tensor(L)

    def log_prob(self, value):
        d = self.dim
        a, b = self._beta_params()

        def f(L, a_, b_):
            total = jnp.zeros(L.shape[:-2], jnp.float32)
            for i in range(1, d):
                row = L[..., i, :i]
                y = jnp.sum(row * row, axis=-1)
                lbeta = (jax.scipy.special.gammaln(a_[i - 1])
                         + jax.scipy.special.gammaln(b_[i - 1])
                         - jax.scipy.special.gammaln(a_[i - 1]
                                                     + b_[i - 1]))
                # Beta_pdf(y) has a (a-1)*log(y) term and the polar
                # Jacobian (density over the row = Beta_pdf * 2 /
                # (A_{i-1} * r^{i-2})) contributes -(i-2)/2*log(y);
                # with a = i/2 the log(y) exponents cancel EXACTLY, so
                # only the (1-y) power and constants remain (also
                # avoids 0*inf at y=0).
                log_area = (math.log(2.0)
                            + (i / 2.0) * math.log(math.pi)
                            - jax.scipy.special.gammaln(i / 2.0))
                total = total + ((b_[i - 1] - 1.0) * jnp.log1p(-y)
                                 - lbeta + math.log(2.0) - log_area)
            return total
        return apply_op(f, _t(value), Tensor(a), Tensor(b),
                        op_name="lkj_log_prob")
