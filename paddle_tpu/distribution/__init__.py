"""paddle.distribution equivalent.

ref: python/paddle/distribution/ — Distribution ABC (distribution.py),
Normal/Uniform/Categorical/Bernoulli/Exponential/Laplace/Gumbel/
LogNormal, kl_divergence registry (kl.py). Sampling draws keys from the
framework generator (core.random), so paddle.seed governs determinism.
"""
from .distributions import (  # noqa: F401
    Bernoulli, Categorical, Distribution, Exponential, Gumbel, Laplace,
    LogNormal, Normal, Uniform, kl_divergence, register_kl,
)
from .more import (  # noqa: F401
    ContinuousBernoulli, ExponentialFamily, LKJCholesky,
)
from .extra import (  # noqa: F401
    AbsTransform, AffineTransform, Beta, Binomial, Cauchy, ChainTransform,
    Chi2, Dirichlet, ExpTransform, Gamma, Geometric, Independent,
    Multinomial, MultivariateNormal, Poisson, PowerTransform,
    SigmoidTransform, StudentT, TanhTransform, Transform,
    TransformedDistribution,
)
