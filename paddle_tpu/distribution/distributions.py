"""Distribution classes.

ref: python/paddle/distribution/distribution.py (ABC: sample/rsample/
log_prob/entropy/mean/variance), normal.py, uniform.py, categorical.py,
bernoulli.py, exponential.py, laplace.py, gumbel.py, lognormal.py, kl.py
(kl_divergence dispatch). Parameters are kept as Tensors and every
computation goes through apply_op, so gradients flow to loc/scale/rate/
logits — rsample is genuinely reparameterized (VAE/policy-gradient
training works). Sampling keys come from core.random so paddle.seed
governs determinism and jit tracing stays pure.
"""
from __future__ import annotations

import math
from typing import Callable, Dict, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.autograd import apply_op
from ..core.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Exponential", "Laplace", "Gumbel", "LogNormal", "kl_divergence",
    "register_kl",
]


def _t(x, dtype=jnp.float32) -> Tensor:
    """Keep Tensor identity (and its grad path); wrap scalars/arrays."""
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(np.asarray(x), dtype))


def _shape(s):
    if s is None:
        return ()
    return tuple(int(v) for v in s)


class Distribution:
    """ref: distribution.py Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value), op_name="exp")

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    """ref: normal.py Normal(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    @property
    def mean(self):
        return apply_op(
            lambda l: jnp.broadcast_to(l, self.batch_shape), self.loc,
            op_name="normal_mean")

    @property
    def variance(self):
        return apply_op(
            lambda s: jnp.broadcast_to(s ** 2, self.batch_shape),
            self.scale, op_name="normal_variance")

    def rsample(self, shape=()):
        key = random_mod.next_key()
        eps = jax.random.normal(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda l, s: l + s * eps, self.loc, self.scale,
                        op_name="normal_rsample")

    def log_prob(self, value):
        def f(v, l, s):
            return (-((v - l) ** 2) / (2 * s ** 2)
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        return apply_op(f, value, self.loc, self.scale,
                        op_name="normal_log_prob")

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                self.batch_shape),
            self.scale, op_name="normal_entropy")

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class LogNormal(Normal):
    """ref: lognormal.py — exp transform of Normal."""

    def rsample(self, shape=()):
        key = random_mod.next_key()
        eps = jax.random.normal(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda l, s: jnp.exp(l + s * eps), self.loc,
                        self.scale, op_name="lognormal_rsample")

    def log_prob(self, value):
        def f(v, l, s):
            logv = jnp.log(v)
            return (-((logv - l) ** 2) / (2 * s ** 2) - logv
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        return apply_op(f, value, self.loc, self.scale,
                        op_name="lognormal_log_prob")

    @property
    def mean(self):
        return apply_op(lambda l, s: jnp.exp(l + s ** 2 / 2), self.loc,
                        self.scale, op_name="lognormal_mean")

    def entropy(self):
        return apply_op(
            lambda l, s: jnp.broadcast_to(
                l + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                self.batch_shape),
            self.loc, self.scale, op_name="lognormal_entropy")


class Uniform(Distribution):
    """ref: uniform.py Uniform(low, high)."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(self.low._data.shape,
                                              self.high._data.shape))

    def rsample(self, shape=()):
        key = random_mod.next_key()
        u = jax.random.uniform(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda lo, hi: lo + (hi - lo) * u, self.low,
                        self.high, op_name="uniform_rsample")

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = jnp.logical_and(v >= lo, v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op(f, value, self.low, self.high,
                        op_name="uniform_log_prob")

    def entropy(self):
        return apply_op(
            lambda lo, hi: jnp.broadcast_to(jnp.log(hi - lo),
                                            self.batch_shape),
            self.low, self.high, op_name="uniform_entropy")


class Categorical(Distribution):
    """ref: categorical.py Categorical(logits)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        super().__init__(self.logits._data.shape[:-1])

    @property
    def probs(self):
        return apply_op(lambda lg: jax.nn.softmax(lg, -1), self.logits,
                        op_name="categorical_probs")

    def sample(self, shape=()):
        key = random_mod.next_key()
        return Tensor(jax.random.categorical(
            key, self.logits._data,
            shape=_shape(shape) + self.batch_shape))

    def log_prob(self, value):
        def f(v, lg):
            logp = jax.nn.log_softmax(lg, -1)
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), -1)[..., 0]
        return apply_op(f, value, self.logits,
                        op_name="categorical_log_prob")

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, -1)
            return -(jnp.exp(logp) * logp).sum(-1)
        return apply_op(f, self.logits, op_name="categorical_entropy")


class Bernoulli(Distribution):
    """ref: bernoulli.py Bernoulli(probs)."""

    def __init__(self, probs, name=None):
        self.probs_t = _t(probs)
        super().__init__(self.probs_t._data.shape)

    def sample(self, shape=()):
        key = random_mod.next_key()
        return Tensor(jax.random.bernoulli(
            key, self.probs_t._data,
            _shape(shape) + self.batch_shape).astype(jnp.float32))

    def log_prob(self, value):
        def f(v, p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op(f, value, self.probs_t,
                        op_name="bernoulli_log_prob")

    def entropy(self):
        def f(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op(f, self.probs_t, op_name="bernoulli_entropy")

    @property
    def mean(self):
        return self.probs_t

    @property
    def variance(self):
        return apply_op(lambda p: p * (1 - p), self.probs_t,
                        op_name="bernoulli_variance")


class Exponential(Distribution):
    """ref: exponential.py Exponential(rate)."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._data.shape)

    def rsample(self, shape=()):
        key = random_mod.next_key()
        e = jax.random.exponential(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda r: e / r, self.rate,
                        op_name="exponential_rsample")

    def log_prob(self, value):
        return apply_op(lambda v, r: jnp.log(r) - r * v, value, self.rate,
                        op_name="exponential_log_prob")

    def entropy(self):
        return apply_op(lambda r: 1.0 - jnp.log(r), self.rate,
                        op_name="exponential_entropy")

    @property
    def mean(self):
        return apply_op(lambda r: 1.0 / r, self.rate,
                        op_name="exponential_mean")


class Laplace(Distribution):
    """ref: laplace.py Laplace(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    def rsample(self, shape=()):
        key = random_mod.next_key()
        e = jax.random.laplace(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda l, s: l + s * e, self.loc, self.scale,
                        op_name="laplace_rsample")

    def log_prob(self, value):
        return apply_op(
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            value, self.loc, self.scale, op_name="laplace_log_prob")

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(1 + jnp.log(2 * s),
                                       self.batch_shape),
            self.scale, op_name="laplace_entropy")


class Gumbel(Distribution):
    """ref: gumbel.py Gumbel(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    def rsample(self, shape=()):
        key = random_mod.next_key()
        g = jax.random.gumbel(key, _shape(shape) + self.batch_shape)
        return apply_op(lambda l, s: l + s * g, self.loc, self.scale,
                        op_name="gumbel_rsample")

    def log_prob(self, value):
        def f(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return apply_op(f, value, self.loc, self.scale,
                        op_name="gumbel_log_prob")

    def entropy(self):
        # Euler-Mascheroni
        return apply_op(
            lambda s: jnp.broadcast_to(jnp.log(s) + 1.5772157,
                                       self.batch_shape),
            self.scale, op_name="gumbel_entropy")


# -- KL registry (ref: distribution/kl.py register_kl/kl_divergence).
# Dispatch is by EXACT class pair: subclass fallbacks silently produce
# wrong values (e.g. LogNormal subclasses Normal but KL(Normal, LogNormal)
# is not the normals' KL), so unknown pairs raise instead.
_KL_REGISTRY: Dict[Tuple[Type, Type], Callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p: Normal, q: Normal):
    def f(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return apply_op(f, p.loc, p.scale, q.loc, q.scale,
                    op_name="kl_normal_normal")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p: LogNormal, q: LogNormal):
    # the exp transform cancels: KL equals that of the underlying normals
    return _kl_normal_normal(p, q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p: Uniform, q: Uniform):
    def f(plo, phi, qlo, qhi):
        res = jnp.log((qhi - qlo) / (phi - plo))
        oob = jnp.logical_or(plo < qlo, phi > qhi)
        return jnp.where(oob, jnp.inf, res)
    return apply_op(f, p.low, p.high, q.low, q.high,
                    op_name="kl_uniform_uniform")


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p: Categorical, q: Categorical):
    def f(a, b):
        lp = jax.nn.log_softmax(a, -1)
        lq = jax.nn.log_softmax(b, -1)
        return (jnp.exp(lp) * (lp - lq)).sum(-1)
    return apply_op(f, p.logits, q.logits, op_name="kl_cat_cat")


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p: Exponential, q: Exponential):
    def f(pr, qr):
        ratio = qr / pr
        return jnp.log(1 / ratio) + ratio - 1
    return apply_op(f, p.rate, q.rate, op_name="kl_exp_exp")
