"""Fleet serving fabric: N replica processes behind one router.

Everything through the self-healing serving plane is one process —
one ``GenerationServer``, one engine, one KV pool. This module is the
millions-of-users topology (ROADMAP item 1): replica processes each
running a supervised server, and a front-end :class:`FleetRouter`
that places continuous-batching traffic across them and survives any
of them dying mid-decode.

Wire protocol — deliberately stdlib-only: a 4-byte big-endian length
prefix followed by a UTF-8 JSON object, over a local TCP socket. Ops:
``submit`` / ``poll`` (stream delta) / ``cancel`` / ``health`` /
``stats`` / ``prepare_swap`` / ``retain_params`` / ``swap_weights`` /
``generate`` / ``shutdown``. :class:`ReplicaServer` serves a
``GenerationServer`` (real or a test fake — the framing is identical)
and :func:`replica_main` is the child-process entrypoint that boots
one from a model + warm bundle and prints a single JSON boot line
(port, pid, executable-cache counters) for the parent to read.

Router robustness contract (the PR 15 invariant, now across a process
boundary):

* **Placement** is KV-pressure-aware: each heartbeat ships the gauges
  the replica already exports (``blocks_free``, backlog, adaptive-
  admission pressure level) and ``policy="pressure"`` routes around
  starved replicas — measurably better than round-robin under skew
  (test-pinned). When EVERY live replica reports pressure level 3 the
  fleet sheds with a ``retry_after`` hint instead of queueing onto a
  brownout.
* **Failover**: a heartbeat stall or data-plane connection death
  FENCES the replica (its router-side epoch bumps; poll results from
  the zombie epoch are discarded), and its in-flight requests are
  re-dispatched to healthy replicas seeded with their already-
  streamed committed tokens — greedy streams stay bit-equal to the
  uninterrupted oracle because decoding is causal in the whole
  (prompt + committed) sequence. A request active at
  ``quarantine_after`` consecutive replica deaths is quarantined as
  poison fleet-wide rather than allowed to crash-loop the fleet.
* **Resurrection**: the dead replica is relaunched via its ``spawn``
  callable (the same executable cache + warm bundle ⇒ 0 fresh XLA
  compiles, bench-pinned) under a bounded full-jittered exponential
  backoff; ``max_restarts`` failures degrade the fleet to the
  survivors — the router itself never crashes.

``rollout()`` (canary probe, divergence rollback) runs unmodified
over :class:`ReplicaClient` handles: ``prepare_swap`` serializes the
state dict over the wire, the replica scans it for non-finite values
server-side and retains prepared trees under opaque tokens, so the
supervisor's ``_count_nonfinite`` sees a :class:`RemotePrepared` with
the count already attached. ``inference.serve(fleet=N)`` wires the
whole fabric behind the existing HTTP front end.

Chaos hooks: every data connection threads through
``fault_injection.FlakyTransport`` (site ``fleet.rpc``) and the
poller calls ``fault_injection.kill_pid("fleet.apply.r<idx>", pid)``
after each token application — tests SIGKILL a real replica at an
exact stream position instead of sleeping and hoping.
"""
from __future__ import annotations

import base64
import io
import itertools
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .core.flags import flag_value
from .observability import flight as _flight
from .observability import metrics as _om
from .utils import backoff as _backoff
from .utils import fault_injection as _fi

__all__ = ["FleetRouter", "ReplicaServer", "ReplicaClient",
           "ReplicaHandle", "RemotePrepared", "FleetSaturated",
           "health_snapshot", "replica_main", "launch_replica",
           "spawn_fleet"]

_F = _om.scope("fleet")
_M_dispatched = _F.counter("dispatched_total",
                           "Requests placed on a replica by the router")
_M_redispatched = _F.counter(
    "redispatched_total",
    "Failovers: in-flight requests re-dispatched after a replica death")
_M_quarantined = _F.counter(
    "quarantined_total",
    "Poison requests failed fleet-wide after repeated replica deaths")
_M_shed = _F.counter("shed_total",
                     "Submissions shed because every live replica was "
                     "at pressure level 3")
_M_stale = _F.counter("stale_drops_total",
                      "Zombie-epoch replica responses discarded by the "
                      "router's fence")
_M_deaths = _F.counter("replica_deaths_total",
                       "Replica fencings (heartbeat stall or connection "
                       "death)")
_M_resurrected = _F.counter("resurrections_total",
                            "Dead replicas successfully relaunched")
_M_degraded = _F.counter("degraded_total",
                         "Replicas abandoned after max_restarts failed "
                         "relaunches")
_M_healthy = _F.gauge("replicas_healthy",
                      "Live replicas the router will place traffic on")

_FLEET_SEQ = itertools.count(1)
_TOKEN_SEQ = itertools.count(1)


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

class FrameConn:
    """One length-prefixed-JSON connection: ``send(obj)``/``recv()``
    move whole frames; framing errors surface as ConnectionError so
    every caller handles a half-dead socket the same way."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._rlock = threading.Lock()
        self._wlock = threading.Lock()

    def send(self, obj) -> None:
        blob = json.dumps(obj, default=str).encode()
        with self._wlock:
            self._sock.sendall(struct.pack(">I", len(blob)) + blob)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed mid-frame")
            buf += chunk
        return buf

    def recv(self):
        with self._rlock:
            n = struct.unpack(">I", self._read_exact(4))[0]
            if n > (1 << 30):
                raise ConnectionError(f"oversized frame ({n} bytes)")
            return json.loads(self._read_exact(n).decode())

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _connect(host: str, port: int, timeout: float = 5.0,
             site: Optional[str] = None):
    s = socket.create_connection((host, port), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn = FrameConn(s)
    # every fleet connection threads through the chaos wrapper: one
    # dict lookup per frame when unarmed, deterministic drop/delay/
    # duplicate when a test arms the site
    return _fi.FlakyTransport(conn, site or "fleet.rpc")


# ---------------------------------------------------------------------------
# readiness — ONE source of truth for the /healthz endpoint, the
# heartbeat RPC, and an operator's load-balancer probe
# ---------------------------------------------------------------------------

def health_snapshot(server) -> dict:
    """Readiness + placement evidence for one ``GenerationServer``
    (duck-typed; the jax-free test fakes qualify). ``ok`` means "will
    productively take traffic": decode loop alive, supervisor not
    given up, not draining, admission below hard shed."""
    thread = getattr(server, "_thread", None)
    loop_alive = bool(thread is not None and thread.is_alive()
                      and not getattr(server, "_crashed", False))
    sup = getattr(server, "_supervisor", None)
    gave_up = bool(getattr(sup, "gave_up", False))
    level = int(getattr(server.policy, "level", 0))
    paged = bool(getattr(server, "_paged", False))
    if paged:
        kv = server.engine._kv
        blocks_free, blocks_total = int(kv.available_blocks()), \
            int(kv.num_blocks)
    else:
        blocks_free = blocks_total = -1  # dense engine: no pool gauge
    backlog = int(server._q.qsize() + len(server._waiting))
    draining = bool(server._stopping.is_set())
    ok = loop_alive and not gave_up and not draining and level < 3
    return {"ok": ok, "loop_alive": loop_alive, "gave_up": gave_up,
            "level": level, "blocks_free": blocks_free,
            "blocks_total": blocks_total, "backlog": backlog,
            "in_flight": len(server._slots),
            "draining": draining, "pid": os.getpid()}


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------

def _encode_array(a) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.asarray(a), allow_pickle=False)
    return {"npy": base64.b64encode(buf.getvalue()).decode("ascii")}


def _decode_array(d):
    return np.load(io.BytesIO(base64.b64decode(d["npy"])),
                   allow_pickle=False)


def _err_payload(e: BaseException) -> dict:
    return {"type": type(e).__name__, "msg": str(e)}


def _rebuild_error(d: Optional[dict]) -> Optional[BaseException]:
    if not d:
        return None
    kind = {"TimeoutError": TimeoutError,
            "ValueError": ValueError}.get(d.get("type"), RuntimeError)
    return kind(f"[replica {d.get('type')}] {d.get('msg')}")


class ReplicaServer:
    """Serve one ``GenerationServer`` over the fleet RPC. Used by
    :func:`replica_main` inside real child processes AND in-thread
    over jax-free fakes in tier-1 tests — the framing, request table
    and op handlers are byte-identical in both.

    ``kill()`` abruptly closes the listener and every live connection
    without draining anything — the in-process simulation of a
    SIGKILL, leaving the wrapped server running as a zombie the
    router must fence."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self.server = server
        self._reqs: Dict[str, dict] = {}   # rid -> live request dict
        self._prepared: Dict[str, object] = {}  # token -> device tree
        self._reqs_order: List[str] = []   # FIFO bound on the table
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"fleet-replica-{self.port}")
        self._accept_thread.start()

    # -- socket plumbing ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(sock)
            threading.Thread(target=self._serve_conn,
                             args=(FrameConn(sock),), daemon=True,
                             name=f"fleet-conn-{self.port}").start()

    def _serve_conn(self, conn: FrameConn) -> None:
        while not self._stop.is_set():
            try:
                msg = conn.recv()
            except (ConnectionError, OSError, ValueError):
                return
            try:
                reply = self._handle(msg)
            except Exception as e:  # noqa: BLE001 — surfaced per op
                reply = {"ok": False, "error": _err_payload(e)}
            try:
                conn.send(reply)
            except (ConnectionError, OSError):
                return
            if msg.get("op") == "shutdown":
                return

    def kill(self) -> None:
        """Simulated process death: every socket dies NOW, nothing
        drains, the wrapped server becomes an unreachable zombie."""
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful stop: drain the wrapped server, then drop sockets."""
        try:
            self.server.shutdown(drain=drain, timeout=timeout)
        finally:
            self.kill()

    # -- ops ----------------------------------------------------------------
    def _remember(self, req: dict) -> None:
        with self._lock:
            rid = req["trace_id"]
            self._reqs[rid] = req
            self._reqs_order.append(rid)
            # bound the table: evict oldest FINISHED entries only (a
            # live stream must stay pollable); duplicates of recent
            # polls still resolve
            while len(self._reqs_order) > 4096:
                old = self._reqs_order[0]
                got = self._reqs.get(old)
                if got is not None and not got["done"].is_set():
                    break
                self._reqs_order.pop(0)
                self._reqs.pop(old, None)

    def _handle(self, msg: dict) -> dict:
        op = msg.get("op")
        srv = self.server
        if op == "submit":
            try:
                req = srv.submit(
                    np.asarray(msg["prompt"], np.int32),
                    int(msg["max_new"]),
                    deadline=msg.get("deadline"))
            except RuntimeError as e:
                reason = "shed" if "admission" in str(e) else \
                    "shutting_down"
                return {"ok": False, "reason": reason,
                        "error": _err_payload(e)}
            self._remember(req)
            return {"ok": True, "rid": req["trace_id"]}
        if op == "poll":
            with self._lock:
                req = self._reqs.get(msg["rid"])
            if req is None:
                return {"ok": False, "reason": "unknown_rid"}
            since = int(msg.get("since", 0))
            err = req["error"] if req["done"].is_set() else None
            return {"ok": True,
                    "tokens": [int(t) for t in req["out"][since:]],
                    "done": req["done"].is_set(),
                    "error": _err_payload(err) if err else None}
        if op == "cancel":
            with self._lock:
                req = self._reqs.get(msg["rid"])
            if req is None:
                return {"ok": False, "reason": "unknown_rid"}
            if req["done"].is_set():
                return {"ok": True, "already_done": True}
            # best-effort: a queued request dies here (admission drops
            # done-set requests); an ACTIVE one finishes its stream —
            # a decode step cannot be abandoned without corrupting the
            # slot tables
            active = any(r is req for r in srv._slots.values()) \
                or any(r is req for r in srv._prefilling.values())
            if active:
                return {"ok": False, "reason": "active"}
            srv._fail(req, RuntimeError("cancelled by the fleet router"))
            return {"ok": True}
        if op == "health":
            return {"ok": True, "health": health_snapshot(srv)}
        if op == "stats":
            return {"ok": True, "stats": srv.stats()}
        if op == "cache_stats":
            # the 0-fresh-compile evidence: after a warm boot has
            # served traffic, misses must still be 0
            from .jit import warmup as _warmup
            return {"ok": True, "cache": _warmup.cache_stats()}
        if op == "generate":
            toks = srv.generate(
                np.asarray(msg["prompt"], np.int32),
                int(msg["max_new"]),
                timeout=float(msg.get("timeout", 300.0)))
            return {"ok": True, "tokens": [int(t) for t in toks]}
        if op == "prepare_swap":
            sd = {k: _decode_array(v) for k, v in msg["state"].items()}
            prepared = srv.engine.prepare_swap(sd)
            from .serving_supervisor import _count_nonfinite
            bad = _count_nonfinite(prepared)
            token = f"prep-{next(_TOKEN_SEQ)}"
            with self._lock:
                self._prepared[token] = prepared
            return {"ok": True, "token": token, "nonfinite": int(bad)}
        if op == "retain_params":
            token = f"prep-{next(_TOKEN_SEQ)}"
            with self._lock:
                self._prepared[token] = srv.engine.params
            return {"ok": True, "token": token}
        if op == "swap_weights":
            with self._lock:
                prepared = self._prepared.get(msg["prepared"])
            if prepared is None:
                return {"ok": False, "reason": "unknown_token"}
            res = srv.swap_weights(prepared=prepared)
            return {"ok": True, "result": res}
        if op == "shutdown":
            threading.Thread(
                target=self.close,
                kwargs={"drain": bool(msg.get("drain", True))},
                daemon=True).start()
            return {"ok": True}
        return {"ok": False, "reason": f"unknown op {op!r}"}


def replica_main(config: dict) -> None:
    """Child-process entrypoint: boot a supervised ``GenerationServer``
    from ``config`` and serve the fleet RPC until shutdown.

    config keys: ``model`` ({"kind": "tiny_llama", "config": {...},
    "seed": n} builds a seeded toy causal LM — deterministic identical
    weights fleet-wide without a checkpoint; {"kind":
    "inference_model", "path": p} loads a saved artifact), engine
    geometry (``max_slots``/``max_seq``/``block_size``/
    ``prefill_chunk``/``int8``/``eos_id``), ``warm_bundle`` (pre-warm
    against the shared executable cache BEFORE the first admit),
    ``supervised`` (attach the PR 15 supervisor), ``host``/``port``
    (0 = ephemeral), ``metrics_port`` (optional /metrics + /healthz).

    Prints exactly ONE JSON boot line to stdout — ``{"ok": true,
    "port": p, "pid": n, "cache": {hits, misses, writes}}`` — the
    parent's readiness signal AND the 0-fresh-compile evidence
    (``cache.misses == 0`` on a warm boot)."""
    import paddle_tpu as paddle
    from .jit import warmup as _warmup
    from .serving import GenerationServer, PagedLlamaDecodeEngine

    _warmup.ensure_executable_cache()
    model_spec = config.get("model") or {}
    kind = model_spec.get("kind", "tiny_llama")
    if kind == "tiny_llama":
        from .models import LlamaConfig, LlamaForCausalLM
        paddle.seed(int(model_spec.get("seed", 0)))
        model = LlamaForCausalLM(
            LlamaConfig.tiny(**model_spec.get("config", {})))
    elif kind == "inference_model":
        from .inference import load_inference_model
        model = load_inference_model(model_spec["path"])
    else:
        raise ValueError(f"unknown model kind {kind!r}")
    engine = PagedLlamaDecodeEngine(
        model,
        max_slots=int(config.get("max_slots", 2)),
        max_seq=int(config.get("max_seq", 128)),
        block_size=int(config.get("block_size",
                                  flag_value("serving_block_size"))),
        prefill_chunk=int(config.get(
            "prefill_chunk", flag_value("serving_prefill_chunk"))),
        int8=bool(config.get("int8", False)),
        eos_id=config.get("eos_id"))
    prewarm = None
    bundle = config.get("warm_bundle") or None
    if bundle:
        prewarm = _warmup.prewarm(bundle, engine=engine)
    prime = config.get("prime")
    if prime:
        # compile the serving programs BEFORE taking traffic (and
        # before an export_bundle snapshot): one short generation
        # through the engine exercises prefill + decode buckets
        engine.generate(np.asarray(prime, np.int32),
                        max_new_tokens=int(config.get("prime_tokens",
                                                      4)))
        engine.reset_state()
    export = config.get("export_bundle")
    if export:
        _warmup.export_bundle(export)
    server = GenerationServer(engine)
    if config.get("supervised", True):
        from .serving_supervisor import supervise
        server._supervisor = supervise(server)
    if config.get("metrics_port") is not None:
        server.metrics_endpoint(port=int(config["metrics_port"]))
    rs = ReplicaServer(server, host=config.get("host", "127.0.0.1"),
                       port=int(config.get("port", 0)))
    boot = {"ok": True, "port": rs.port, "pid": os.getpid(),
            "cache": _warmup.cache_stats()}
    if prewarm is not None:
        boot["prewarm"] = prewarm
    print(json.dumps(boot), flush=True)
    # serve until the RPC shutdown op (close() sets _stop) or SIGKILL
    while not rs._stop.is_set():
        time.sleep(0.2)


def launch_replica(config: dict, env: Optional[dict] = None,
                   timeout: float = 300.0):
    """Spawn one replica subprocess (``python -m
    paddle_tpu.serving_fleet``, config via env) and block for its boot
    line. Returns ``(proc, port, boot)``."""
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update(env)
    child_env["PADDLE_TPU_REPLICA_CONFIG"] = json.dumps(config)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving_fleet"],
        env=child_env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica died before boot (rc={proc.returncode})")
    try:
        boot = json.loads(line.strip())
    except (json.JSONDecodeError, ValueError) as e:
        proc.kill()
        raise RuntimeError(f"bad replica boot line {line!r}") from e
    return proc, int(boot["port"]), boot


# ---------------------------------------------------------------------------
# router side
# ---------------------------------------------------------------------------

class FleetSaturated(RuntimeError):
    """Every live replica is at pressure level 3 (or dead): the fleet
    sheds instead of queueing onto a brownout. ``retry_after`` is the
    client hint in seconds."""

    def __init__(self, msg: str, retry_after: float):
        super().__init__(msg)
        self.retry_after = float(retry_after)


class ReplicaHandle:
    """Router-side view of one replica: address, data connection,
    heartbeat state, and the FENCING EPOCH — every dispatch stamps
    ``(idx, epoch)`` on the request, and responses only apply while
    the stamp still matches, so a zombie replica's late answers are
    discarded instead of corrupting a failed-over stream."""

    def __init__(self, idx: int, host: str, port: int,
                 pid: Optional[int] = None, proc=None,
                 spawn: Optional[Callable[[int], "ReplicaHandle"]]
                 = None, kill_cb: Optional[Callable[[], None]] = None):
        self.idx = int(idx)
        self.host, self.port = host, int(port)
        self.pid = pid
        self.proc = proc          # subprocess.Popen, when we own it
        self.spawn = spawn        # resurrection factory
        self.kill_cb = kill_cb    # in-proc kill (tests)
        self.epoch = 0
        self.alive = True
        self.degraded = False     # max_restarts exhausted
        self.health: Optional[dict] = None
        self.misses = 0
        self.restarts = 0
        self.dispatched = 0
        self._conn = None
        self._io_lock = threading.Lock()

    def conn(self):
        if self._conn is None:
            self._conn = _connect(self.host, self.port,
                                  site=f"fleet.rpc.r{self.idx}")
            self._conn.settimeout(10.0)
        return self._conn

    def call(self, msg: dict) -> dict:
        """One request/response over the shared data connection."""
        with self._io_lock:
            conn = self.conn()
            try:
                conn.send(msg)
                return conn.recv()
            except (ConnectionError, OSError, socket.timeout):
                self.drop_conn()
                raise

    def drop_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def probe_health(self, timeout: float) -> dict:
        """Heartbeat on a DEDICATED short-timeout connection — a data
        socket wedged behind a long op must not read as a dead
        replica, and a dead replica must not wedge the monitor."""
        conn = _connect(self.host, self.port, timeout=timeout,
                        site=f"fleet.hb.r{self.idx}")
        try:
            conn.settimeout(timeout)
            conn.send({"op": "health"})
            reply = conn.recv()
        finally:
            conn.close()
        if not reply.get("ok"):
            raise ConnectionError(f"health op rejected: {reply}")
        return reply["health"]


class FleetRouter:
    """Place continuous-batching traffic across N replicas; survive
    any of them dying. See the module docstring for the contract.

    ``replicas``: list of :class:`ReplicaHandle`. ``policy``:
    ``"pressure"`` (default — KV-pressure-aware placement from
    heartbeat gauges) or ``"rr"`` (round-robin; kept as the A/B
    baseline the placement test pins against)."""

    def __init__(self, replicas: List[ReplicaHandle], *,
                 policy: str = "pressure",
                 heartbeat_seconds: Optional[float] = None,
                 heartbeat_misses: Optional[int] = None,
                 quarantine_after: int = 2,
                 restart_backoff: Optional[float] = None,
                 restart_backoff_cap: float = 2.0,
                 max_restarts: Optional[int] = None,
                 retry_after: Optional[float] = None,
                 poll_interval: float = 0.005):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.policy = str(policy)
        self.heartbeat_seconds = float(
            flag_value("serving_fleet_heartbeat_seconds")
            if heartbeat_seconds is None else heartbeat_seconds)
        self.heartbeat_misses = int(
            flag_value("serving_fleet_heartbeat_misses")
            if heartbeat_misses is None else heartbeat_misses)
        self.quarantine_after = max(int(quarantine_after), 1)
        self.restart_backoff = float(
            flag_value("serving_fleet_restart_backoff")
            if restart_backoff is None else restart_backoff)
        self.restart_backoff_cap = float(restart_backoff_cap)
        self.max_restarts = int(
            flag_value("serving_fleet_max_restarts")
            if max_restarts is None else max_restarts)
        self.retry_after = float(
            flag_value("serving_fleet_retry_after")
            if retry_after is None else retry_after)
        self.poll_interval = float(poll_interval)
        self._lock = threading.Lock()
        self._inflight: Dict[str, dict] = {}
        self._parked: List[dict] = []   # awaiting a live replica
        self._rr_next = 0
        self._stop = threading.Event()
        self.shed = 0
        self.failovers = 0
        self.quarantined = 0
        self.stale_drops = 0
        self.finished = 0
        self.failed = 0
        self._pollers = [
            threading.Thread(target=self._poll_loop, args=(h,),
                             daemon=True, name=f"fleet-poll-{h.idx}")
            for h in self.replicas]
        for t in self._pollers:
            t.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="fleet-monitor")
        self._monitor_thread.start()
        _M_healthy.set(len(self.replicas))
        _flight.record("fleet", "router_up",
                       replicas=len(self.replicas), policy=self.policy)

    # -- submission ---------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               deadline: Optional[float] = None) -> dict:
        """Fleet submit: returns a request dict with the same surface
        as ``GenerationServer.submit`` (``out``/``done``/``error``/
        ``trace_id``) plus fleet bookkeeping. Raises
        :class:`FleetSaturated` (with ``retry_after``) when every live
        replica is at pressure level 3."""
        prompt = [int(t) for t in
                  np.asarray(prompt_ids, np.int32).reshape(-1)]
        req = {"prompt": prompt, "max_new": int(max_new_tokens),
               "out": [], "done": threading.Event(), "error": None,
               "trace_id": f"fleet-{os.getpid()}-{next(_FLEET_SEQ)}",
               "t0": time.monotonic(), "deadline": deadline,
               "strikes": 0, "owner": None, "rid": None, "base": 0,
               "terminal": False}
        _flight.record("fleet", "submit", trace_id=req["trace_id"],
                       max_new=req["max_new"])
        self._dispatch(req, exclude=())
        if isinstance(req["error"], FleetSaturated):
            raise req["error"]  # surfaced like GenerationServer's shed
        return req

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 timeout: float = 300.0) -> List[int]:
        req = self.submit(prompt_ids, max_new_tokens)
        if not req["done"].wait(timeout):
            raise TimeoutError("fleet generation timed out")
        if req["error"] is not None:
            raise req["error"]
        return list(req["out"])

    # -- placement ----------------------------------------------------------
    def _live(self) -> List[ReplicaHandle]:
        return [h for h in self.replicas if h.alive and not h.degraded]

    def _pick(self, exclude: Tuple[int, ...]) -> Optional[ReplicaHandle]:
        """Choose the placement target, or None when nothing can take
        the request (⇒ shed/park)."""
        live = [h for h in self._live() if h.idx not in exclude]
        if not live:
            return None
        candidates = [h for h in live
                      if (h.health or {}).get("level", 0) < 3]
        if not candidates:
            return None  # everyone at hard shed: fleet-level shed
        if self.policy == "rr":
            ordered = sorted(candidates, key=lambda h: h.idx)
            pick = ordered[self._rr_next % len(ordered)]
            self._rr_next += 1
            return pick
        return min(candidates, key=self._pressure_key)

    def _pressure_key(self, h: ReplicaHandle):
        """Sort key: lowest admission pressure level first, then the
        most free KV blocks (fractional — pools may differ), then the
        shortest backlog, then least recently loaded. A replica that
        has not heartbeat yet sorts as unknown-but-willing (mid)."""
        snap = h.health or {}
        level = int(snap.get("level", 0))
        total = snap.get("blocks_total", -1)
        free = snap.get("blocks_free", -1)
        free_frac = (free / total) if total and total > 0 else 0.5
        backlog = int(snap.get("backlog", 0)) \
            + int(snap.get("in_flight", 0))
        return (level, -free_frac, backlog, h.dispatched)

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, req: dict, exclude: Tuple[int, ...]) -> None:
        """Place ``req`` (fresh or failed-over) on a replica. The wire
        prompt is prompt + committed tokens and the wire budget the
        REMAINING tokens — decoding is causal in the whole sequence,
        so a re-dispatched greedy stream continues bit-equal."""
        tried = list(exclude)
        while True:
            with self._lock:
                h = self._pick(tuple(tried))
            if h is None:
                self._no_replica(req, tried)
                return
            wire_prompt = req["prompt"] + [int(t) for t in req["out"]]
            wire_budget = req["max_new"] - len(req["out"])
            if wire_budget <= 0:   # failover raced completion
                self._finish(req)
                return
            try:
                reply = h.call({"op": "submit", "prompt": wire_prompt,
                                "max_new": wire_budget,
                                "deadline": req["deadline"]})
            except (ConnectionError, OSError, socket.timeout):
                self._replica_down(h, reason="dispatch_conn")
                tried.append(h.idx)
                continue
            if not reply.get("ok"):
                if reply.get("reason") == "shed":
                    # per-replica admission disagreed with our stale
                    # gauge: respect it and try the next-best replica
                    tried.append(h.idx)
                    continue
                self._fail(req, _rebuild_error(reply.get("error"))
                           or RuntimeError(f"replica rejected: {reply}"))
                return
            with self._lock:
                req["owner"] = (h.idx, h.epoch)
                req["rid"] = reply["rid"]
                # the replica's stream counts from ITS admission —
                # polls must rebase by what was already committed at
                # dispatch or a failed-over stream would skip/duplicate
                req["base"] = len(req["out"])
                self._inflight[req["trace_id"]] = req
                h.dispatched += 1
            _M_dispatched.inc()
            _flight.record("fleet", "dispatch",
                           trace_id=req["trace_id"], replica=h.idx,
                           epoch=h.epoch,
                           committed=len(req["out"]))
            return

    def _no_replica(self, req: dict, tried: List[int]) -> None:
        live = self._live()
        if live:
            # live replicas exist but all are at hard shed (or just
            # shed us): fleet-level shed with the retry hint
            with self._lock:
                self.shed += 1
            _M_shed.inc()
            _flight.record("fleet", "fleet_shed",
                           trace_id=req["trace_id"],
                           retry_after=self.retry_after,
                           live=len(live))
            self._fail(req, FleetSaturated(
                f"every live replica is at admission pressure level 3 "
                f"— retry after {self.retry_after}s",
                self.retry_after), count_shed=True)
            return
        if any(not h.degraded for h in self.replicas):
            # replicas are dead but resurrection is still running:
            # park; the monitor re-dispatches when one rejoins
            with self._lock:
                req["owner"] = None
                self._parked.append(req)
            _flight.record("fleet", "parked", trace_id=req["trace_id"])
            return
        self._fail(req, RuntimeError(
            "fleet degraded: every replica exhausted max_restarts"))

    # -- completion ---------------------------------------------------------
    def _finish(self, req: dict) -> None:
        with self._lock:
            if req["terminal"]:
                return
            req["terminal"] = True
            self._inflight.pop(req["trace_id"], None)
            self.finished += 1
        _flight.record("fleet", "finished", trace_id=req["trace_id"],
                       tokens=len(req["out"]))
        req["done"].set()

    def _fail(self, req: dict, error: BaseException,
              count_shed: bool = False) -> None:
        with self._lock:
            if req["terminal"]:
                return
            req["terminal"] = True
            self._inflight.pop(req["trace_id"], None)
            if not count_shed:
                self.failed += 1
        req["error"] = error
        _flight.record("fleet",
                       "shed" if count_shed else "failed",
                       trace_id=req["trace_id"],
                       error=type(error).__name__,
                       tokens=len(req["out"]))
        req["done"].set()

    # -- polling ------------------------------------------------------------
    def _owned_by(self, h: ReplicaHandle) -> List[dict]:
        with self._lock:
            return [r for r in self._inflight.values()
                    if r["owner"] == (h.idx, h.epoch)]

    def _poll_loop(self, h: ReplicaHandle) -> None:
        while not self._stop.is_set():
            if not h.alive or h.degraded:
                time.sleep(self.poll_interval * 4)
                continue
            work = self._owned_by(h)
            if not work:
                time.sleep(self.poll_interval)
                continue
            for req in work:
                owner = req["owner"]
                since = max(len(req["out"]) - req.get("base", 0), 0)
                try:
                    reply = h.call({"op": "poll", "rid": req["rid"],
                                    "since": since})
                except (ConnectionError, OSError, socket.timeout):
                    self._replica_down(h, reason="poll_conn")
                    break
                if not reply.get("ok"):
                    continue  # unknown rid: re-dispatch owns it now
                self._apply(req, owner, h,
                            reply.get("tokens") or [],
                            bool(reply.get("done")),
                            reply.get("error"))
            time.sleep(self.poll_interval)

    def _apply(self, req: dict, owner, h: ReplicaHandle,
               tokens: List[int], done: bool, error) -> None:
        """Fold one poll response into the fleet stream — IFF the
        dispatch stamp still matches the replica's current epoch.
        A response from a fenced (zombie) epoch is dropped: the
        request has been re-dispatched and folding the zombie's view
        would duplicate or fork the committed stream."""
        with self._lock:
            if req["terminal"]:
                return
            if owner != (h.idx, h.epoch) or req["owner"] != owner:
                self.stale_drops += 1
                _M_stale.inc()
                _flight.record("fleet", "stale_drop",
                               trace_id=req["trace_id"],
                               replica=h.idx,
                               stamped=list(owner) if owner else None,
                               current=h.epoch)
                return
            if tokens:
                req["out"].extend(int(t) for t in tokens)
        # deterministic chaos trigger: a test arms fleet.apply.r<idx>
        # to SIGKILL the replica at an exact stream position
        if h.pid:
            _fi.kill_pid(f"fleet.apply.r{h.idx}", h.pid)
        if done:
            err = _rebuild_error(error)
            if err is not None:
                self._fail(req, err)
            else:
                self._finish(req)

    # -- monitor / failover -------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self.heartbeat_seconds):
            for h in list(self.replicas):
                if h.degraded or not h.alive:
                    continue
                try:
                    snap = h.probe_health(
                        timeout=max(self.heartbeat_seconds, 0.1))
                except (ConnectionError, OSError, socket.timeout,
                        ValueError):
                    h.misses += 1
                    if h.misses >= self.heartbeat_misses:
                        self._replica_down(h, reason="heartbeat")
                    continue
                h.misses = 0
                h.health = snap
                if snap.get("gave_up"):
                    # supervisor exhausted ITS restarts: the process
                    # is up but permanently refusing work — treat as
                    # dead so resurrection replaces it
                    self._replica_down(h, reason="gave_up")
            self._retry_parked()
            _M_healthy.set(len(self._live()))

    def _retry_parked(self) -> None:
        with self._lock:
            parked, self._parked = self._parked, []
        for req in parked:
            if req["terminal"]:
                continue
            self._dispatch(req, exclude=())

    def _replica_down(self, h: ReplicaHandle, reason: str) -> None:
        """Fence ``h`` and fail its work over. Idempotent per epoch:
        poller and monitor may both notice the same death."""
        with self._lock:
            if not h.alive:
                return
            h.alive = False
            h.epoch += 1   # the fence: in-flight stamps are now stale
            h.health = None
            victims = [r for r in self._inflight.values()
                       if r["owner"] and r["owner"][0] == h.idx]
        h.drop_conn()
        _M_deaths.inc()
        _flight.record("fleet", "replica_dead", replica=h.idx,
                       reason=reason, epoch=h.epoch,
                       victims=len(victims))
        for req in victims:
            req["strikes"] += 1
            if req["strikes"] >= self.quarantine_after:
                with self._lock:
                    self.quarantined += 1
                _M_quarantined.inc()
                _flight.record("fleet", "quarantined",
                               trace_id=req["trace_id"],
                               strikes=req["strikes"])
                self._fail(req, RuntimeError(
                    f"request quarantined as poison: active at "
                    f"{req['strikes']} consecutive replica deaths"))
                continue
            with self._lock:
                self.failovers += 1
            _M_redispatched.inc()
            _flight.record("fleet", "failover",
                           trace_id=req["trace_id"], from_replica=h.idx,
                           committed=len(req["out"]),
                           strikes=req["strikes"])
            self._dispatch(req, exclude=(h.idx,))
        _M_healthy.set(len(self._live()))
        if h.spawn is not None:
            threading.Thread(target=self._resurrect, args=(h,),
                             daemon=True,
                             name=f"fleet-resurrect-{h.idx}").start()
        elif h.kill_cb is None and h.proc is None:
            pass  # externally managed replica: stays down

    def _resurrect(self, h: ReplicaHandle) -> None:
        """Relaunch a dead replica under bounded full-jittered backoff.
        ``max_restarts`` failures degrade to the surviving fleet —
        journaled, counted, and never an exception out of this
        thread."""
        if h.proc is not None:
            try:
                h.proc.wait(timeout=10)  # reap the SIGKILLed child
            except (subprocess.TimeoutExpired, OSError):
                pass
        attempt = 0
        while not self._stop.is_set():
            attempt += 1
            if attempt > self.max_restarts:
                h.degraded = True
                _M_degraded.inc()
                _flight.record("fleet", "degraded", replica=h.idx,
                               restarts=attempt - 1)
                return
            delay = _backoff.full_jitter(
                min(self.restart_backoff * (2 ** (attempt - 1)),
                    self.restart_backoff_cap))
            if self._stop.wait(delay):
                return
            _flight.record("fleet", "resurrect_attempt",
                           replica=h.idx, attempt=attempt)
            try:
                fresh = h.spawn(h.idx)
            except Exception as e:  # noqa: BLE001 — retried, bounded
                _flight.record("fleet", "resurrect_failed",
                               replica=h.idx, attempt=attempt,
                               error=type(e).__name__)
                continue
            with self._lock:
                h.host, h.port = fresh.host, fresh.port
                h.pid, h.proc = fresh.pid, fresh.proc
                h.kill_cb = fresh.kill_cb
                h.misses = 0
                h.health = None
                h.restarts += attempt
                h.alive = True
            _M_resurrected.inc()
            _flight.record("fleet", "resurrected", replica=h.idx,
                           attempt=attempt, pid=h.pid)
            self._retry_parked()
            return

    # -- admin --------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            inflight = len(self._inflight)
            parked = len(self._parked)
        return {"replicas": len(self.replicas),
                "live": len(self._live()),
                "in_flight": inflight, "parked": parked,
                "finished": self.finished, "failed": self.failed,
                "shed": self.shed, "failovers": self.failovers,
                "quarantined": self.quarantined,
                "stale_drops": self.stale_drops,
                "restarts": sum(h.restarts for h in self.replicas),
                "degraded": sum(int(h.degraded)
                                for h in self.replicas)}

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the router and every replica we own (RPC shutdown,
        then terminate the subprocess if it lingers)."""
        self._stop.set()
        for h in self.replicas:
            try:
                h.call({"op": "shutdown", "drain": drain})
            except (ConnectionError, OSError, socket.timeout):
                pass
            h.drop_conn()
            if h.proc is not None:
                try:
                    h.proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    h.proc.kill()
        _flight.record("fleet", "router_down", **self.stats())


# ---------------------------------------------------------------------------
# rollout over RPC
# ---------------------------------------------------------------------------

class RemotePrepared:
    """Opaque handle to a prepared weight tree living ON the replica.
    ``nonfinite`` carries the replica-side scan so the supervisor's
    ``_count_nonfinite`` never tries to tree-walk a token string."""

    __slots__ = ("token", "nonfinite")

    def __init__(self, token: str, nonfinite: int = 0):
        self.token = token
        self.nonfinite = int(nonfinite)


class _RemoteEngine:
    """The ``srv.engine`` duck-type ``rollout()`` touches, over RPC."""

    def __init__(self, client: "ReplicaClient"):
        self._c = client

    def prepare_swap(self, state_dict) -> RemotePrepared:
        state = {str(k): _encode_array(v)
                 for k, v in state_dict.items()}
        reply = self._c._call({"op": "prepare_swap", "state": state})
        return RemotePrepared(reply["token"], reply["nonfinite"])

    @property
    def params(self) -> RemotePrepared:
        """The retained rollback tree — kept replica-side, referenced
        by token (already finite: it was serving traffic)."""
        reply = self._c._call({"op": "retain_params"})
        return RemotePrepared(reply["token"], 0)


class ReplicaClient:
    """A ``rollout()``-compatible handle for ONE remote replica:
    ``.engine.prepare_swap``/``.engine.params``, ``.generate`` and
    ``.swap_weights(prepared=)`` all run over the fleet RPC, so the
    canary machinery (probe, divergence, rollback) is literally the
    PR 15 code path across a process boundary."""

    def __init__(self, host: str, port: int, timeout: float = 300.0):
        self.host, self.port = host, int(port)
        self._timeout = float(timeout)
        self._conn = None
        self._io_lock = threading.Lock()
        self.engine = _RemoteEngine(self)

    def _call(self, msg: dict) -> dict:
        with self._io_lock:
            if self._conn is None:
                self._conn = _connect(self.host, self.port,
                                      site="fleet.rollout")
                self._conn.settimeout(self._timeout)
            self._conn.send(msg)
            reply = self._conn.recv()
        if not reply.get("ok"):
            err = _rebuild_error(reply.get("error"))
            raise err if err is not None else RuntimeError(
                f"replica op {msg.get('op')!r} failed: {reply}")
        return reply

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 timeout: float = 300.0) -> List[int]:
        reply = self._call({"op": "generate",
                            "prompt": [int(t) for t in prompt_ids],
                            "max_new": int(max_new_tokens),
                            "timeout": float(timeout)})
        return list(reply["tokens"])

    def swap_weights(self, checkpoint_or_state=None, *,
                     prepared: Optional[RemotePrepared] = None) -> dict:
        if prepared is None:
            raise ValueError(
                "ReplicaClient.swap_weights needs prepared= (a "
                "RemotePrepared from engine.prepare_swap / "
                "engine.params)")
        reply = self._call({"op": "swap_weights",
                            "prepared": prepared.token})
        return reply["result"]

    def close(self) -> None:
        with self._io_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


# ---------------------------------------------------------------------------
# fleet bring-up
# ---------------------------------------------------------------------------

def spawn_fleet(n: int, replica_config: dict,
                env: Optional[dict] = None,
                router_kwargs: Optional[dict] = None) -> FleetRouter:
    """Launch ``n`` replica subprocesses from one config (sharing the
    executable cache + warm bundle the config names) and return the
    router over them, with resurrection wired to relaunch from the
    same config."""
    def make_spawn(idx: int):
        def spawn(_idx: int) -> ReplicaHandle:
            proc, port, _boot = launch_replica(dict(replica_config),
                                               env=env)
            return ReplicaHandle(idx, "127.0.0.1", port,
                                 pid=proc.pid, proc=proc, spawn=spawn)
        return spawn

    handles = []
    for i in range(int(n)):
        spawn = make_spawn(i)
        proc, port, _boot = launch_replica(dict(replica_config),
                                           env=env)
        handles.append(ReplicaHandle(i, "127.0.0.1", port,
                                     pid=proc.pid, proc=proc,
                                     spawn=spawn))
    return FleetRouter(handles, **(router_kwargs or {}))


def _main() -> int:
    cfg = os.environ.get("PADDLE_TPU_REPLICA_CONFIG")
    if not cfg and len(sys.argv) > 1:
        with open(sys.argv[1], "r") as f:
            cfg = f.read()
    if not cfg:
        print("usage: python -m paddle_tpu.serving_fleet <config.json>"
              " (or PADDLE_TPU_REPLICA_CONFIG in the env)",
              file=sys.stderr)
        return 2
    replica_main(json.loads(cfg))
    return 0


if __name__ == "__main__":
    sys.exit(_main())
