"""Compiled (SPMD) parallelism building blocks.

Unlike paddle_tpu.distributed.fleet (the reference-shaped host-driven
wrappers, ref: fleet/meta_parallel/), these are mesh-axis programs that
live entirely inside one jit: the compiler sees the whole schedule.
"""
from .pipeline_spmd import (  # noqa: F401
    spmd_pipeline, spmd_pipeline_interleaved, stack_layer_params,
)
