"""Fully-compiled pipeline parallelism: GPipe schedule inside one jit.

The reference's PP is a host-driven micro-batch loop with NCCL p2p
(ref: fleet/meta_parallel/pipeline_parallel.py:575-720 1F1B,
pp_utils/p2p_communication.py send/recv). On TPU a host loop serializes on
dispatch latency (SURVEY.md §7 hard parts), so this module compiles the
whole schedule: per-stage parameters are STACKED with a leading stage dim
sharded on the 'pp' mesh axis; a lax.fori_loop ticks M + S - 1 times, each
tick running every stage on its in-flight micro-batch and rotating
activations one hop with ppermute (p2p over ICI). Backward is jax.grad
through the loop — autodiff reverses the schedule, giving the cooldown
phase for free.

Stages must be structurally identical (e.g. the decoder-layer stack);
embedding/head run outside the pipelined region, as on stage-0/stage-N
in the reference's PipelineLayer segmentation (ref: pp_layers.py:257).
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..distributed._mesh_axes import shard_map

__all__ = ["spmd_pipeline", "spmd_pipeline_interleaved",
           "stack_layer_params", "remat_policy"]


def remat_policy(name):
    """Resolve a rematerialization policy knob for the pipeline stage body.

    ref-analog: the reference bounds PP activation memory by hand with the
    1F1B schedule (pipeline_parallel.py:575-720) + recompute
    (fleet recompute / auto_parallel_recompute pass). Under whole-program
    autodiff the equivalent lever is jax.checkpoint on the per-tick stage
    computation:
      - "none": save every stage-internal activation (fastest backward,
        highest memory);
      - "dots": save only matmul outputs
        (jax.checkpoint_policies.dots_saveable) — the usual sweet spot;
      - "full": save nothing, recompute the whole stage body in backward
        (jax.checkpoint_policies.nothing_saveable) — activation residuals
        shrink to the one carried activation per tick.
    Memory shape (measured by tests/test_pipeline_memory.py): the
    compiled GPipe schedule stores one carried activation per tick
    (linear in M with a one-activation constant under "full"); the
    host-driven fleet 1F1B path keeps the reference's S-bounded profile
    when M-independence is required.
    """
    if name in (None, "none", False):
        return None
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    if callable(name):
        return name
    raise ValueError(f"unknown remat policy {name!r}")


def _maybe_remat(stage_fn, remat):
    policy = remat_policy(remat)
    if policy is None:
        return stage_fn
    return jax.checkpoint(stage_fn, policy=policy)


def stack_layer_params(per_layer_params: Sequence[dict]) -> dict:
    """[{name: arr}, ...] for S structurally-identical layers -> one pytree
    {name: arr[S, ...]}; shard its leading dim on the pp axis."""
    keys = list(per_layer_params[0].keys())
    return {k: jnp.stack([p[k] for p in per_layer_params]) for k in keys}


def _pipeline_local(params, microbatches, *, stage_fn, axis):
    """Runs per-stage inside shard_map. params: leading dim 1 (this stage's
    slice); microbatches: [M, B, ...] (replicated input feed)."""
    S = jax.lax.psum(1, axis)
    sid = jax.lax.axis_index(axis)
    M = microbatches.shape[0]
    # each mesh stage may hold several consecutive layers (stacked dim //
    # axis size); it runs them back-to-back per tick
    group = next(iter(jax.tree.leaves(params))).shape[0]
    first = sid == 0
    last = sid == S - 1

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)

    def tick(t, carry):
        buf, outs = carry
        # stage 0 feeds micro-batch t; the others consume the activation
        # that rotated in from the previous stage last tick
        x = jnp.where(first, microbatches[jnp.clip(t, 0, M - 1)], buf)
        y = x
        for g in range(group):
            y = stage_fn(jax.tree.map(lambda a: a[g], params), y)
        # the last stage finished micro-batch t-(S-1) this tick
        w = t - (S - 1)
        valid = jnp.logical_and(last, jnp.logical_and(w >= 0, w < M))
        wc = jnp.clip(w, 0, M - 1)
        outs = outs.at[wc].set(jnp.where(valid, y, outs[wc]))
        # rotate activations one hop along the ring (stage s -> s+1)
        buf_next = jax.lax.ppermute(
            y, axis, [(i, (i + 1) % S) for i in range(S)])
        return buf_next, outs

    _, outs = jax.lax.fori_loop(0, M + S - 1, tick, (buf0, outs0))
    # only the last stage holds real outputs; masked psum replicates them
    outs = jax.lax.psum(jnp.where(last, outs, 0.0), axis)
    return outs


def spmd_pipeline(stage_fn: Callable, stacked_params, microbatches, mesh,
                  axis: str = "pp", batch_axes=(), remat=None):
    """Run the compiled pipeline.

    stage_fn(params_one_stage, x) -> y with y.shape == x.shape.
    stacked_params: pytree of [L, ...] arrays (see stack_layer_params); L
    must be a multiple of the pp axis size — each stage runs L/S
    consecutive layers per tick.
    microbatches: [M, B, ...] array; M micro-batches of the global batch.
    batch_axes: mesh axes sharding the batch dim (dp composition).
    remat: None | "dots" | "full" | jax checkpoint policy — see
    remat_policy. Returns [M, B, ...] outputs of the final stage.
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n_stages = dict(zip(jmesh.axis_names, jmesh.devices.shape))[axis]
    n_layers = next(iter(jax.tree.leaves(stacked_params))).shape[0]
    if n_layers % n_stages != 0:
        raise ValueError(
            f"stacked layer count {n_layers} must be a multiple of the "
            f"'{axis}' axis size {n_stages}")
    stage_fn = _maybe_remat(stage_fn, remat)
    ndim = microbatches.ndim
    data_spec = P(None, tuple(batch_axes) or None,
                  *([None] * (ndim - 2)))
    param_specs = jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params)
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn, axis=axis),
        mesh=jmesh, in_specs=(param_specs, data_spec),
        out_specs=data_spec, check_vma=False)
    return fn(stacked_params, microbatches)


def _pipeline_interleaved_local(params, microbatches, *, stage_fn, axis,
                                num_chunks):
    """Circular interleaved schedule inside shard_map.

    params: [V, 1(stage), ...] — this stage's V chunk slices, each chunk
    possibly holding several consecutive layers ([V, 1, G, ...]).
    Each in-flight activation carries (value, chunk v, micro-batch m,
    alive); it laps the ring V times, one chunk per lap, and dies after
    chunk V-1 on the last stage. Stage 0 injects a new micro-batch
    whenever its slot arrives dead. Per tick each stage runs ONE chunk
    (vs the non-interleaved schedule's V consecutive layers), so the
    fill/drain bubble shrinks by the factor V — the compiled analog of
    the reference's VPP (pipeline_parallel.py:1174
    PipelineParallelWithInterleave).
    """
    S = jax.lax.psum(1, axis)
    sid = jax.lax.axis_index(axis)
    V = num_chunks
    M = microbatches.shape[0]
    first = sid == 0
    last = sid == S - 1
    # local param layout: [V, 1 (this stage's slice), G, ...]
    group = next(iter(jax.tree.leaves(params))).shape[2]

    def run_chunk(v, x):
        def chunk_branch(vv):
            def br(xx):
                y = xx
                for g in range(group):
                    y = stage_fn(
                        jax.tree.map(lambda a: a[vv, 0, g], params), y)
                return y
            return br
        return jax.lax.switch(v, [chunk_branch(vv) for vv in range(V)], x)

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)

    def tick(t, carry):
        buf, v, m, alive, next_m, outs = carry
        # stage 0: inject a fresh micro-batch into a dead slot
        inject = jnp.logical_and(first,
                                 jnp.logical_and(~alive, next_m < M))
        x = jnp.where(inject, microbatches[jnp.clip(next_m, 0, M - 1)],
                      buf)
        v = jnp.where(inject, 0, v)
        m = jnp.where(inject, next_m, m)
        alive = jnp.logical_or(alive, inject)
        next_m = next_m + inject.astype(jnp.int32)

        y = jnp.where(alive, run_chunk(jnp.clip(v, 0, V - 1), x), x)

        # the last stage on the final lap completes micro-batch m
        done = jnp.logical_and(alive, jnp.logical_and(last, v == V - 1))
        wc = jnp.clip(m, 0, M - 1)
        outs = outs.at[wc].set(jnp.where(done, y, outs[wc]))

        # lap counter bumps on the wrap from stage S-1 to stage 0
        v_next = v + jnp.where(last, 1, 0)
        alive_next = jnp.logical_and(alive, ~done)
        perm = [(i, (i + 1) % S) for i in range(S)]
        buf_n = jax.lax.ppermute(y, axis, perm)
        v_n = jax.lax.ppermute(v_next, axis, perm)
        m_n = jax.lax.ppermute(m, axis, perm)
        alive_n = jax.lax.ppermute(alive_next, axis, perm)
        return buf_n, v_n, m_n, alive_n, next_m, outs

    waves = (M + S - 1) // S
    T = waves * V * S + S
    _, _, _, _, _, outs = jax.lax.fori_loop(
        0, T, tick,
        (buf0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
         jnp.zeros((), jnp.bool_), jnp.zeros((), jnp.int32), outs0))
    outs = jax.lax.psum(jnp.where(last, outs, 0.0), axis)
    return outs


def spmd_pipeline_interleaved(stage_fn: Callable, stacked_params,
                              microbatches, mesh, axis: str = "pp",
                              batch_axes=(), num_chunks: int = 2,
                              remat=None):
    """Interleaved (virtual-pipeline) compiled schedule.

    Layer l of the [L, ...] stack runs as chunk l // (L/V/S') ... —
    concretely the stack is reshaped to [V, S, G, ...] so stage s owns
    chunks {v: layers (v*S + s)*G .. +G}, the round-robin placement of
    the reference's VPP (pp_layers.py get_stage_from_index for
    interleave). L must be divisible by V*S. The reference's zero-bubble
    variants exist to fill the dx/dW host schedule; under whole-program
    compilation XLA schedules those kernels inside one executable, so the
    compiled pipeline already has no host-induced bubble.
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    S = dict(zip(jmesh.axis_names, jmesh.devices.shape))[axis]
    L = next(iter(jax.tree.leaves(stacked_params))).shape[0]
    V = num_chunks
    if L % (V * S) != 0:
        raise ValueError(
            f"layer count {L} must be a multiple of num_chunks*stages "
            f"= {V}*{S}")
    G = L // (V * S)
    stage_fn = _maybe_remat(stage_fn, remat)
    # [L, ...] -> [V, S, G, ...]: layer (v*S + s)*G + g -> [v, s, g]
    params_vsg = jax.tree.map(
        lambda a: a.reshape((V, S, G) + a.shape[1:]), stacked_params)
    ndim = microbatches.ndim
    data_spec = P(None, tuple(batch_axes) or None,
                  *([None] * (ndim - 2)))
    param_specs = jax.tree.map(
        lambda a: P(None, axis, *([None] * (a.ndim - 2))), params_vsg)
    fn = shard_map(
        functools.partial(_pipeline_interleaved_local, stage_fn=stage_fn,
                          axis=axis, num_chunks=V),
        mesh=jmesh, in_specs=(param_specs, data_spec),
        out_specs=data_spec, check_vma=False)
    return fn(params_vsg, microbatches)
