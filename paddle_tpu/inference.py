"""Inference engine: saved model -> compiled serving predictor.

ref: paddle/fluid/inference/api/analysis_predictor.h (AnalysisPredictor:
load program+params, run analysis/fusion passes, zero-copy IO) and
python/paddle/inference (Config + create_predictor). The TPU analog: the
"analysis passes + fusion" role belongs to XLA — a Predictor functionalizes
the model, jit-compiles forward per input signature (shape/dtype-keyed
cache), and serves batches. Saved artifacts are paddle.jit.save outputs:
state_dict + a model-factory reference, so a server process can
reconstruct without the training script.
"""
from __future__ import annotations

import importlib
import os
import inspect
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .framework.io import load as _load, save as _save
from .jit.api import functionalize

__all__ = ["Config", "Predictor", "create_predictor", "save_inference_model",
           "load_inference_model", "serve"]


def _forced_eval_fwd(model, apply):
    """Forward that serves in eval semantics without disturbing the
    caller's per-sublayer modes."""
    def fwd(params, buffers, *args):
        layers = model.sublayers(include_self=True)
        snapshot = [(l, l.training) for l in layers]
        try:
            for l in layers:
                l.training = False
            out, _ = apply(params, buffers, *args)
        finally:
            for l, t in snapshot:
                l.training = t
        return out
    return fwd


def _export_aot(model, input_spec):
    """AOT-serialize the compiled eval forward via jax.export — the
    StableHLO travels inside the artifact, so a serving process can run
    it WITHOUT the model's Python class being importable
    (ref: AnalysisPredictor loads a self-contained program+params;
    the reference never needs the training script either)."""
    apply, params, buffers = functionalize(model)
    jitted = jax.jit(_forced_eval_fwd(model, apply))
    arg_avals = []
    for s in input_spec:
        if any(d is None or int(d) <= 0 for d in s.shape):
            raise ValueError(
                f"AOT export needs fully-static input shapes, got "
                f"{list(s.shape)} (use bucketing for varlen serving)")
        shape = tuple(int(d) for d in s.shape)
        arg_avals.append(jax.ShapeDtypeStruct(shape, jnp.dtype(s.dtype)))
    p_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in params.items()}
    b_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in buffers.items()}
    exported = jax.export.export(jitted)(p_avals, b_avals, *arg_avals)
    return {
        "blob": exported.serialize(),
        "param_keys": sorted(params),
        "buffer_keys": sorted(buffers),
    }


def save_inference_model(path: str, model, input_spec=None, aot=False):
    """ref: paddle.static.save_inference_model / jit.save — persist params
    plus the importable factory so inference can rebuild the module.
    input_spec (shapes/dtypes) is stored for consumers that pre-compile.

    Reconstructability is validated AT SAVE TIME: a model whose __init__
    needs arguments must expose them as `.config` (the LM zoo convention),
    otherwise load would fail later in the serving process.
    """
    cls = type(model)
    cfg = getattr(model, "config", None)
    if cfg is None:
        sig = inspect.signature(cls.__init__)
        P_ = inspect.Parameter
        required = [
            n for n, p in list(sig.parameters.items())[1:]
            if (p.kind in (P_.POSITIONAL_OR_KEYWORD, P_.POSITIONAL_ONLY,
                           P_.KEYWORD_ONLY)
                and p.default is P_.empty)
            or p.kind is P_.VAR_POSITIONAL  # e.g. Sequential(*layers)
        ]
        if required:
            raise ValueError(
                f"cannot save {cls.__qualname__} for inference: __init__ "
                f"takes {required} but the model has no .config "
                "attribute to rebuild from. Store constructor arguments "
                "on `self.config`, or save weights only via paddle.save")
    payload = {
        "state_dict": model.state_dict(),
        "module": cls.__module__,
        "class_name": cls.__qualname__,
        "init_config": cfg,
        "input_spec": [
            {"shape": list(s.shape), "dtype": str(s.dtype)}
            for s in (input_spec or [])
        ],
    }
    if aot:
        if not input_spec:
            raise ValueError(
                "save_inference_model(aot=True) needs input_spec to fix "
                "the exported program's signature")
        payload["aot"] = _export_aot(model, input_spec)
    _save(payload, path + ".pdmodel")


def load_inference_model(path: str, _payload=None):
    """Rebuild the Layer from a save_inference_model artifact. Raises if
    the reconstructed module's parameters don't match the checkpoint —
    serving silently-random weights is the worst failure mode."""
    payload = _payload if _payload is not None else _load(
        path + ".pdmodel", return_numpy=False)
    mod = importlib.import_module(payload["module"])
    cls = mod
    for part in payload["class_name"].split("."):
        cls = getattr(cls, part)
    cfg = payload["init_config"]
    model = cls(cfg) if cfg is not None else cls()
    # install weights preserving the CHECKPOINT dtype (a bf16-saved model
    # must serve in bf16)
    missing, unexpected = model.set_state_dict(payload["state_dict"],
                                               cast_dtype=False)
    if missing or unexpected:
        raise ValueError(
            f"saved model does not match reconstructed "
            f"{payload['class_name']}: missing={missing[:5]}, "
            f"unexpected={unexpected[:5]}")
    model.eval()
    return model


class Config:
    """ref: paddle.inference.Config — carries the model path + runtime
    options (the CUDA/TensorRT knobs become XLA-level choices here)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self._bf16 = False

    def enable_bf16(self):
        self._bf16 = True

    # GPU-era knobs kept as accepted no-ops for API compatibility (XLA
    # already does the fusion/memory planning these toggled)
    def enable_memory_optim(self, *a, **k):
        return None

    def enable_use_gpu(self, *a, **k):
        return None

    def switch_ir_optim(self, *a, **k):
        return None


class Predictor:
    """Compiled serving wrapper (ref: AnalysisPredictor::Run contract:
    named inputs in, named outputs out, internal exec state reused)."""

    def __init__(self, model_or_config):
        self._cache_key_base = None
        self._aot = None
        if isinstance(model_or_config, Config):
            cfg = model_or_config
            if cfg.model_path is None:
                raise ValueError(
                    "Config has no model_path; pass Config(path) pointing "
                    "at a save_inference_model artifact")
            payload = _load(cfg.model_path + ".pdmodel",
                            return_numpy=False)
            if payload.get("aot"):
                if cfg._bf16:
                    raise ValueError(
                        "enable_bf16() cannot re-cast an AOT artifact "
                        "(its compiled signature is fixed at export); "
                        "save with a bf16 model instead")
                # AOT warm start: the serialized StableHLO serves without
                # the model class being importable in this process
                self._init_aot(payload)
                return
            model = load_inference_model(cfg.model_path, _payload=payload)
            if cfg._bf16:
                model.bfloat16()
            # artifact-backed predictors share compiled executables
            # process-wide through the native ExecCache (KernelFactory
            # analog): a second Predictor on the same path skips compile.
            # mtime+size in the key invalidate on artifact overwrite (the
            # replaced cache entry drops the old model's closure).
            art = cfg.model_path + ".pdmodel"
            st = os.stat(art)
            self._cache_key_base = \
                f"predictor|{os.path.abspath(cfg.model_path)}" \
                f"|{st.st_mtime_ns}|{st.st_size}|bf16={cfg._bf16}"
        else:
            model = model_or_config
        self.model = model
        apply, params, buffers = functionalize(model)
        self._apply = apply
        self._params = params
        self._buffers = buffers

        fwd = _forced_eval_fwd(model, apply)

        from ._native import lib as _nlib
        use_cache = self._cache_key_base is not None and _nlib is not None
        cached = (_nlib.exec_cache_get(self._cache_key_base)
                  if use_cache else None)
        # (re)compile or reuse the jitted callable — its XLA compile cache
        # comes with it; params/buffers bind per run() call
        self._jitted = cached if cached is not None else jax.jit(fwd)
        if use_cache and cached is None:
            # evict entries for older versions of this artifact first —
            # their keys (old mtime/size) would otherwise pin the old
            # model's weights until cap eviction
            prefix = self._cache_key_base.rsplit("|", 3)[0] + "|"
            _nlib.exec_cache_evict_prefix(prefix)
            _nlib.exec_cache_put(self._cache_key_base, self._jitted)

    def _init_aot(self, payload):
        exported = jax.export.deserialize(payload["aot"]["blob"])
        sd = payload["state_dict"]

        def arr(v):
            return v._data if isinstance(v, Tensor) else jnp.asarray(v)

        self._params = {k: arr(sd[k]) for k in payload["aot"]["param_keys"]}
        self._buffers = {k: arr(sd[k])
                         for k in payload["aot"]["buffer_keys"]}
        self._aot = exported
        self.model = None
        self._input_spec = payload.get("input_spec", [])

    def run(self, *inputs):
        """numpy/Tensor/jax-array inputs -> list of numpy outputs."""
        raw = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        if self._aot is not None:
            out = self._aot.call(self._params, self._buffers, *raw)
        else:
            out = self._jitted(self._params, self._buffers, *raw)
        if isinstance(out, (tuple, list)):
            return [np.asarray(o) for o in out]
        return [np.asarray(out)]

    # reference-style named-handle API: names come from the model's
    # forward signature
    def get_input_names(self) -> Sequence[str]:
        if self._aot is not None:
            return [f"input_{i}" for i in range(len(self._input_spec))]
        sig = inspect.signature(self.model.forward)
        return [n for n, p in sig.parameters.items()
                if p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)]

    def predict(self, *inputs):
        return self.run(*inputs)


def create_predictor(config: Config) -> Predictor:
    """ref: paddle.inference.create_predictor."""
    return Predictor(config)


class _MicroBatcher:
    """Request micro-batching for the predictor server (ref: the
    reference predictor's multi-stream batched serving,
    inference/api/analysis_predictor.h): concurrent requests arriving
    within a short window whose inputs share trailing shapes/dtypes are
    concatenated along axis 0, run as ONE compiled forward, and split
    back — one dispatch serves many clients. Requests that can't batch
    (different signature, outputs not row-aligned) fall back to
    individual runs."""

    def __init__(self, predictor, max_batch: int = 32,
                 window_ms: float = 2.0):
        import queue
        import threading
        self._p = predictor
        self.max_batch = max(int(max_batch), 1)
        self.window_s = max(float(window_ms), 0.0) / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self.batches_run = 0       # introspection / tests
        self.requests_served = 0
        # signatures whose batched run failed once (e.g. fixed-shape AOT
        # executables): don't re-attempt the doomed concatenation every
        # window
        self._no_batch: set = set()
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def run(self, inputs):
        import threading
        done = threading.Event()
        slot: dict = {}
        self._q.put((inputs, done, slot))
        done.wait()
        if "error" in slot:
            raise slot["error"]
        return slot["outs"]

    @staticmethod
    def _sig(inputs):
        return tuple((np.asarray(a).shape[1:], str(np.asarray(a).dtype))
                     for a in inputs)

    def _loop(self):
        import queue
        import time as _time
        while True:
            first = self._q.get()
            batch = [first]
            deadline = _time.monotonic() + self.window_s
            while len(batch) < self.max_batch:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            # No exception may kill this singleton daemon thread — that
            # would hang every subsequent serve() request forever. _sig
            # failures (malformed inputs) are isolated per REQUEST so
            # one bad client doesn't fail the well-formed requests that
            # share its window; _run_group failures fail that group.
            groups: dict = {}
            for item in batch:
                try:
                    groups.setdefault(self._sig(item[0]), []).append(item)
                except Exception as e:
                    self._fail(item, e)
            for sig, members in groups.items():
                try:
                    self._run_group(sig, members)
                except Exception as e:
                    for m in members:
                        self._fail(m, e)

    @staticmethod
    def _fail(item, e):
        # store the ORIGINAL exception (matching _run_single) so callers
        # see the same type whether the failure hit the batched or the
        # singleton path
        _, done, slot = item
        if not done.is_set():
            slot.setdefault("error", e)
            done.set()

    @staticmethod
    def _bucket(total: int) -> int:
        """Pad totals up to a power of two: arbitrary concatenated row
        counts would each compile a fresh XLA program (and stall every
        queued request behind the compile); bucketing bounds the
        distinct compiled shapes to ~log2(max total)."""
        b = 1
        while b < total:
            b *= 2
        return b

    def _run_group(self, sig, members):
        if len(members) == 1 or sig in self._no_batch:
            for m in members:
                self._run_single(m)
            return
        try:
            rows = [int(np.asarray(m[0][0]).shape[0]) for m in members]
            total = sum(rows)
            padded = self._bucket(total)
            stacked = []
            for i in range(len(members[0][0])):
                arr = np.concatenate(
                    [np.asarray(m[0][i]) for m in members], axis=0)
                if padded > total:
                    pad = np.repeat(arr[-1:], padded - total, axis=0)
                    arr = np.concatenate([arr, pad], axis=0)
                stacked.append(arr)
            outs = self._p.run(*stacked)
            if not all(np.asarray(o).shape[:1] == (padded,)
                       for o in outs):
                raise ValueError("outputs not row-aligned with inputs")
            off = 0
            self.batches_run += 1
            for m, r in zip(members, rows):
                m[2]["outs"] = [np.asarray(o)[off:off + r] for o in outs]
                self.requests_served += 1
                m[1].set()
                off += r
        except Exception:
            # batching invalid for this model/signature (e.g. an AOT
            # artifact's fixed input shape): remember and serve each
            # request on its own from now on
            self._no_batch.add(sig)
            for m in members:
                self._run_single(m)

    def _run_single(self, item):
        inputs, done, slot = item
        try:
            slot["outs"] = [np.asarray(o) for o in self._p.run(*inputs)]
            self.batches_run += 1
            self.requests_served += 1
        except Exception as e:  # noqa: BLE001 — surfaced to the client
            slot["error"] = e
        done.set()


def serve(model_path: str, host: str = "127.0.0.1", port: int = 8866,
          block: bool = True, max_batch: int = 32,
          batch_window_ms: float = 2.0, generate: bool = False,
          max_slots: int = 4, max_seq: int = 256, int8: bool = False,
          eos_id=None, speculative: bool = False,
          spec_tokens: Optional[int] = None,
          spec_draft_layers: Optional[int] = None,
          warm_bundle=None, supervised: bool = False,
          fleet: int = 0):
    """Minimal predictor server (ref: the reference ships its predictor
    behind paddle_serving / the C API server loop; this is the
    batteries-included analog). Concurrent requests are micro-batched
    into one compiled forward (see _MicroBatcher); ``max_batch=1``
    disables batching.

    Protocol: POST /run with an .npz body holding arrays input_0..N;
    response is an .npz of output_0..M. GET /health returns 200.
    Returns the HTTPServer (started in a daemon thread) when block=False.

    ``generate=True`` additionally serves POST /generate for causal-LM
    artifacts: body is an .npz with ``input_ids`` [L] and scalar
    ``max_new_tokens``; response is ``output_ids`` (the generated
    continuation). Requests share the PAGED decode engine's slots with
    iteration-level continuous batching over a shared KV block pool —
    a long generation never blocks a short one, a long PROMPT only
    stalls the batch one prefill chunk at a time, and KV HBM scales
    with active tokens (see serving.PagedLlamaDecodeEngine +
    GenerationServer); ``int8=True`` runs the projections as real s8
    matmuls. ``speculative=True`` additionally attaches a
    truncated-layer draft (``spec_draft_layers`` layers, weights
    shared with the target) proposing ``spec_tokens``
    (default ``FLAGS_serving_spec_tokens``) tokens per step — greedy
    output stays bit-equal, decode steps commit up to the whole
    accepted window per host round-trip.

    ``warm_bundle`` (a manifest path or loaded bundle dict; default
    ``FLAGS_warmup_bundle``) pre-warms the decode/prefill/spec
    executables against the persistent executable cache
    (``FLAGS_executable_cache_dir``) BEFORE the server admits its
    first request — a freshly rolled replica is 100%-cache-hit on its
    first token instead of paying a compile storm under traffic.

    ``supervised=True`` attaches a
    ``serving_supervisor.ServingSupervisor`` to the generation
    server: a decode-loop crash (or stall, with
    ``FLAGS_serving_supervisor_stall_seconds`` set) auto-dumps
    flight, restarts the loop with bounded backoff, and RESUMES
    in-flight generations bit-equal from their committed tokens —
    repeat-offender requests are quarantined instead of crash-looping
    the replica.

    ``fleet=N`` (N >= 2, with ``generate=True``) serves /generate
    through a :class:`serving_fleet.FleetRouter` over N supervised
    replica SUBPROCESSES instead of one in-process engine: KV-
    pressure-aware placement, failover with bit-equal stream
    recovery, and warm-bundle resurrection of dead replicas (see
    ``serving_fleet``). The replicas share this process's
    ``FLAGS_executable_cache_dir`` and ``warm_bundle``, so a recycled
    replica rejoins without a compile storm.
    """
    import io
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from .core.flags import flag_value
    from .jit import warmup as _warmup
    _warmup.ensure_executable_cache()
    predictor = Predictor(Config(model_path))
    batcher = _MicroBatcher(predictor, max_batch=max_batch,
                            window_ms=batch_window_ms)
    gen_server = None
    fleet_router = None
    if warm_bundle is None:
        warm_bundle = flag_value("warmup_bundle") or None
    if generate and int(fleet) >= 2:
        from .serving_fleet import spawn_fleet
        fleet_router = spawn_fleet(int(fleet), {
            "model": {"kind": "inference_model", "path": model_path},
            "max_slots": max_slots, "max_seq": max_seq, "int8": int8,
            "eos_id": eos_id, "warm_bundle": warm_bundle,
            "supervised": True})
    elif generate:
        from .serving import GenerationServer, PagedLlamaDecodeEngine
        # reuse the predictor's already-loaded Layer (a second
        # load_inference_model would hold the weights twice at startup)
        model = predictor.model if predictor.model is not None \
            else load_inference_model(model_path)
        engine = PagedLlamaDecodeEngine(
            model, max_slots=max_slots, max_seq=max_seq, int8=int8,
            eos_id=eos_id)
        if speculative:
            engine.attach_draft(
                engine.make_draft(model, num_layers=spec_draft_layers),
                spec_tokens=spec_tokens)
        if warm_bundle:
            # pre-warm BEFORE the loop thread starts admitting: the
            # first request's decode/prefill steps must be cache hits
            _warmup.prewarm(warm_bundle, engine=engine)
        gen_server = GenerationServer(engine)
        if supervised:
            from .serving_supervisor import supervise
            # held on the server so the monitor lives exactly as long
            # as the serving process does
            gen_server._supervisor = supervise(gen_server)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            if self.path == "/health":
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok")
            else:
                self.send_response(404)
                self.end_headers()

        def do_POST(self):
            if self.path not in ("/run", "/generate"):
                self.send_response(404)
                self.end_headers()
                return
            if self.path == "/generate" and gen_server is None \
                    and fleet_router is None:
                msg = b"serve(generate=True) not enabled"
                self.send_response(404)
                self.send_header("Content-Length", str(len(msg)))
                self.end_headers()
                self.wfile.write(msg)
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                data = np.load(io.BytesIO(self.rfile.read(n)),
                               allow_pickle=False)
                if self.path == "/generate":
                    ids = np.asarray(data["input_ids"]).reshape(-1)
                    mnt = int(data["max_new_tokens"]) \
                        if "max_new_tokens" in data else 32
                    toks = (fleet_router or gen_server).generate(
                        ids, mnt)
                    outs = [np.asarray(toks, np.int32)]
                    buf = io.BytesIO()
                    np.savez(buf, output_ids=outs[0])
                    body = buf.getvalue()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/npz")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                inputs = [data[f"input_{i}"] for i in range(len(data))]
                outs = batcher.run(inputs)
                buf = io.BytesIO()
                np.savez(buf, **{f"output_{i}": o
                                 for i, o in enumerate(outs)})
                body = buf.getvalue()
                self.send_response(200)
                self.send_header("Content-Type", "application/npz")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except Exception as e:  # surface the error to the client
                msg = repr(e).encode()
                self.send_response(500)
                self.send_header("Content-Length", str(len(msg)))
                self.end_headers()
                self.wfile.write(msg)

    server = ThreadingHTTPServer((host, port), Handler)
    server.batcher = batcher  # introspection (tests, metrics)
    server.gen_server = gen_server
    server.fleet_router = fleet_router
    if block:
        server.serve_forever()
        return None
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
