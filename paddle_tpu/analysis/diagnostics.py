"""Structured diagnostics: the one currency every analyzer trades in.

The program auditor (``analysis/auditor.py``), the source linter
(``analysis/lint.py``) and the lock-order checker (``analysis/locks.py``)
all emit :class:`Diagnostic` records — rule id, severity, a location
(``file.py:line`` for source rules, a DAG/lock description for runtime
rules), a message and a fix hint — so one reporting surface
(``analysis.report()`` / ``python -m paddle_tpu.analysis``) can render,
count and gate on all three. Rule metadata lives in :data:`RULES` and is
the source of the README rules table (test-pinned, like the flags
reference).

Severity contract: ``error`` = a defect that will corrupt results or
deadlock (use-after-donate, lock cycle); ``warning`` = a hazard or perf
cliff (host sync in a hot path, recompile churn, unguarded registry
mutation); ``info`` = attribution the capture report enumerates without
judgement (flush boundaries, donation sites).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["Diagnostic", "RuleInfo", "RULES", "severity_rank"]

_SEVERITIES = ("error", "warning", "info")


def severity_rank(severity: str) -> int:
    """error < warning < info (sortable: most severe first)."""
    try:
        return _SEVERITIES.index(severity)
    except ValueError:
        return len(_SEVERITIES)


@dataclass(frozen=True)
class RuleInfo:
    id: str
    analyzer: str       # "audit" | "lint" | "locks"
    severity: str       # default severity of findings
    title: str
    description: str


# The closed rule universe. PTA* = program auditor (runtime capture),
# PTL* = source linter (AST), PTK* = lock-order checker (instrumented
# locks), PTC* = static capture planner (graph-break analysis +
# shape/dtype abstract interpretation). tests/test_analysis.py and
# tests/test_capture_plan.py seed one bug per detection rule and assert
# the exact id; README's rules table is generated from this.
RULES: Dict[str, RuleInfo] = {r.id: r for r in [
    RuleInfo(
        "PTA001", "audit", "warning", "implicit host sync",
        "A device→host materialization (.numpy()/.item()/float()/"
        "__array__) inside the audited region — each one stalls dispatch "
        "and, when it lands mid-chain, flushes the fusion DAG "
        "(flush reason host_read). The capture report attributes every "
        "sync to its call site."),
    RuleInfo(
        "PTA002", "audit", "error", "use-after-donate",
        "A live Tensor handle still references a buffer that was donated "
        "to a jitted executable (XLA deleted it): the next read raises "
        "or returns garbage. Generalizes the fused optimizer's "
        "copy-on-donate alias registry into a detector."),
    RuleInfo(
        "PTA003", "audit", "warning", "recompile churn",
        "A program cache kept compiling during the measured (post-"
        "warmup) run: shape-polymorphic call sites, unhashable statics "
        "or churning cache keys. Steady-state steps should be compile-"
        "free; every compile here is dispatch-path latency."),
    RuleInfo(
        "PTL001", "lint", "warning", "implicit host sync in library code",
        "A .numpy()/.item()/.tolist() call inside paddle_tpu/ library "
        "code: a hidden device→host sync on what may be a hot path. "
        "Deliberate syncs (structural args that must be host-static for "
        "XLA, user-facing host APIs) belong in the allowlist with a "
        "justification."),
    RuleInfo(
        "PTL002", "lint", "warning", "registered flag never read",
        "A FLAGS_* registered in core/flags.py (or a late define_flag) "
        "with no read anywhere in the package: either dead surface or a "
        "flag that silently does nothing the docs claim it does."),
    RuleInfo(
        "PTL003", "lint", "warning", "unguarded global registry mutation",
        "A structural mutation (del/pop/clear/eviction loop) of a "
        "module-level registry outside any lock: concurrent dispatch "
        "threads can corrupt iteration or drop entries mid-sweep. "
        "Single-assignment memo inserts are GIL-atomic and not flagged."),
    RuleInfo(
        "PTL004", "lint", "error", "bare except",
        "A bare `except:` swallows KeyboardInterrupt/SystemExit AND the "
        "fault-injection harness's BaseException kill-points — device "
        "code wrapped in one can absorb the very crash a test injects."),
    RuleInfo(
        "PTL005", "lint", "error", "ops.yaml fusable marker inconsistent",
        "An op marked `fusable:` in ops.yaml with no matching "
        "register_impl/register_param_impl registration (or a "
        "registration for an op ops.yaml doesn't mark): the fusion "
        "plane would silently never fuse it."),
    RuleInfo(
        "PTC001", "capture", "warning", "data-dependent control flow",
        "An `if`/`while` whose test reads a tensor VALUE (`if t:`, "
        "`while t.item()`, a comparison on a tensor feeding the "
        "branch): every taken branch becomes a guard + graph break in "
        "whole-step capture — the trace tree grows one compiled path "
        "per branch outcome. Shape/ndim/dtype reads are static "
        "metadata and are not flagged."),
    RuleInfo(
        "PTC002", "capture", "warning", "capture-poisoning side effect",
        "A side effect inside the candidate capture region that replay "
        "cannot reproduce: in-place tensor mutation, RNG consumption "
        "(dropout and friends), mutation of module/global/self state, "
        "or host I/O. jit/sot.py marks such recordings non-replayable "
        "at runtime (the call stays eager forever); this flags them "
        "before tracing is even attempted."),
    RuleInfo(
        "PTC003", "capture", "warning", "host read inside the step",
        "A device->host fetch (.item()/.numpy()/.tolist()/float()) "
        "inside the candidate region. When it postdominates all device "
        "work it is HOISTABLE (fix hint: move it after the step / "
        "batch the fetch); mid-step reads serialize dispatch and must "
        "become capture guards or move."),
    RuleInfo(
        "PTC004", "capture", "warning", "shape-polymorphic call site",
        "A call site whose tensor shapes vary run-to-run (boolean-mask "
        "indexing, nonzero/unique/masked_select, or PTA003 churn rows "
        "from the dynamic audit): each distinct shape compiles a new "
        "executable. Needs a BucketPolicy so varlen inputs share a "
        "bounded set of compiled entries."),
    RuleInfo(
        "PTC005", "capture", "error", "ops.yaml shape spec inconsistent",
        "An op's declared `shape:` spec disagrees with its live fusion "
        "impl on sample avals (golden-run comparison), or a fusable op "
        "carries no spec / a spec decorates a non-fusable op — the "
        "abstract interpreter would plan capture regions from wrong "
        "shape/dtype arithmetic (the PTL005 pattern, for shapes)."),
    RuleInfo(
        "PTK001", "locks", "error", "lock-order cycle",
        "Two (or more) instrumented locks acquired in opposite nesting "
        "orders on different code paths: the classic AB/BA deadlock. "
        "Reported with both acquisition stacks."),
    RuleInfo(
        "PTK002", "locks", "warning", "lock held across device work",
        "An instrumented lock held while device work ran under it (a "
        "fusion flush / jitted executable), or held longer than the "
        "long-hold threshold: every other thread needing that lock "
        "stalls behind device latency."),
]}


@dataclass
class Diagnostic:
    """One finding. ``location`` is ``path:line`` for source rules, a
    runtime description (``fusion-dag: mean((x*y))``, ``lock:
    serving.submit``) otherwise."""

    rule: str
    location: str
    message: str
    severity: Optional[str] = None   # default: the rule's severity
    hint: str = ""
    data: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.severity is None:
            info = RULES.get(self.rule)
            self.severity = info.severity if info else "warning"

    def to_dict(self) -> Dict[str, Any]:
        d = {"rule": self.rule, "severity": self.severity,
             "location": self.location, "message": self.message}
        if self.hint:
            d["hint"] = self.hint
        if self.data:
            d["data"] = self.data
        return d

    def render(self) -> str:
        hint = f"\n      hint: {self.hint}" if self.hint else ""
        return (f"  [{self.rule}/{self.severity}] {self.location}: "
                f"{self.message}{hint}")


def sort_diagnostics(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(diags, key=lambda d: (severity_rank(d.severity),
                                        d.rule, d.location))
