"""Shape/dtype abstract interpreter for the static capture planner.

Whole-step capture (ROADMAP Fusion III) must prove, BEFORE tracing,
that a candidate region is shape-stable: that its recorded fusion-DAG /
SOT-segment ops, evaluated over abstract ``(shape, dtype)`` values,
produce one bounded signature set under a given
:class:`~paddle_tpu.jit.sot.BucketPolicy`. This module is that
interpreter:

- **Specs** — every op ops.yaml marks ``fusable:`` declares a
  ``shape:`` spec (one of ``op_registry.SHAPE_SPECS``) describing how
  its output aval follows from its input avals + node attrs:
  ``elementwise`` (shape and dtype preserved), ``broadcast`` (numpy
  broadcasting + dtype promotion), ``reduce`` (axis/keepdim/optional
  dtype attrs), ``matmul`` / ``linear`` (contraction arithmetic),
  ``cast`` (dtype from attrs), ``attention`` (q/k/v ``[B, S, H, D]``
  — the output follows the QUERY aval; flash_attention /
  flash_attention_segmented / ring_attention, so a transformer step
  plans through its attention instead of treating it as an opaque
  boundary). :func:`abstract_eval` evaluates one op.
- **Golden-run validation** — :func:`validate_specs` grades every
  declared spec against the LIVE fusion impl
  (``core.fusion.infer_output_aval`` — ``jax.eval_shape`` of the
  registered callable, through the same ``_aval_cache`` memo the flush
  path uses) on sample avals, both shape and dtype, plus both marker
  directions (fusable-without-spec, spec-without-fusable — load-time
  guarded, re-checked here so a hand-built table can't drift).
  Disagreements are **PTC005** (the PTL005 pattern, for shapes).
- **Program interpretation** — :func:`interpret_signature` replays a
  recorded fusion program signature over abstract values; and
  :func:`bucketed_leaf_signatures` enumerates the distinct compiled
  signatures a BucketPolicy admits for a dynamic axis — the "bounded
  set of executables" proof the capture plan cites for PTC004 rows.

Specs are validated on the inexact dtypes training actually runs
(float32/bfloat16, plus mixed-promotion pairs); integer-promotion
corners route through the live-impl ground truth rather than the spec.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .diagnostics import Diagnostic

__all__ = ["AVal", "abstract_eval", "validate_specs", "validate_op",
           "interpret_signature", "bucketed_leaf_signatures"]


class AVal(tuple):
    """Abstract value: ``(shape, dtype)``. A plain tuple subclass so
    signatures hash/compare structurally."""

    __slots__ = ()

    def __new__(cls, shape, dtype):
        return tuple.__new__(cls, (tuple(int(d) for d in shape),
                                   np.dtype(dtype)))

    @property
    def shape(self):
        return self[0]

    @property
    def dtype(self):
        return self[1]

    def __repr__(self):
        return f"AVal({list(self.shape)}, {self.dtype})"


def _promote(*dtypes) -> np.dtype:
    """JAX-style dtype promotion (jnp.promote_types over the inputs).
    Lazy import: the interpreter itself is host-only arithmetic."""
    import jax.numpy as jnp
    out = dtypes[0]
    for d in dtypes[1:]:
        out = jnp.promote_types(out, d)
    return np.dtype(out)


def _broadcast_shapes(*shapes) -> Optional[Tuple[int, ...]]:
    try:
        return tuple(int(d) for d in np.broadcast_shapes(*shapes))
    except ValueError:
        return None


def _attrs_dict(attrs) -> Dict[str, Any]:
    return dict(attrs) if attrs else {}


# -- per-spec evaluators ------------------------------------------------------

def _ew_eval(avals, attrs):
    """elementwise: unary, shape AND dtype preserved (the strongest
    invariant — a planner can propagate it with zero uncertainty)."""
    if len(avals) != 1:
        return None
    return AVal(avals[0].shape, avals[0].dtype)


def _bcast_eval(avals, attrs):
    """broadcast: n-ary elementwise with numpy broadcasting + dtype
    promotion (add/multiply/maximum/...)."""
    if not avals:
        return None
    shape = _broadcast_shapes(*[a.shape for a in avals])
    if shape is None:
        return None
    return AVal(shape, _promote(*[a.dtype for a in avals]))


def _reduce_eval(avals, attrs):
    """reduce: axis (None | int | tuple) / keepdim / optional dtype
    attrs — exactly the fuse_attrs the reduction wrappers pass."""
    if len(avals) != 1:
        return None
    a = avals[0]
    kw = _attrs_dict(attrs)
    axis = kw.get("axis")
    keepdim = bool(kw.get("keepdim", False))
    ndim = len(a.shape)
    if axis is None:
        axes = tuple(range(ndim))
    else:
        axes = axis if isinstance(axis, (tuple, list)) else (axis,)
        axes = tuple(ax + ndim if ax < 0 else ax for ax in axes)
        if any(not 0 <= ax < ndim for ax in axes) and ndim > 0:
            return None
    if ndim == 0:
        shape: Tuple[int, ...] = ()
    elif keepdim:
        shape = tuple(1 if i in axes else d
                      for i, d in enumerate(a.shape))
    else:
        shape = tuple(d for i, d in enumerate(a.shape)
                      if i not in axes)
    dtype = kw.get("dtype")
    return AVal(shape, np.dtype(dtype) if dtype is not None else a.dtype)


def _matmul_shape(sa, sb):
    """jnp.matmul shape arithmetic: 1-D operands get a dim prepended/
    appended (and dropped from the result), batch dims broadcast."""
    if not sa or not sb:
        return None  # 0-d operands don't contract
    a1 = len(sa) == 1
    b1 = len(sb) == 1
    if a1:
        sa = (1,) + sa
    if b1:
        sb = sb + (1,)
    if sa[-1] != sb[-2]:
        return None
    batch = _broadcast_shapes(sa[:-2], sb[:-2])
    if batch is None:
        return None
    out = batch + (sa[-2], sb[-1])
    if b1:
        out = out[:-1]
    if a1:
        out = out[:-1] if b1 else out[:-2] + out[-1:]
    return out


def _matmul_eval(avals, attrs):
    """matmul: transpose_x/transpose_y attrs swap the last two dims of
    >1-D operands (the _matmul_impl contract), then jnp.matmul rules."""
    if len(avals) != 2:
        return None
    kw = _attrs_dict(attrs)
    sa, sb = avals[0].shape, avals[1].shape
    if kw.get("transpose_x") and len(sa) > 1:
        sa = sa[:-2] + (sa[-1], sa[-2])
    if kw.get("transpose_y") and len(sb) > 1:
        sb = sb[:-2] + (sb[-1], sb[-2])
    shape = _matmul_shape(sa, sb)
    if shape is None:
        return None
    return AVal(shape, _promote(avals[0].dtype, avals[1].dtype))


def _linear_eval(avals, attrs):
    """linear: x[..., in] @ w[in, out] (+ optional b broadcast over the
    result) with paddle's [in, out] weight layout."""
    if len(avals) not in (2, 3):
        return None
    x, w = avals[0], avals[1]
    if len(w.shape) != 2 or not x.shape or x.shape[-1] != w.shape[0]:
        return None
    shape = x.shape[:-1] + (w.shape[1],)
    dts = [x.dtype, w.dtype]
    if len(avals) == 3:
        b = avals[2]
        shape2 = _broadcast_shapes(shape, b.shape)
        if shape2 is None:
            return None
        shape = shape2
        dts.append(b.dtype)
    return AVal(shape, _promote(*dts))


def _cast_eval(avals, attrs):
    """cast: shape preserved, dtype from the node's `dtype` attr."""
    if len(avals) != 1:
        return None
    kw = _attrs_dict(attrs)
    if kw.get("dtype") is None:
        return None
    return AVal(avals[0].shape, np.dtype(kw["dtype"]))


def _attention_eval(avals, attrs):
    """attention: q/k/v ``[B, S, H, D]`` (plus optional integer
    segment ids ``[B, S]`` — the varlen-packing variant). The output
    follows the QUERY aval exactly: same shape, same dtype (the
    in-tree kernels take uniform q/k/v dtypes and cast the context
    product back to the query's). KV length may differ from the query
    length (cache decode verifies short queries over long keys)."""
    if len(avals) not in (3, 4):
        return None
    q, k, v = avals[0], avals[1], avals[2]
    if len(q.shape) != 4 or k.shape != v.shape or len(k.shape) != 4:
        return None
    # batch, heads and head_dim must agree; only the sequence axis may
    # differ between query and key/value
    if (q.shape[0], q.shape[2], q.shape[3]) != \
            (k.shape[0], k.shape[2], k.shape[3]):
        return None
    if len(avals) == 4:
        seg = avals[3]
        if seg.dtype.kind not in "iu" or \
                seg.shape != (q.shape[0], q.shape[1]):
            return None
    return AVal(q.shape, q.dtype)


_EVALUATORS = {
    "elementwise": _ew_eval,
    "broadcast": _bcast_eval,
    "reduce": _reduce_eval,
    "matmul": _matmul_eval,
    "linear": _linear_eval,
    "cast": _cast_eval,
    "attention": _attention_eval,
}


def _spec_of(op: str) -> Optional[str]:
    from ..ops.op_registry import OP_TABLE
    info = OP_TABLE.get(op)
    return info.get("shape_spec") if info else None


def abstract_eval(op: str, avals: Sequence[AVal],
                  attrs=None) -> Optional[AVal]:
    """Evaluate one op over abstract values via its declared ``shape:``
    spec. Returns None when the op has no spec or the spec rejects the
    inputs (rank/contraction mismatch) — callers fall back to the live
    ground truth (``fusion.infer_output_aval``)."""
    spec = _spec_of(op)
    if spec is None:
        return None
    avals = [a if isinstance(a, AVal) else AVal(a[0], a[1])
             for a in avals]
    return _EVALUATORS[spec](avals, attrs)


# -- golden-run validation (PTC005) ------------------------------------------

# sample avals per spec id: the inexact training domain plus mixed-
# promotion pairs; (avals, attrs) cases, each graded abstract-vs-live
_F32 = np.dtype("float32")
_BF16 = np.dtype("bfloat16")


def _sample_cases(op: str, spec: str) -> List[Tuple[list, Any]]:
    if spec == "elementwise":
        return [([((3, 4), _F32)], None), ([((2, 1, 5), _BF16)], None),
                ([((), _F32)], None)]
    if spec == "broadcast":
        return [([((3, 4), _F32), ((4,), _F32)], None),
                ([((3, 4), _BF16), ((3, 4), _F32)], None),
                ([((3, 1), _F32), ((1, 5), _BF16)], None)]
    if spec == "reduce":
        if op == "squared_l2_norm":   # fixed full reduction, no attrs
            return [([((3, 4), _F32)], ()), ([((5,), _BF16)], ())]
        cases = []
        for axis, keepdim in ((None, False), (1, False), (1, True),
                              ((0, 2), False), (-1, True)):
            av = ((2, 3, 4), _F32) if isinstance(axis, tuple) or axis \
                else ((3, 4), _F32)
            attrs = (("axis", axis), ("keepdim", keepdim))
            if op in ("sum", "prod"):   # their wrappers carry a dtype
                attrs = (("axis", axis), ("dtype", None),
                         ("keepdim", keepdim))
            cases.append(([av], attrs))
        if op in ("sum", "prod"):
            cases.append(([((3, 4), _BF16)],
                          (("axis", None), ("dtype", _F32),
                           ("keepdim", False))))
        return cases
    if spec == "matmul":
        return [
            ([((3, 4), _F32), ((4, 5), _F32)],
             (("transpose_x", False), ("transpose_y", False))),
            ([((4, 3), _F32), ((4, 5), _BF16)],
             (("transpose_x", True), ("transpose_y", False))),
            ([((2, 3, 4), _BF16), ((2, 4, 5), _BF16)],
             (("transpose_x", False), ("transpose_y", False))),
            ([((4,), _F32), ((4, 5), _F32)],
             (("transpose_x", False), ("transpose_y", False))),
            ([((3, 4), _F32), ((4,), _F32)],
             (("transpose_x", False), ("transpose_y", False))),
        ]
    if spec == "linear":
        return [([((2, 3, 4), _F32), ((4, 5), _F32)], ()),
                ([((2, 4), _BF16), ((4, 5), _BF16), ((5,), _BF16)], ()),
                ([((2, 4), _BF16), ((4, 5), _F32), ((5,), _F32)], ())]
    if spec == "cast":
        return [([((3, 4), _F32)], (("dtype", _BF16),)),
                ([((2,), _BF16)], (("dtype", _F32),)),
                ([((3,), _F32)], (("dtype", np.dtype("int32")),))]
    if spec == "attention":
        # uniform q/k/v dtypes (the kernel contract); graded through
        # the registered parametric impls (the real entry points)
        _I32 = np.dtype("int32")
        if op == "flash_attention_segmented":
            return [([((2, 8, 4, 16), _F32)] * 3 + [((2, 8), _I32)],
                     ()),
                    ([((1, 16, 2, 8), _BF16)] * 3 + [((1, 16), _I32)],
                     ())]
        cases = [([((2, 8, 4, 16), _F32)] * 3, ()),
                 ([((1, 16, 2, 8), _BF16)] * 3, ())]
        if op == "flash_attention":
            # cache-decode geometry: 1 query row over a longer KV
            cases.append(([((2, 1, 4, 16), _F32),
                           ((2, 8, 4, 16), _F32),
                           ((2, 8, 4, 16), _F32)], ()))
        return cases
    return []


def validate_op(op: str, spec: Optional[str] = None) -> List[Diagnostic]:
    """Grade one op's shape spec against its live fusion impl on the
    sample avals (PTC005 on any disagreement). ``spec`` overrides the
    declared one — the self-check seeds a deliberately wrong spec this
    way to prove the detector fires."""
    from ..core import fusion
    declared = _spec_of(op)
    spec = spec or declared
    if spec is None:
        return []
    evaluator = _EVALUATORS.get(spec)
    if evaluator is None:
        return [Diagnostic(
            "PTC005", f"ops/ops.yaml: {op}",
            f"op `{op}` declares shape spec {spec!r} which "
            f"analysis/shapes.py implements no evaluator for",
            hint="pick a spec from op_registry.SHAPE_SPECS")]
    diags: List[Diagnostic] = []
    # sample from the DECLARED spec (its cases carry the op's real
    # attrs, which the live impl needs) and grade with the spec under
    # test — so a wrong override is judged on the op's true domain
    for avals, attrs in _sample_cases(op, declared or spec):
        avals = [AVal(s, d) for s, d in avals]
        want = fusion.infer_output_aval(op, avals, attrs)
        if want is None:
            continue  # impl unregistered/rejecting: PTL005's domain
        got = evaluator(avals, attrs)
        want_aval = AVal(want[0], want[1])
        if got is None or tuple(got) != tuple(want_aval):
            diags.append(Diagnostic(
                "PTC005", f"ops/ops.yaml: {op}",
                f"shape spec `{spec}` predicts "
                f"{got!r} for inputs {avals} attrs {attrs!r}, but the "
                f"live impl produces {want_aval!r}",
                hint="fix the spec (or the impl) — the capture planner "
                     "plans executables from this arithmetic; the two "
                     "must agree exactly"))
            break  # one counterexample per op keeps reports readable
    return diags


def validate_specs() -> List[Diagnostic]:
    """The PTC005 sweep: both marker directions plus the golden-run
    agreement check for every declared spec."""
    from ..ops.op_registry import OP_TABLE
    diags: List[Diagnostic] = []
    for name, info in sorted(OP_TABLE.items()):
        spec = info.get("shape_spec")
        fusable = info.get("fusable")
        if fusable and not spec:
            diags.append(Diagnostic(
                "PTC005", f"ops/ops.yaml: {name}",
                f"op `{name}` is marked fusable:{fusable!r} but carries "
                f"no `shape:` spec — the abstract interpreter cannot "
                f"plan regions containing it",
                hint="declare one of op_registry.SHAPE_SPECS"))
            continue
        if spec and not fusable:
            diags.append(Diagnostic(
                "PTC005", f"ops/ops.yaml: {name}",
                f"op `{name}` declares shape spec `{spec}` but is not "
                f"fusable — dead declaration (the interpreter only "
                f"walks fusable regions)",
                hint="mark the op fusable, or drop the spec"))
            continue
        if spec:
            diags.extend(validate_op(name, spec))
    return diags


# -- program interpretation ---------------------------------------------------

def interpret_signature(sig) -> Dict[str, Any]:
    """Replay a recorded fusion program signature ``(nodes, leaf_descs,
    out_idx, diff_idx)`` (core.fusion's structural cache key) over
    abstract values. Every node is evaluated through its declared spec
    AND the live impl; a disagreement is a PTC005 diagnostic (the
    recorded-program variant of the golden run). Returns
    ``{"outputs": [AVal...], "node_avals": [...], "diagnostics": [...]}``.
    """
    from ..core import fusion
    nodes, leaf_descs = sig[0], sig[1]
    out_idx = sig[2] if len(sig) > 2 else ()
    leaves = [AVal(d[1], d[2]) for d in leaf_descs]
    env: List[Optional[AVal]] = []
    diags: List[Diagnostic] = []
    for op, children, attrs in nodes:
        child_avals = []
        ok = True
        for kind, j, _ad in children:
            v = env[j] if kind == "n" else leaves[j]
            if v is None:
                ok = False
                break
            child_avals.append(v)
        if not ok:
            env.append(None)
            continue
        spec_out = abstract_eval(op, child_avals, attrs)
        live = fusion.infer_output_aval(op, child_avals, attrs)
        live_out = AVal(live[0], live[1]) if live is not None else None
        if spec_out is not None and live_out is not None and \
                tuple(spec_out) != tuple(live_out):
            diags.append(Diagnostic(
                "PTC005", f"fusion-dag: {op}",
                f"spec predicts {spec_out!r} but the live impl gives "
                f"{live_out!r} for inputs {child_avals} attrs {attrs!r}",
                hint="the recorded program and the declared spec "
                     "disagree — fix ops.yaml before trusting plans "
                     "over this region"))
        env.append(spec_out if spec_out is not None else live_out)
    outs = [env[i] for i in out_idx] if out_idx else list(env[-1:])
    return {"outputs": outs, "node_avals": env, "diagnostics": diags}


def bucketed_leaf_signatures(shape, dynamic_axes: Dict[int, Any],
                             max_size: int,
                             dtype="float32") -> List[Tuple]:
    """The bounded-executables proof for one leaf: enumerate the
    distinct ``(shape, dtype)`` signatures a bucket policy admits when
    each axis in ``dynamic_axes`` (axis -> buckets, a sorted list or
    "pow2" — BucketPolicy's vocabulary) sweeps sizes ``1..max_size``.
    Without a policy that sweep compiles ``max_size`` distinct
    executables per axis; with it, ``len(result)`` — the number the
    capture plan quotes for a PTC004 row."""
    from ..jit.sot import BucketPolicy
    policy = BucketPolicy({})
    per_axis: Dict[int, List[int]] = {}
    for axis, buckets in dynamic_axes.items():
        per_axis[axis] = sorted(
            {policy.bucket_of(s, buckets)
             for s in range(1, int(max_size) + 1)})
    sigs = {tuple(shape)}
    for axis, sizes in per_axis.items():
        sigs = {s[:axis] + (n,) + s[axis + 1:]
                for s in sigs for n in sizes}
    return sorted((s, np.dtype(dtype).name) for s in sigs)
