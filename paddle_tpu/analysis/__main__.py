"""``python -m paddle_tpu.analysis`` — the analysis plane's CLI.

Default: lint the package and print the report (exit 1 on error-severity
findings — the CI contract tests/test_lint_clean.py mirrors in-process).

Options:
  --self-check   seed one bug per analyzer, assert each rule fires
                 (the bench --dispatch-only smoke); exit 1 on failure
  --rules        print the rule table (ids, analyzers, severities)
  --json         emit the report as JSON instead of text
"""
from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--rules" in argv:
        from .report import rules_table
        print(rules_table())
        return 0
    if "--self-check" in argv:
        from .report import self_check
        return 0 if self_check(verbose=True)["ok"] else 1
    from .report import report
    rep = report()
    if "--json" in argv:
        print(json.dumps(rep.to_dict(), indent=2, default=str))
    else:
        print(rep.render())
    return 1 if rep.errors else 0


if __name__ == "__main__":
    sys.exit(main())
