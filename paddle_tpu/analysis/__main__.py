"""``python -m paddle_tpu.analysis`` — the analysis plane's CLI.

Default: lint the package and print the report (exit 1 on error-severity
findings — the CI contract tests/test_lint_clean.py mirrors in-process).

Options:
  --self-check    seed one bug per analyzer, assert each rule fires
                  (the bench --dispatch-only smoke); exit 1 on failure
  --rules         print the rule table (ids, analyzers, severities)
  --capture-plan  static capture plan over the repo's own step
                  functions (hapi train/eval step, serving decode step,
                  bench step) — the whole-step-capture work list; exit
                  1 on unaccounted breaks or error-severity findings
  --json          emit the report/plan as JSON instead of text
"""
from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--rules" in argv:
        from .report import rules_table
        print(rules_table())
        return 0
    if "--self-check" in argv:
        from .report import self_check
        return 0 if self_check(verbose=True)["ok"] else 1
    if "--capture-plan" in argv:
        from .planner import plan_repo_steps
        plan = plan_repo_steps()
        if "--json" in argv:
            print(json.dumps(plan.to_dict(), indent=2, default=str))
        else:
            print(plan.render())
        bad = not plan.consistent() or any(
            d.severity == "error" for d in plan.diagnostics)
        return 1 if bad else 0
    from .report import report
    rep = report()
    if "--json" in argv:
        print(json.dumps(rep.to_dict(), indent=2, default=str))
    else:
        print(rep.render())
    return 1 if rep.errors else 0


if __name__ == "__main__":
    sys.exit(main())
