"""Program auditor: run a callable in recording mode and produce a
*capture report* — the planning input for whole-step program capture.

The roadmap's Fusion III item needs to know, for one train (or decode)
step, exactly where and why execution breaks out of capture. This
module answers that by instrumenting the seams the runtime already
exposes and replaying the step:

- **Flush boundaries** — every fusion-chain flush with its reason
  (host_read / op_boundary / backward / cap / ...) AND its origin call
  site (``core.fusion._flush_observer``), aggregated into top-N flush
  sites.
- **Host syncs** — every device→host materialization
  (``.numpy()``/``.item()``/``tolist``/``__array__``) with call-site
  attribution (``core.tensor._sync_hook``) → **PTA001**.
- **Donations** — every buffer-donating fused optimizer step
  (``optimizer.fused_step._donation_observer``), plus a post-run sweep
  for live Tensor handles whose buffer XLA has deleted
  (use-after-donate) → **PTA002**.
- **Recompile churn** — program-cache compiles inside the measured
  window (``fusion._program_observer``, dispatch pair builds, whole-step
  ``jit`` rebuilds) and unhashable-static call sites → **PTA003**.

Protocol: ``audit(fn)`` runs ``fn`` ``warmup`` times (default 2 — the
compile-on-second-sighting policy means a steady-state structure has
compiled by then), then records ONE measured run. A steady-state step
should show zero compiles in the measured window; every one that
remains is churn.
"""
from __future__ import annotations

import gc
from typing import Any, Callable, Dict, List

from .diagnostics import Diagnostic, sort_diagnostics
from .locks import caller_site

__all__ = ["Auditor", "CaptureReport", "audit"]

_SKIP_SUFFIXES = ("analysis/auditor.py", "analysis/locks.py",
                  "core/tensor.py", "core/fusion.py", "core/autograd.py")


def _origin() -> str:
    """``pkg/file.py:line`` of the nearest frame outside the recording
    machinery (fusion keeps its own copy — core must not depend on the
    analysis package)."""
    return caller_site(_SKIP_SUFFIXES)


def _sig_summary(sig) -> Dict[str, Any]:
    """Human-readable summary of a fusion program signature: the op
    chain and the leaf shapes (the part that churns under shape
    polymorphism)."""
    nodes, leaf_descs = sig[0], sig[1]
    return {"ops": [n[0] for n in nodes],
            "leaf_shapes": [list(d[1]) for d in leaf_descs]}


def _is_deleted(buf) -> bool:
    fn = getattr(buf, "is_deleted", None)
    if fn is None:
        return False
    try:
        return bool(fn())
    except Exception:  # noqa: BLE001 — a dead runtime reads as deleted
        return False


class CaptureReport:
    """Everything one measured run revealed. ``diagnostics`` carry the
    judgement; the event lists carry the full attribution (the Fusion
    III planning data)."""

    def __init__(self):
        self.flushes: List[Dict[str, Any]] = []
        self.syncs: List[Dict[str, Any]] = []
        self.donations: List[Dict[str, Any]] = []
        self.fusion_compiles: List[Dict[str, Any]] = []
        self.pair_builds: List[str] = []
        self.step_builds: List[str] = []
        self.unhashable_statics: Dict[str, int] = {}
        self.use_after_donate: List[Dict[str, Any]] = []
        self.diagnostics: List[Diagnostic] = []
        self.warmup_runs = 0
        self.result: Any = None

    # -- aggregation -----------------------------------------------------
    def flush_sites(self, top_n: int = 10) -> List[Dict[str, Any]]:
        """Top-N (origin, reason) flush sites by count — replaces the
        reason-only counters as the capture-planning input."""
        agg: Dict[tuple, int] = {}
        for ev in self.flushes:
            key = (ev["origin"], ev["reason"])
            agg[key] = agg.get(key, 0) + 1
        rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top_n]
        return [{"site": k[0], "reason": k[1], "count": v}
                for k, v in rows]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "flushes": self.flushes,
            "flush_sites": self.flush_sites(),
            "syncs": self.syncs,
            "donations": self.donations,
            "fusion_compiles": self.fusion_compiles,
            "pair_builds": self.pair_builds,
            "step_builds": self.step_builds,
            "unhashable_statics": dict(self.unhashable_statics),
            "use_after_donate": self.use_after_donate,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }

    def render(self) -> str:
        lines = ["capture report",
                 f"  flush boundaries: {len(self.flushes)}   host syncs: "
                 f"{len(self.syncs)}   donations: {len(self.donations)}   "
                 f"measured-window compile/first-run events: "
                 f"{len(self.fusion_compiles) + len(self.pair_builds) + len(self.step_builds)}"]
        if self.flushes:
            lines.append("  top flush sites (site, reason, count):")
            for row in self.flush_sites():
                lines.append(f"    {row['site']:<46} {row['reason']:<18} "
                             f"x{row['count']}")
        if self.syncs:
            lines.append("  host syncs:")
            agg: Dict[tuple, int] = {}
            for ev in self.syncs:
                agg[(ev["origin"], ev["kind"])] = \
                    agg.get((ev["origin"], ev["kind"]), 0) + 1
            for (site, kind), n in sorted(agg.items(), key=lambda kv: -kv[1]):
                lines.append(f"    {site:<46} {kind:<12} x{n}")
        if self.donations:
            total = sum(d["nbytes"] for d in self.donations)
            lines.append(f"  donations: {len(self.donations)} fused steps, "
                         f"{total} bytes donated in place")
        if self.unhashable_statics:
            lines.append("  unhashable-static call sites (run un-jitted "
                         "every call — recompile-risk inventory):")
            for fn_name, n in sorted(self.unhashable_statics.items(),
                                     key=lambda kv: -kv[1]):
                lines.append(f"    {fn_name:<46} x{n}")
        if self.diagnostics:
            lines.append("  diagnostics:")
            for d in self.diagnostics:
                lines.append(d.render())
        else:
            lines.append("  diagnostics: none")
        return "\n".join(lines)


class Auditor:
    """Context manager that installs the recording hooks (chaining any
    previously installed observer, e.g. a SOT tracer or an active lock
    auditor) and collects events into a :class:`CaptureReport`."""

    def __init__(self):
        self.report = CaptureReport()
        self._recording = False
        self._saved: Dict[str, Any] = {}

    # -- event handlers --------------------------------------------------
    def _on_flush(self, reason, nops, pkind, origin):
        if self._recording:
            self.report.flushes.append(
                {"reason": reason, "ops": nops, "kind": pkind,
                 "origin": origin})

    def _on_program(self, sig, event):
        if self._recording and event in ("compile", "first"):
            entry = _sig_summary(sig)
            entry["event"] = event
            self.report.fusion_compiles.append(entry)

    def _on_sync(self, t, kind):
        if not self._recording:
            return
        buf = t._buf
        site = _origin()
        self.report.syncs.append({
            "kind": kind, "origin": site,
            "shape": list(t.shape), "dtype": str(t.dtype)})
        if buf is not None and _is_deleted(buf):
            self.report.use_after_donate.append({
                "origin": site, "kind": kind, "shape": list(t.shape),
                "detail": "host read of a donated (deleted) buffer"})

    def _on_dispatch(self, event, fn):
        if not self._recording:
            return
        name = getattr(fn, "__name__", repr(fn))
        if event == "pair_build":
            self.report.pair_builds.append(name)
        elif event == "unhashable_static":
            self.report.unhashable_statics[name] = \
                self.report.unhashable_statics.get(name, 0) + 1

    def _on_donation(self, opt, prep, mode):
        from . import locks as _locks
        la = _locks.active_auditor()
        if la is not None:
            la.note_device_op("fused_optimizer_step")
        if not self._recording:
            return
        labels = [p.name or f"param{i}"
                  for i, p in enumerate(prep.params)]
        self.report.donations.append({
            "mode": mode, "nbytes": prep.nbytes,
            "params": labels[:8] + (["..."] if len(labels) > 8 else []),
            "count": len(labels),
            # the observer fires inside the fused-step plane; skip past
            # it (and Optimizer.step) to the user's call site
            "origin": caller_site(_SKIP_SUFFIXES + (
                "optimizer/fused_step.py", "optimizer/optimizer.py"))})

    def _on_step_build(self, kind):
        if self._recording:
            self.report.step_builds.append(kind)

    # -- hook install/remove ---------------------------------------------
    def __enter__(self):
        from ..core import fusion, tensor, autograd
        from ..optimizer import fused_step
        from ..jit import api as jit_api
        self._mods = (fusion, tensor, autograd, fused_step, jit_api)
        saved = self._saved
        saved["flush"] = fusion._flush_observer
        saved["program"] = fusion._program_observer
        saved["sync"] = tensor._sync_hook
        saved["dispatch"] = autograd._dispatch_observer
        saved["donation"] = fused_step._donation_observer
        saved["build"] = jit_api._build_observer

        def chain(mine, prev):
            if prev is None:
                return mine

            def both(*a, **kw):
                mine(*a, **kw)
                prev(*a, **kw)
            return both

        fusion._flush_observer = chain(self._on_flush, saved["flush"])
        fusion._program_observer = chain(self._on_program,
                                         saved["program"])
        tensor._sync_hook = chain(self._on_sync, saved["sync"])
        autograd._dispatch_observer = chain(self._on_dispatch,
                                            saved["dispatch"])
        fused_step._donation_observer = chain(self._on_donation,
                                              saved["donation"])
        jit_api._build_observer = chain(self._on_step_build,
                                        saved["build"])
        return self

    def __exit__(self, *exc):
        fusion, tensor, autograd, fused_step, jit_api = self._mods
        fusion._flush_observer = self._saved["flush"]
        fusion._program_observer = self._saved["program"]
        tensor._sync_hook = self._saved["sync"]
        autograd._dispatch_observer = self._saved["dispatch"]
        fused_step._donation_observer = self._saved["donation"]
        jit_api._build_observer = self._saved["build"]
        return False

    # -- analysis ---------------------------------------------------------
    def scan_use_after_donate(self) -> None:
        """Post-run sweep: any LIVE Tensor whose device buffer XLA has
        deleted (a donated input nobody rebound) is a read-waiting-to-
        crash. Generalizes the fused step's copy-on-donate alias
        registry from prevention to detection."""
        from ..core.tensor import Tensor
        gc.collect()  # dead handles can't be read; scan the live ones
        for obj in gc.get_objects():
            if type(obj) is not Tensor and not isinstance(obj, Tensor):
                continue
            buf = getattr(obj, "_buf", None)
            if buf is not None and _is_deleted(buf):
                self.report.use_after_donate.append({
                    "origin": f"tensor {obj.name or hex(id(obj))}",
                    "kind": "live_handle", "shape": list(buf.shape),
                    "detail": "live Tensor handle wraps a donated "
                              "(deleted) buffer"})

    def finalize(self) -> CaptureReport:
        rep = self.report
        self.scan_use_after_donate()
        diags: List[Diagnostic] = []
        # PTA001: one diagnostic per distinct sync site
        sites: Dict[tuple, int] = {}
        for ev in rep.syncs:
            sites[(ev["origin"], ev["kind"])] = \
                sites.get((ev["origin"], ev["kind"]), 0) + 1
        for (site, kind), n in sorted(sites.items()):
            diags.append(Diagnostic(
                "PTA001", site,
                f"device->host sync via .{kind} x{n} in the measured "
                f"step",
                hint="keep the value on device (device-resident "
                     "counters / masked updates), or batch the fetch "
                     "outside the step"))
        # PTA002: reads of deleted buffers + live handles wrapping them
        for ev in rep.use_after_donate:
            diags.append(Diagnostic(
                "PTA002", ev["origin"],
                f"use-after-donate: {ev['detail']} "
                f"(shape {ev.get('shape')})",
                hint="copy the buffer before donating (the fused "
                     "step's copy-on-donate), or drop the stale handle "
                     "before the donating step runs"))
        # PTA003: compiles inside the measured (steady-state) window
        if rep.fusion_compiles:
            by_ops: Dict[tuple, List[Dict[str, Any]]] = {}
            for c in rep.fusion_compiles:
                by_ops.setdefault(tuple(c["ops"]), []).append(c)
            for ops, entries in sorted(by_ops.items()):
                shapes = {tuple(map(tuple, e["leaf_shapes"]))
                          for e in entries}
                poly = (f" across {len(shapes)} distinct leaf-shape "
                        f"sets (shape-polymorphic call site)"
                        if len(shapes) > 1 else "")
                # "first" = first sighting, runs UN-jitted (compile-on-
                # second-sighting) — a cache miss, not a compile; say so
                # or the reader hunts for a compile that never happened
                ncomp = sum(1 for e in entries if e["event"] == "compile")
                parts = []
                if ncomp:
                    parts.append(f"compiled {ncomp}x")
                if len(entries) - ncomp:
                    parts.append(f"first-sighted {len(entries) - ncomp}x "
                                 f"(ran un-jitted)")
                diags.append(Diagnostic(
                    "PTA003", "fusion-dag: " + "->".join(ops),
                    f"fusion program {' + '.join(parts)} in the "
                    f"measured window{poly}",
                    hint="steady state should hit the program cache; "
                         "pad/bucket dynamic shapes or hoist the "
                         "changing static out of the chain"))
        if rep.pair_builds:
            agg: Dict[str, int] = {}
            for n in rep.pair_builds:
                agg[n] = agg.get(n, 0) + 1
            detail = ", ".join(f"{k} x{v}" for k, v in sorted(agg.items()))
            diags.append(Diagnostic(
                "PTA003", "dispatch.jit_pair_cache",
                f"jit pair(s) compiled in the measured window: {detail}",
                hint="a steady-state step builds no new pairs; check "
                     "for per-call static values entering the key"))
        if rep.step_builds:
            diags.append(Diagnostic(
                "PTA003", "jit.whole_step",
                f"whole-step program rebuilt in the measured window: "
                f"{', '.join(rep.step_builds)}",
                hint="TrainStep/StaticFunction should build once; a "
                     "rebuild per step recompiles the full graph"))
        rep.diagnostics = sort_diagnostics(diags)

        from ..observability import metrics as _om
        _om.counter("analysis.audits_total",
                    "Capture audits run by paddle_tpu.analysis").inc()
        cd = _om.counter(
            "analysis.diagnostics_total",
            "Diagnostics emitted by the analysis plane, by rule")
        for d in rep.diagnostics:
            cd.inc(rule=d.rule)
        return rep


def audit(fn: Callable, *args, warmup: int = 2,
          **kwargs) -> CaptureReport:
    """Run ``fn(*args, **kwargs)`` in recording mode and return its
    :class:`CaptureReport`.

    ``warmup`` extra runs precede the measured one (default 2: the
    fusion plane and the eager pair cache both compile on SECOND
    sighting, so the measured window of a steady-state step is
    compile-free; set 0 to audit cold-start behavior)."""
    with Auditor() as a:
        for _ in range(max(int(warmup), 0)):
            fn(*args, **kwargs)
            a.report.warmup_runs += 1
        a._recording = True
        try:
            a.report.result = fn(*args, **kwargs)
        except BaseException as e:
            # a real use-after-donate CRASHES the measured run — the
            # attribution recorded up to that point is exactly what the
            # audit exists to provide, so finalize and ship it on the
            # exception instead of discarding it
            a._recording = False
            e.capture_report = a.finalize()
            raise
        finally:
            a._recording = False
        return a.finalize()
