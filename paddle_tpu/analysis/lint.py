"""Source linter: an AST rule engine (stdlib ``ast`` only) with
repo-specific rules for the hazards this codebase has actually hit.

Rules (ids + defaults in ``analysis.diagnostics.RULES``):

- **PTL001** — implicit host sync in library code: ``.numpy()`` /
  ``.item()`` / ``.tolist()`` calls inside ``paddle_tpu/``. Each is a
  device→host round trip; on a hot path it also flushes the fusion DAG.
  Deliberate syncs (structural args that must be host-static for XLA,
  the host-interop API itself) are allowlisted with a justification.
- **PTL002** — registered flag never read: a ``define_flag`` whose name
  is read nowhere (``_registry[...]`` / ``flag_value(...)`` /
  ``get_flags``): dead surface, or a documented behavior that silently
  doesn't exist (the state ``FLAGS_benchmark`` and
  ``FLAGS_retain_grad_for_all_tensor`` were in until this linter).
- **PTL003** — unguarded global registry mutation: a structural
  mutation (``del``/``pop``/``popitem``/``clear``) of a module-level
  container inside a function with no enclosing ``with <lock>``.
  Single-assignment memo inserts are GIL-atomic and not flagged; the
  sweep-while-iterate patterns this rule exists for are not.
- **PTL004** — bare ``except:``: swallows KeyboardInterrupt/SystemExit
  and the fault-injection harness's BaseException kill-points.
- **PTL005** — ops.yaml ``fusable`` marker inconsistent with the live
  fusion impl registries (an op the DAG could never actually fuse, or a
  registration ops.yaml doesn't admit). Data-driven: compares the
  loaded ``OP_TABLE`` against ``fusion._IMPLS``/``_PIMPLS``.

Suppression is explicit and justified, never global: a checked-in
allowlist (``analysis/allowlist.py``) of (rule, path-glob, reason)
entries, plus inline ``# lint-allow: PTLxxx reason`` pragmas for
single sites. Suppressed findings are counted and reported, not
discarded silently.
"""
from __future__ import annotations

import ast
import fnmatch
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, sort_diagnostics

__all__ = ["lint", "LintResult", "iter_source_files", "REPO_ROOT"]

_HERE = os.path.dirname(os.path.abspath(__file__))
PKG_ROOT = os.path.dirname(_HERE)                    # .../paddle_tpu
REPO_ROOT = os.path.dirname(PKG_ROOT)

_MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque", "WeakValueDictionary", "WeakKeyDictionary"}
_STRUCTURAL_MUTATORS = {"clear", "pop", "popitem"}
_SYNC_ATTRS = {"numpy", "item", "tolist"}


def iter_source_files(root: Optional[str] = None) -> List[str]:
    """Every .py file under the package (default: paddle_tpu/)."""
    root = root or PKG_ROOT
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def _rel(path: str) -> str:
    p = os.path.abspath(path).replace("\\", "/")
    root = REPO_ROOT.replace("\\", "/") + "/"
    return p[len(root):] if p.startswith(root) else p


def _terminal_name(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _expr_mentions_lock(node) -> bool:
    for sub in ast.walk(node):
        n = None
        if isinstance(sub, ast.Name):
            n = sub.id
        elif isinstance(sub, ast.Attribute):
            n = sub.attr
        if n is not None and "lock" in n.lower():
            return True
    return False


class _FileVisitor(ast.NodeVisitor):
    """One pass per file. Collects per-file findings and the cross-file
    facts (flag defines/reads) the repo-level rules need."""

    def __init__(self, relpath: str, facts: "RepoFacts"):
        self.relpath = relpath
        self.facts = facts
        self.diags: List[Diagnostic] = []
        self._module_mutables: Set[str] = set()
        self._with_lock_depth = 0
        self._func_depth = 0
        self._collect_module_mutables_done = False

    # -- helpers ---------------------------------------------------------
    def _loc(self, node) -> str:
        return f"{self.relpath}:{node.lineno}"

    def _collect_module_mutables(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set))
            if not is_mut and isinstance(value, ast.Call):
                is_mut = _terminal_name(value.func) in _MUTABLE_CTORS
            if not is_mut:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    self._module_mutables.add(t.id)

    # -- traversal -------------------------------------------------------
    def visit_Module(self, node):
        self._collect_module_mutables(node)
        self.generic_visit(node)

    def visit_With(self, node):
        locked = any(_expr_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self._with_lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_lock_depth -= 1

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.diags.append(Diagnostic(
                "PTL004", self._loc(node),
                "bare `except:` — also catches KeyboardInterrupt/"
                "SystemExit and fault-injection kill-points",
                hint="catch Exception (or the specific error); bare "
                     "handlers around device code absorb injected "
                     "crashes the tests rely on"))
        self.generic_visit(node)

    def visit_Delete(self, node):
        if self._func_depth and not self._with_lock_depth:
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in self._module_mutables:
                    self.diags.append(Diagnostic(
                        "PTL003", self._loc(node),
                        f"del on module-level registry "
                        f"`{t.value.id}` outside any lock",
                        hint="guard the sweep with the module's lock, "
                             "or justify the lock-free design in the "
                             "allowlist"))
        self.generic_visit(node)

    def visit_Call(self, node):
        func = node.func
        # PTL001: host-sync attribute calls
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS \
                and not node.args and not node.keywords:
            recv_ok = True
            if func.attr in ("item", "tolist"):
                recv = func.value
                if isinstance(recv, ast.Call):
                    # np.<fn>(...).item() is host->host numpy — but a
                    # chained device call (loss.mean().item()) is still
                    # a sync and must not slip through
                    f = recv.func
                    numpy_recv = (isinstance(f, ast.Attribute)
                                  and isinstance(f.value, ast.Name)
                                  and f.value.id in ("np", "numpy"))
                    recv_ok = not numpy_recv and _terminal_name(f) \
                        not in ("asarray", "array")
                else:
                    recv_ok = isinstance(recv, (ast.Name, ast.Attribute))
            if recv_ok:
                self.diags.append(Diagnostic(
                    "PTL001", self._loc(node),
                    f".{func.attr}() — implicit device->host sync in "
                    f"library code",
                    hint="keep the value on device, or allowlist with "
                         "a justification if the sync is the API "
                         "contract (host-static structural args, host "
                         "interop)"))
        # PTL003: structural mutators on module registries
        if isinstance(func, ast.Attribute) and \
                func.attr in _STRUCTURAL_MUTATORS and \
                isinstance(func.value, ast.Name) and \
                func.value.id in self._module_mutables and \
                self._func_depth and not self._with_lock_depth:
            self.diags.append(Diagnostic(
                "PTL003", self._loc(node),
                f"`{func.value.id}.{func.attr}()` on a module-level "
                f"registry outside any lock",
                hint="guard with the module's lock, or justify the "
                     "lock-free design in the allowlist"))
        # flag facts
        fname = _terminal_name(func)
        if fname == "define_flag" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            self.facts.flag_defines.setdefault(
                node.args[0].value, self._loc(node))
        elif fname == "flag_value" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            self.facts.flag_reads.add(node.args[0].value)
        elif fname == "get_flags":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str):
                    name = sub.value
                    if name.startswith("FLAGS_"):
                        name = name[len("FLAGS_"):]
                    self.facts.flag_reads.add(name)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # _registry["name"] / _flag_registry["name"] reads
        base = _terminal_name(node.value)
        if base is not None and "registry" in base:
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                self.facts.flag_reads.add(sl.value)
        self.generic_visit(node)


class RepoFacts:
    def __init__(self):
        self.flag_defines: Dict[str, str] = {}   # name -> define loc
        self.flag_reads: Set[str] = set()


class LintResult:
    def __init__(self):
        self.diagnostics: List[Diagnostic] = []
        self.suppressed: List[Tuple[Diagnostic, str]] = []
        self.files_scanned = 0
        self.parse_errors: List[str] = []

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def render(self) -> str:
        lines = [f"lint: {self.files_scanned} files, "
                 f"{len(self.diagnostics)} finding(s), "
                 f"{len(self.suppressed)} allowlisted"]
        for d in self.diagnostics:
            lines.append(d.render())
        if self.suppressed:
            lines.append("  allowlisted (rule @ location — justification):")
            for d, why in self.suppressed:
                lines.append(f"    {d.rule} @ {d.location} — {why}")
        if self.parse_errors:
            lines.append("  parse errors: " + "; ".join(self.parse_errors))
        return "\n".join(lines)


def allowlist_reason(d: Diagnostic, entries) -> Optional[str]:
    """The ONE suppression-matching rule (shared by the linter and the
    capture pass): an entry ``(rule, pattern, reason)`` suppresses a
    diagnostic when the rule matches and the fnmatch pattern hits the
    file path, the full ``path:line`` location, or the message."""
    path = d.location.partition(":")[0]
    for rule, pattern, reason in entries:
        if rule == d.rule and (fnmatch.fnmatch(path, pattern)
                               or fnmatch.fnmatch(d.location, pattern)
                               or fnmatch.fnmatch(d.message, pattern)):
            return reason
    return None


def _pragmas(source: str) -> Dict[int, Set[str]]:
    """line -> {rule ids} from inline `# lint-allow: PTLxxx reason`."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        marker = "# lint-allow:"
        pos = line.find(marker)
        if pos < 0:
            continue
        rules = {tok.strip().rstrip(",")
                 for tok in line[pos + len(marker):].split()
                 if tok.strip().rstrip(",").startswith(("PTL", "PTA",
                                                        "PTK", "PTC"))}
        if rules:
            out[i] = rules
    return out


def _check_ops_yaml(diags: List[Diagnostic]) -> None:
    """PTL005: ops.yaml fusable markers vs the live fusion registries.
    Skipped (not failed) when the runtime isn't importable — the AST
    rules still run standalone."""
    try:
        from ..ops.op_registry import OP_TABLE
        from ..core import fusion
    except Exception:  # noqa: BLE001 — standalone lint: rule skipped
        return
    for name, info in sorted(OP_TABLE.items()):
        marker = info.get("fusable")
        if marker is True:
            if name not in fusion._IMPLS and name not in fusion._PIMPLS:
                diags.append(Diagnostic(
                    "PTL005", f"ops/ops.yaml: {name}",
                    f"op `{name}` is marked fusable but registered no "
                    f"fusion impl (register_impl/register_param_impl) "
                    f"— the DAG can never fuse it",
                    hint="register the canonical impl at the op's "
                         "definition site, or drop the marker"))
        elif marker in ("reduce", "epilogue"):
            if name not in fusion._PIMPLS:
                diags.append(Diagnostic(
                    "PTL005", f"ops/ops.yaml: {name}",
                    f"op `{name}` is marked fusable:{marker} but has "
                    f"no parametric impl (register_param_impl)",
                    hint="reduction/contraction nodes are rebuilt from "
                         "_PIMPLS + attrs; without a registration the "
                         "op silently never defers"))
    for name in sorted(set(fusion._IMPLS) | set(fusion._PIMPLS)):
        info = OP_TABLE.get(name)
        if info is not None and not info.get("has_vjp", True):
            # non-differentiable ops can't fuse by design (the fused
            # GradNode needs a VJP); their identity registration is
            # harmless pre-registration, not an inconsistency
            continue
        if info is None or not info.get("fusable"):
            diags.append(Diagnostic(
                "PTL005", f"ops/ops.yaml: {name}",
                f"fusion impl registered for `{name}` but ops.yaml "
                f"does not mark it fusable — dead registration or a "
                f"missing marker",
                hint="add the `fusable:` marker (the class gate reads "
                     "ops.yaml, not the registry) or remove the "
                     "registration"))


def lint_source(source: str, name: str = "<snippet>") -> List[Diagnostic]:
    """Run the per-file AST rules over a source string (no allowlist,
    no cross-file rules) — the seeded-bug fixture entry point for tests
    and ``--self-check``."""
    tree = ast.parse(source, filename=name)
    visitor = _FileVisitor(name, RepoFacts())
    visitor.visit(tree)
    return sort_diagnostics(visitor.diags)


def lint(paths: Optional[List[str]] = None,
         use_allowlist: bool = True) -> LintResult:
    """Lint ``paths`` (default: every .py under paddle_tpu/) and return
    a :class:`LintResult`. Allowlist + pragma suppressions are applied
    (and reported) unless ``use_allowlist=False`` — the seeded-bug
    tests turn it off to see raw findings."""
    result = LintResult()
    facts = RepoFacts()
    files = paths if paths is not None else iter_source_files()
    raw: List[Diagnostic] = []
    pragma_map: Dict[str, Dict[int, Set[str]]] = {}
    for path in files:
        rel = _rel(path)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            result.parse_errors.append(f"{rel}: {e}")
            continue
        result.files_scanned += 1
        pragma_map[rel] = _pragmas(source)
        visitor = _FileVisitor(rel, facts)
        visitor.visit(tree)
        raw.extend(visitor.diags)

    # cross-file: PTL002 (only meaningful on a whole-package scan —
    # a partial path list would see defines without their reads)
    if paths is None:
        for name, loc in sorted(facts.flag_defines.items()):
            if name not in facts.flag_reads:
                raw.append(Diagnostic(
                    "PTL002", loc,
                    f"FLAGS_{name} is registered but read nowhere in "
                    f"paddle_tpu/ — either dead surface or documented "
                    f"behavior that silently does nothing",
                    hint="wire the flag where its docs claim it acts, "
                         "or allowlist it as deliberate reference-"
                         "parity surface"))
        _check_ops_yaml(raw)

    # suppression: inline pragmas, then the checked-in allowlist
    allow_entries: List[Tuple[str, str, str]] = []
    if use_allowlist:
        from .allowlist import ALLOWLIST
        allow_entries = list(ALLOWLIST)
    for d in raw:
        path, _, lineno = d.location.partition(":")
        line = int(lineno) if lineno.isdigit() else -1
        rules_here = pragma_map.get(path, {}).get(line, ())
        if use_allowlist and d.rule in rules_here:
            result.suppressed.append((d, "inline pragma"))
            continue
        why = allowlist_reason(d, allow_entries)
        if why is not None:
            result.suppressed.append((d, why))
        else:
            result.diagnostics.append(d)
    result.diagnostics = sort_diagnostics(result.diagnostics)

    try:
        from ..observability import metrics as _om
        _om.counter("analysis.lint_runs_total",
                    "Source-linter runs").inc()
        cd = _om.counter(
            "analysis.diagnostics_total",
            "Diagnostics emitted by the analysis plane, by rule")
        for d in result.diagnostics:
            cd.inc(rule=d.rule)
    except Exception:  # noqa: BLE001 — lint must work standalone
        pass
    return result
