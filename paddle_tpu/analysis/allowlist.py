"""Checked-in lint allowlist: (rule, location-glob, one-line reason).

Contract (ISSUE 6): deliberate exceptions are encoded HERE, per rule
and per site/file, each with a justification — never by silencing a
rule globally. Patterns match the repo-relative file path
("paddle_tpu/ops/math.py"), the full location ("...py:121"), or the
diagnostic message ("FLAGS_log_level is registered*" — stable when
line numbers aren't); globs are fnmatch-style. Suppressed findings are still counted and listed by
``analysis.lint``/the CLI, so drift stays visible.

When you fix a site, delete its entry — tests/test_lint_clean.py keeps
the repo clean against the ACTIVE rule set, and a stale entry here is
dead weight the next reader has to reason about.
"""

ALLOWLIST = [
    # -- PTL001: deliberate device->host syncs ---------------------------
    ("PTL001", "paddle_tpu/core/tensor.py",
     "the host-interop API itself: __float__/__int__/__bool__ route "
     "through item() by definition"),
    ("PTL001", "paddle_tpu/__init__.py",
     "paddle.tolist() is the public host-conversion API"),
    ("PTL001", "paddle_tpu/ops/inplace.py",
     "Tensor.tolist fallback shim — host conversion is its contract"),
    ("PTL001", "paddle_tpu/ops/creation.py",
     "Tensor-valued fill/shape args must be host-static for XLA "
     "(shapes/fill enter the program as constants)"),
    ("PTL001", "paddle_tpu/ops/manipulation.py",
     "Tensor-valued axis/pad/section args must be host-static for XLA"),
    ("PTL001", "paddle_tpu/ops/math.py",
     "Tensor-valued clip bounds / top-k k must be host-static for XLA"),
    ("PTL001", "paddle_tpu/nn/functional/common.py",
     "Tensor-valued pad widths must be host-static for XLA"),
    ("PTL001", "paddle_tpu/nn/functional/vision.py",
     "Tensor-valued output shape must be host-static for XLA"),
    ("PTL001", "paddle_tpu/nn/functional/extension.py",
     "sequence lengths drive host-side loop bounds (pack/unpack)"),
    ("PTL001", "paddle_tpu/optimizer/lr.py",
     "ReduceOnPlateau branches scheduling on the metric value by "
     "contract (host decision)"),
    ("PTL001", "paddle_tpu/optimizer/extra.py",
     "LBFGS line search branches on the loss value by contract; the "
     "optimizer opts out of fusion (_fusable_step=False)"),
    ("PTL001", "paddle_tpu/hapi/model.py",
     "predict/summary host conversions by contract; the train/eval "
     "loss fetch is HOISTED to the fit/evaluate log boundary (lazy "
     "device loss, Fusion III) so the step hot path itself is "
     "sync-free"),
    ("PTL001", "paddle_tpu/hapi/callbacks.py",
     "VisualDL/metric logging is host-side by nature"),
    ("PTL001", "paddle_tpu/io/sampler.py",
     "numpy index arrays (host data already) — .tolist() here never "
     "touches the device"),
    ("PTL001", "paddle_tpu/audio/backends.py",
     "file-I/O backend: waveform data is host-resident by contract"),
    ("PTL001", "paddle_tpu/geometric/*",
     "graph sampling utilities run on host numpy by design"),
    ("PTL001", "paddle_tpu/incubate/*",
     "ASP mask search / graph-sample khop are host-side preprocessing"),
    ("PTL001", "paddle_tpu/vision/detection_ops.py",
     "NMS/bbox post-processing is host-side by design"),

    # -- PTL002: reference-parity flags, deliberately inert --------------
    # keyed on the flag name via message glob, not file:line — flags.py
    # gains a flag nearly every PR and a line pin would rot
    ("PTL002", "FLAGS_eager_delete_tensor_gb is registered*",
     "documented no-op on TPU (XLA owns memory); kept so reference "
     "set_flags() calls don't raise"),
    ("PTL002", "FLAGS_use_bf16_matmul is registered*",
     "accumulation policy is governed by JAX's "
     "default_matmul_precision on TPU; accepted-but-inert for "
     "reference parity"),
    ("PTL002", "FLAGS_log_level is registered*",
     "reserved verbosity surface (jit.set_verbosity is the live "
     "knob); accepted for reference parity"),

    # -- PTL003: deliberate lock-free mutations --------------------------
    ("PTL003", "paddle_tpu/core/autograd.py",
     "_pair_cache_strong.clear() is a GIL-atomic one-shot bound reset "
     "on the measured dispatch hot path; a lock would cost more than "
     "the benign worst case (a racing thread re-promotes its entry)"),
    ("PTL003", "paddle_tpu/core/fusion.py",
     "_pending_tensors pop at donation-site flush runs on the step "
     "thread; WeakValueDictionary ops are self-consistent under the "
     "GIL and a lost entry only re-flushes a chain"),
    ("PTL003", "paddle_tpu/core/random.py",
     "paired __enter__/__exit__ push/pop of the key-stream context "
     "stack; stream contexts are step-thread-confined by convention"),
    ("PTL003", "paddle_tpu/autograd/py_layer.py",
     "paired __enter__/__exit__ push/pop of the saved-tensor-hooks "
     "context stack; hook contexts are step-thread-confined"),
    ("PTL003", "paddle_tpu/jit/sot.py",
     "guard-digest memo eviction inside the (single-threaded) SOT "
     "trace replay; tracing two threads through one SOTFunction is "
     "unsupported upstream of this cache"),
    ("PTL003", "paddle_tpu/distributed/collective.py",
     "process-group teardown (destroy_process_group) is a collective "
     "lifecycle call — single-threaded by the bootstrap contract"),
    ("PTL003", "paddle_tpu/incubate/asp.py",
     "ASP mask registry mutates only in user-driven prune/reset calls "
     "(host-side preprocessing, not touched by worker threads)"),
]

# Capture-planner (PTC*) exceptions: classifications of the repo's OWN
# step functions (capture.scan_repo_steps, run in tier-1). Same
# contract as ALLOWLIST — (rule, glob, one-line WHY), stale entries
# fail tests — but kept separate because these suppress findings of
# the capture pass, not the linter, and each entry is a deliberate
# CAPTURE-BOUNDARY decision the Fusion III plan reads as
# "capture-compatible, by design".
CAPTURE_ALLOWLIST = [
    # (the historical hapi loss-fetch PTC003 entry is GONE: Fusion III
    # hoisted the fetch out of train_batch/eval_batch — they return a
    # lazy device loss and fit/evaluate fetch at the log boundary, so
    # the step functions now scan clean with no exception needed)
    # -- hot start (ISSUE 14): precise rows FIRST so the broad
    #    serving globs below don't absorb them with the wrong story --
    ("PTC002", "paddle_tpu/jit/sot.py*",
     "CapturedStep.prewarm is the BOOT-time AOT seam, not a step: it "
     "installs the warm bundle's rebuilt program into the LRU before "
     "the first step ever runs — the same program-cache bookkeeping "
     "_get_program does at compile time, never replayed state"),
    ("PTC002", "*`self._prefills` inside the step*",
     "lazy program-cache instantiation (the per-bucket prefill "
     "executable), shared by the serving hot path and the "
     "warm-bundle _prewarm_entry replay: a dict-of-jitted-programs "
     "fill, not step state — the programs themselves are pure"),
    ("PTC002", "*`self.weight_swaps` inside the step*",
     "hot-swap bookkeeping advances exactly at the step boundary the "
     "swap is defined at: _apply_pending_swap runs between decode "
     "steps on the loop thread, installs a validated param tree, and "
     "never executes inside a captured program"),
    ("PTC002", "*`self._draft.*",
     "speculative decoding's draft mirror: the draft engine's slot "
     "state (last_ids/pos) is re-seeded from the TARGET's committed "
     "stream at the capture boundary — the draft/verify executables "
     "themselves are pure, only the accept/rollback bookkeeping "
     "between them mutates host state"),
    # -- self-healing serving plane (ISSUE 15): the supervisor/policy
    #    entry points are HOST control planes between captured
    #    programs — precise rows first, per concern ------------------
    ("PTC002", "*`self._steps_seen` inside the step*",
     "adaptive-admission evidence bookkeeping: on_step folds "
     "step-boundary gauges into host-side EWMAs and a step counter — "
     "the policy DECIDES between captured programs, it never executes "
     "inside one (brownout knobs only steer which already-compiled "
     "program the next iteration picks)"),
    ("PTC002", "paddle_tpu/serving_supervisor.py*",
     "crash-recovery/rollout host bookkeeping is the capture boundary "
     "BY DESIGN: strike/quarantine/restart counters and the "
     "re-admission of recovered requests (prompt + committed tokens "
     "through the normal prefill path) all advance while NO captured "
     "program is in flight — the dead loop is fenced first, the new "
     "loop replays the same pure compiled programs after"),
    # -- prefix-sharing KV (ISSUE 16): precise row first, same
    #    pattern as the hot-start/self-healing rows above ------------
    ("PTC002", "*`self.prefix_hit_tokens` inside the step*",
     "prefix-sharing admission bookkeeping: the radix-tree match, "
     "block aliasing and refcount bumps all run host-side in the "
     "allocator at admission — the capture boundary BY DESIGN; the "
     "captured prefill/decode programs see only the resulting block "
     "tables, and the one device-side effect (cloning the shared "
     "boundary block before its first write) is its own tiny jitted "
     "copy program (serving.prefix_cow), dispatched between steps"),
    # -- fleet serving fabric (ISSUE 17): the router is a pure HOST
    #    control plane across process boundaries — precise row so the
    #    broad serving glob below can't absorb it --------------------
    ("PTC002", "paddle_tpu/serving_fleet.py*",
     "fleet dispatch/fencing bookkeeping (the in-flight table, the "
     "epoch bump, failover/quarantine tallies) is the capture "
     "boundary BY DESIGN: the router never holds a tensor — replicas "
     "run the captured programs in their own processes, and every "
     "mutation here happens between RPC frames, with the zombie "
     "epoch's responses discarded rather than replayed"),
    ("PTC002", "paddle_tpu/serving.py*",
     "slot/block bookkeeping (pos/last_ids/active, block-table "
     "extension, prefill staging, speculative accept/rollback — "
     "committing the verified prefix and truncating rejected draft "
     "block writes) advances BETWEEN captured programs by design: "
     "the jitted dense/paged _decode_impl, the paged _prefill_impl "
     "chunks and the spec propose/verify pair are the capture "
     "regions, the server loop is the boundary that replays them"),
    ("PTC003", "paddle_tpu/serving.py*",
     "the per-step/per-window token fetch and the final-prefill-chunk "
     "first-token fetch ARE the decode contract: continuous batching "
     "must see each token on host to admit/retire requests; "
     "decode_steps batches it to one fetch per window and a "
     "speculative step fetches ONCE for up to spec_k committed "
     "tokens (the verify outputs drive accept/rollback)"),
    ("PTC003", "bench.py*",
     "deliberate device barriers: a value transfer is the only "
     "trustworthy sync over the TPU tunnel — warmup fetches bound the "
     "compile, the final fetch closes the timed region; the timed "
     "loop itself stays fetch-free"),
    ("PTC001", "paddle_tpu/amp/grad_scaler.py*",
     "the legacy override path ONLY: an optimizer with a custom "
     "step() (the LBFGS pattern) must run as written, so the found "
     "flag branches on host by contract — the plain path masks the "
     "update on device and never takes this branch, and under "
     "whole-step capture the entire scaler iteration (scale/backward/"
     "unscale/check/masked skip/scale bookkeeping) runs inside the "
     "ONE captured executable without reaching GradScaler.step at "
     "all"),
]
