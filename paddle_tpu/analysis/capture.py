"""Static graph-break analysis: prove, before tracing, where a step
function can and cannot become one jitted executable.

PR 6's dynamic auditor reports breaks on paths a recording actually
executed; this pass (stdlib ``ast``, the ``analysis/lint.py`` engine
style) reads the step function's SOURCE, so it also sees the branches a
recording never took — the other half Fusion III's planner needs.

Rules (ids + defaults in ``analysis.diagnostics.RULES``):

- **PTC001** — data-dependent control flow on tensor values: ``if t:``,
  ``while t.item():``, tensor-valued comparisons/``bool()`` feeding a
  branch. Each taken branch is a guard + graph break at capture time.
  Shape/ndim/dtype reads are static metadata, never flagged.
- **PTC002** — capture-poisoning side effects: in-place tensor
  mutation (``t[i] = v``, ``zero_()``-family methods), RNG consumption,
  mutation of ``self``/module/global state (``.append`` on persistent
  containers, augmented assignment to ``self`` attributes), host I/O
  (``print``/``open``). ``jit/sot.py`` marks these non-replayable at
  runtime; this flags them ahead of time.
- **PTC003** — host reads (``.item()``/``.numpy()``/``.tolist()``/
  ``float(t)``/``np.asarray(t)``). A read that postdominates all device
  work in the function is HOISTABLE (fix hint: move after the step);
  a mid-step read must become a capture guard or move.
- **PTC004** — statically visible shape polymorphism: boolean-mask
  indexing and ``nonzero``/``unique``/``masked_select`` calls, whose
  output shapes are data-dependent. (The planner adds the dynamic
  cross-check: PTA003 churn rows become PTC004 entries with a
  BucketPolicy hint.)

Tensor values are tracked by monotonic may-taint: seeds are calls into
tensor-producing modules (``paddle``/``jnp``/``jax``/``F``), known
factories (``to_tensor`` and friends) and tensor parameters (explicit,
or a live callable's defaultless positional args); taint flows through
arithmetic, method calls, container literals and unpacking, and — once
a name has held device-derived data — never retracts (a branch on a
re-bound host value is still data-dependent control flow: the fetch
was the sync, the branch is the guard). Host-read RESULTS start
untainted. Conservatism is otherwise toward NOT flagging — the
planner's zero-false-positive contract on clean jittable steps
outranks recall, because the dynamic audit backstops anything the
static pass misses on executed paths.

Suppression mirrors the linter: ``analysis/allowlist.py``'s
``CAPTURE_ALLOWLIST`` (rule, glob, justification — stale entries fail
tests) or inline ``# lint-allow: PTC00x reason`` pragmas.
"""
from __future__ import annotations

import ast
import os
import textwrap
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic, sort_diagnostics
from .lint import REPO_ROOT, _pragmas, _rel, _terminal_name

__all__ = ["capture_scan", "scan_source", "scan_file_function",
           "scan_repo_steps", "enclosing_function_scan", "REPO_STEPS",
           "CaptureScanResult"]

# modules whose calls produce device tensors
_TENSOR_MODULES = {"paddle", "paddle_tpu", "jnp", "jax", "F",
                   "functional", "nn", "lax"}
# bare-name calls that produce tensors
_TENSOR_FACTORIES = {"to_tensor", "_to_tensor", "zeros", "ones", "full",
                     "arange", "linspace", "eye", "empty", "zeros_like",
                     "ones_like", "full_like", "asarray"}
# BARE-NAME builtin calls whose results are never tensors even with
# tensor args (attribute calls like t.sum()/paddle.max() are exempt —
# they are tensor ops sharing a builtin's name)
_NON_TENSOR_CALLS = {"isinstance", "len", "type", "range", "enumerate",
                     "zip", "sorted", "list", "tuple", "dict", "set",
                     "getattr", "hasattr", "repr", "str", "id", "print",
                     "min", "max", "sum", "abs", "issubclass", "iter"}
# host-metadata attributes: reading them is static, not a device read
_METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "name", "place",
                   "stop_gradient", "trainable", "training", "is_leaf"}
# device->host conversion methods (the sync surface, PTL001's set)
_HOST_READS = {"item", "numpy", "tolist"}
# host scalar converters: float(t)/int(t)/bool(t) on a tensor sync
_SCALAR_CONVERTERS = {"float", "int", "bool"}
# in-place tensor mutators (ops/inplace.py surface + setters); the
# generic rule also catches `meth_()` with a tainted receiver
_INPLACE_METHODS = {"set_value", "fill_", "zero_", "add_", "subtract_",
                    "multiply_", "divide_", "scale_", "clip_", "copy_",
                    "exponential_", "uniform_", "normal_", "scatter_",
                    "squeeze_", "unsqueeze_", "reshape_", "flatten_",
                    "clear_gradient"}
# device RNG consumers (replay cannot reproduce the key stream)
_RNG_CALLS = {"dropout", "rand", "randn", "randint", "randperm",
              "uniform", "normal", "standard_normal", "bernoulli",
              "multinomial", "poisson", "rand_like", "randn_like",
              "randint_like", "dropout2d", "dropout3d", "alpha_dropout"}
# data-dependent-shape producers (PTC004)
_DYNSHAPE_CALLS = {"nonzero", "masked_select", "unique",
                   "index_select_dynamic"}
# persistent-container mutators (PTC002 when the receiver persists
# beyond the step: self attributes, globals)
_CONTAINER_MUTATORS = {"append", "extend", "update", "add",
                       "setdefault", "pop", "clear", "insert", "remove"}


def _root_name(node) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_self_attr(node) -> bool:
    return (isinstance(node, ast.Attribute)
            and _root_name(node) == "self")


class _FnScanner(ast.NodeVisitor):
    """Scans ONE function definition. Run ``visit`` twice: pass 1 grows
    the taint set to fixpoint across loops, pass 2 (``collect=True``)
    records events and findings."""

    def __init__(self, relpath: str, tensor_params: Sequence[str] = ()):
        self.relpath = relpath
        self.tainted: Set[str] = set(tensor_params)
        # names bound to tensor-valued COMPARISONS (boolean masks):
        # only these make indexing shape-dynamic — an integer-tensor
        # gather has the index's static shape
        self.masks: Set[str] = set()
        self.globals_declared: Set[str] = set()
        self.collect = False
        self.diags: List[Diagnostic] = []
        self.device_lines: List[int] = []
        self.syncs: List[Tuple[int, str, ast.AST]] = []
        self.branch_lines: Set[int] = set()
        self.loop_spans: List[Tuple[int, int]] = []
        self._depth = 0

    # -- taint oracle ----------------------------------------------------
    def is_tensor(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return False
            return self.is_tensor(node.value)
        if isinstance(node, ast.Call):
            return self._call_is_tensor(node)
        if isinstance(node, ast.BinOp):
            return self.is_tensor(node.left) or self.is_tensor(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tensor(node.operand)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in node.ops):
                return False  # identity/membership, not a value compare
            return self.is_tensor(node.left) or \
                any(self.is_tensor(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tensor(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_tensor(node.body) or self.is_tensor(node.orelse)
        if isinstance(node, ast.Subscript):
            return self.is_tensor(node.value)
        if isinstance(node, (ast.List, ast.Tuple)):
            return any(self.is_tensor(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tensor(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self.is_tensor(node.elt)
        if isinstance(node, ast.Await):
            return self.is_tensor(node.value)
        return False

    def _call_is_tensor(self, node: ast.Call) -> bool:
        func = node.func
        name = _terminal_name(func)
        # the builtin exclusion applies to BARE calls only: t.sum() /
        # t.abs() / paddle.max(t) are tensor ops sharing a builtin's
        # name, and untainting them would hide their branches
        if isinstance(func, ast.Name) and (
                name in _NON_TENSOR_CALLS or name in _SCALAR_CONVERTERS):
            return False
        if name in _HOST_READS:
            return False
        root = _root_name(func) if isinstance(func, ast.Attribute) else None
        if name in ("asarray", "array") and root in ("np", "numpy"):
            return False  # host conversion: the result left the device
        if name in _TENSOR_FACTORIES:
            return True
        if root in _TENSOR_MODULES:
            return True
        if isinstance(func, ast.Attribute) and self.is_tensor(func.value):
            return True  # method on a tensor
        # tensor-in -> tensor-out assumption for opaque callables
        # (self.network(*ins), a step closure, a loss module)
        return any(self.is_tensor(a) for a in node.args) or \
            any(self.is_tensor(kw.value) for kw in node.keywords)

    def _is_mask(self, node) -> bool:
        if isinstance(node, ast.Compare):
            return self.is_tensor(node)
        if isinstance(node, ast.Name):
            return node.id in self.masks
        if isinstance(node, ast.UnaryOp):
            return self._is_mask(node.operand)       # ~mask
        if isinstance(node, ast.BinOp):
            return self._is_mask(node.left) or \
                self._is_mask(node.right)            # mask & mask
        return False

    def _taint_target(self, target, tensor: bool, mask: bool = False):
        # MAY-taint, monotonic: once a name has held tensor-derived
        # data it stays tainted — the fixpoint pass re-walks the body,
        # so a kill here would let loop headers (`a = 0` before a loop
        # that re-taints `a`) erase loop-carried taint every pass. A
        # later branch on a re-bound host value is still data-dependent
        # control flow on device data (the fetch was the sync, the
        # branch is the guard), so never-discarding is also the
        # semantically honest reading.
        if isinstance(target, ast.Name):
            if tensor:
                self.tainted.add(target.id)
            if mask:
                self.masks.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e, tensor)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value, tensor)
        # attribute/subscript targets don't enter the local taint set

    # -- event recording -------------------------------------------------
    def _note_device(self, node):
        if self.collect:
            self.device_lines.append(getattr(node, "lineno", 0))

    def _note_sync(self, node, kind: str):
        if self.collect:
            self.syncs.append((node.lineno, kind, node))

    def _diag(self, rule, node, msg, hint=""):
        if self.collect:
            self.diags.append(Diagnostic(
                rule, f"{self.relpath}:{node.lineno}", msg, hint=hint))

    # -- statements ------------------------------------------------------
    def visit_Global(self, node):
        self.globals_declared.update(node.names)

    def visit_Assign(self, node):
        self.generic_visit(node)
        tensor = self.is_tensor(node.value)
        mask = self._is_mask(node.value)
        for t in node.targets:
            self._taint_target(t, tensor, mask)
            if isinstance(t, ast.Subscript):
                base = t.value
                if self.is_tensor(base):
                    self._diag(
                        "PTC002", node,
                        "in-place tensor mutation (subscript store) "
                        "inside the candidate capture region",
                        hint="replay cannot reproduce buffer mutation "
                             "— rebuild the value functionally "
                             "(where/scatter) or cut the region here")
                elif _is_self_attr(base):
                    self._diag(
                        "PTC002", node,
                        f"subscript store on persistent state "
                        f"`{ast.unparse(base)}` inside the step",
                        hint="state mutated mid-step never replays; "
                             "move bookkeeping to the step boundary")
            elif isinstance(t, ast.Name) and t.id in self.globals_declared:
                self._diag(
                    "PTC002", node,
                    f"assignment to global `{t.id}` inside the step",
                    hint="global writes are silently skipped on "
                         "replay; return the value instead")

    def visit_AnnAssign(self, node):
        self.generic_visit(node)
        if node.value is not None:
            self._taint_target(node.target, self.is_tensor(node.value))

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        t = node.target
        base = t.value if isinstance(t, ast.Subscript) else t
        if isinstance(t, ast.Subscript) and self.is_tensor(t.value):
            self._diag(
                "PTC002", node,
                "in-place tensor mutation (augmented subscript store)",
                hint="rebuild the value functionally or cut the "
                     "capture region here")
        elif _is_self_attr(base):
            self._diag(
                "PTC002", node,
                f"augmented assignment to persistent state "
                f"`{ast.unparse(base)}` inside the step",
                hint="state mutated mid-step never replays; move "
                     "bookkeeping to the step boundary")
        elif isinstance(t, ast.Name):
            if self.is_tensor(node.value) or t.id in self.tainted:
                self.tainted.add(t.id)
                self._note_device(node)

    def visit_For(self, node):
        if self.collect:
            self.loop_spans.append(
                (node.lineno, getattr(node, "end_lineno", node.lineno)))
        self._taint_target(node.target, self.is_tensor(node.iter))
        self.generic_visit(node)

    def visit_While(self, node):
        if self.collect:
            self.loop_spans.append(
                (node.lineno, getattr(node, "end_lineno", node.lineno)))
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def _check_branch(self, node, kw: str):
        test = node.test
        # a host read feeding the test IS the data dependence, whether
        # spelled .item()/.numpy() or float(t)/bool(t)/int(t)
        reads = []
        for n in ast.walk(test):
            if not isinstance(n, ast.Call):
                continue
            name = _terminal_name(n.func)
            if name in _HOST_READS:
                reads.append(name)
            elif isinstance(n.func, ast.Name) and \
                    name in _SCALAR_CONVERTERS and len(n.args) == 1 \
                    and self.is_tensor(n.args[0]):
                reads.append(name)
        if reads or self.is_tensor(test):
            via = (f"via {reads[0]}()" if reads
                   else "on a tensor value")
            self._diag(
                "PTC001", node,
                f"data-dependent `{kw}` {via}: each taken branch "
                f"becomes a guard + graph break under whole-step "
                f"capture",
                hint="hoist the decision out of the step, rewrite as "
                     "a masked/where computation, or accept one "
                     "compiled path per branch outcome (SOT guard)")
            self.branch_lines.add(node.lineno)

    # -- calls -----------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        func = node.func
        name = _terminal_name(func)
        # host reads: .item()/.numpy()/.tolist() (PTL001's receiver
        # heuristic: skip np.* host->host chains)
        if isinstance(func, ast.Attribute) and name in _HOST_READS \
                and not node.args and not node.keywords:
            recv = func.value
            recv_ok = True
            if isinstance(recv, ast.Call):
                root = _root_name(recv.func)
                recv_ok = root not in ("np", "numpy") and \
                    _terminal_name(recv.func) not in ("asarray", "array")
            elif not isinstance(recv, (ast.Name, ast.Attribute,
                                       ast.Subscript)):
                recv_ok = False
            if recv_ok:
                self._note_sync(node, f".{name}()")
        # float(t)/int(t)/bool(t) and np.asarray(t) on tainted values
        elif isinstance(func, ast.Name) and name in _SCALAR_CONVERTERS \
                and len(node.args) == 1 and self.is_tensor(node.args[0]):
            self._note_sync(node, f"{name}()")
        elif name in ("asarray", "array") and \
                _root_name(func) in ("np", "numpy") and node.args and \
                self.is_tensor(node.args[0]):
            self._note_sync(node, f"np.{name}()")
        # RNG consumption
        elif name in _RNG_CALLS:
            root = _root_name(func) if isinstance(func, ast.Attribute) \
                else None
            if root not in ("np", "numpy", "random", "rng"):
                self._diag(
                    "PTC002", node,
                    f"RNG consumption (`{name}`) inside the candidate "
                    f"capture region",
                    hint="a replayed segment would reuse the recorded "
                         "key stream; keep RNG ops outside the region "
                         "or accept the eager fallback (sot marks the "
                         "trace non-replayable)")
        # dynamic-shape producers
        elif name in _DYNSHAPE_CALLS:
            self._diag(
                "PTC004", node,
                f"`{name}` produces data-dependent shapes: every "
                f"distinct result shape compiles a new executable",
                hint="pad to a static bound + mask, or declare a "
                     "BucketPolicy for the consuming region")
        # in-place tensor mutators
        elif isinstance(func, ast.Attribute) and (
                name in _INPLACE_METHODS
                or (name and name.endswith("_") and len(name) > 1
                    and not name.startswith("_")
                    and self.is_tensor(func.value))):
            self._diag(
                "PTC002", node,
                f"in-place mutation `{ast.unparse(func)}()` inside the "
                f"candidate capture region",
                hint="jit/sot.py marks mutating traces non-replayable; "
                     "use the functional form or cut the region here")
        # persistent-container mutation
        elif isinstance(func, ast.Attribute) and \
                name in _CONTAINER_MUTATORS:
            recv = func.value
            persistent = _is_self_attr(recv) or (
                isinstance(recv, ast.Name)
                and recv.id in self.globals_declared)
            if persistent:
                self._diag(
                    "PTC002", node,
                    f"`{ast.unparse(recv)}.{name}()` mutates "
                    f"module/self state inside the step",
                    hint="host-state mutation is silently skipped on "
                         "replay; move it to the step boundary or "
                         "return the value")
        # host I/O
        elif isinstance(func, ast.Name) and name in ("print", "open"):
            self._diag(
                "PTC002", node,
                f"host I/O (`{name}`) inside the candidate capture "
                f"region",
                hint="I/O never replays; log outside the step or "
                     "behind a step-boundary callback")
        # device work: tensor-producing calls, plus .backward()/.step()
        # on ANY receiver — an optimizer/engine is never tainted, but
        # its step IS device work, and missing it would wrongly grade a
        # preceding host read "hoistable" (over-counting only demotes a
        # hoist to a guard, the safe direction)
        if self._call_is_tensor(node) or (
                isinstance(func, ast.Attribute)
                and name in ("backward", "step")):
            self._note_device(node)

    def visit_Subscript(self, node):
        self.generic_visit(node)
        # boolean-MASK indexing: the gather's output shape depends on
        # how many elements are true. (Integer-tensor gathers keep the
        # index's static shape and are capture-compatible — only
        # comparison-produced masks are flagged, per the zero-false-
        # positive contract.)
        if isinstance(node.ctx, ast.Load) and \
                self.is_tensor(node.value) and self._is_mask(node.slice):
            self._diag(
                "PTC004", node,
                "boolean-mask indexing: the result shape depends on "
                "runtime data",
                hint="pad to a static bound + mask, or declare a "
                     "BucketPolicy for the consuming region")

    def visit_BinOp(self, node):
        self.generic_visit(node)
        if self.is_tensor(node.left) or self.is_tensor(node.right):
            self._note_device(node)

    # one level of nested helpers is scanned as part of the region (a
    # `def loss_fn():` inside the step runs inside the step); deeper
    # nesting is out of scope — scan it as its own candidate instead
    def visit_FunctionDef(self, node):
        if self._depth == 0:
            self._depth += 1
            self.generic_visit(node)
            self._depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    # -- finalize --------------------------------------------------------
    def finalize(self) -> List[Diagnostic]:
        last_device = max(self.device_lines, default=0)
        for line, kind, node in self.syncs:
            if line in self.branch_lines:
                continue  # already a PTC001 at this site
            in_device_loop = any(
                lo <= line <= hi and
                any(lo <= d <= hi for d in self.device_lines)
                for lo, hi in self.loop_spans)
            tail = line >= last_device and not in_device_loop
            if tail:
                msg = (f"host read {kind} postdominates all device work "
                       f"— hoistable")
                hint = ("move the fetch after the step (or batch "
                        "fetches across steps): the step body then "
                        "captures whole")
            else:
                msg = f"host read {kind} mid-step (device work follows)"
                hint = ("a mid-step sync serializes dispatch and cuts "
                        "the capture region: make it an SOT guard, or "
                        "move the read off the step path")
            self.diags.append(Diagnostic(
                "PTC003", f"{self.relpath}:{line}", msg, hint=hint,
                data={"hoistable": tail, "kind": kind}))
        return sort_diagnostics(self.diags)


def _scan_fn_node(fn_node: ast.AST, relpath: str,
                  tensor_params: Sequence[str] = ()) -> List[Diagnostic]:
    scanner = _FnScanner(relpath, tensor_params)
    # taint to a true fixpoint first (loop-carried chains like
    # a = b; b = c; c = <tensor> need one pass per hop); each pass can
    # only add or move taint among a bounded name set, so this
    # terminates — the iteration cap is a belt for pathological
    # oscillation (taint both added and dropped around a loop)
    for _ in range(32):
        before = (frozenset(scanner.tainted), frozenset(scanner.masks))
        for stmt in fn_node.body:
            scanner.visit(stmt)
        if (frozenset(scanner.tainted),
                frozenset(scanner.masks)) == before:
            break
    scanner.collect = True
    for stmt in fn_node.body:
        scanner.visit(stmt)
    return scanner.finalize()


def scan_source(source: str, name: str = "<step>",
                tensor_params: Sequence[str] = (),
                first_line: int = 1) -> List[Diagnostic]:
    """Scan a source snippet (a module or a single def) — the seeded-
    fixture entry point for tests and ``--self-check``. When the
    snippet holds one function def, its parameters are treated as
    tensors unless ``tensor_params`` says otherwise."""
    tree = ast.parse(textwrap.dedent(source), filename=name)
    if first_line != 1:
        ast.increment_lineno(tree, first_line - 1)
    defs = [n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    diags: List[Diagnostic] = []
    if len(defs) == 1 and not tensor_params:
        tensor_params = [a.arg for a in defs[0].args.args
                         if a.arg not in ("self", "cls")]
    if defs:
        for d in defs:
            diags.extend(_scan_fn_node(d, name, tensor_params))
    else:
        diags.extend(_scan_fn_node(tree, name, tensor_params))
    return sort_diagnostics(diags)


def _find_def(tree: ast.Module, qualname: str):
    """Locate a (possibly method) function def by dotted qualname."""
    parts = qualname.split(".")
    body = tree.body
    node = None
    for i, part in enumerate(parts):
        node = None
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)) and n.name == part:
                node = n
                break
        if node is None:
            return None
        body = getattr(node, "body", [])
    return node if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None


def scan_file_function(path: str, qualname: str,
                       tensor_params: Sequence[str] = ()):
    """Scan one function of a real file. Returns ``(diags, meta)`` with
    ``meta = {"file", "function", "span"}`` (the planner's coverage
    spans)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    node = _find_def(tree, qualname)
    rel = _rel(path)
    if node is None:
        raise ValueError(f"{rel}: no function {qualname!r}")
    diags = _scan_fn_node(node, rel, tensor_params)
    meta = {"file": rel, "function": qualname,
            "span": (node.lineno, getattr(node, "end_lineno",
                                          node.lineno)),
            "pragmas": _pragmas(source)}
    return diags, meta


def capture_scan(fn, tensor_params: Optional[Sequence[str]] = None):
    """Scan a live callable (plain function, bound method, SOTFunction,
    or closure). Returns ``(diags, meta)``."""
    import inspect
    target = fn
    for attr in ("_fn", "__wrapped__", "__func__"):
        inner = getattr(target, attr, None)
        if inner is not None and callable(inner):
            target = inner
    try:
        source = inspect.getsource(target)
        path = inspect.getsourcefile(target) or "<unknown>"
        first = target.__code__.co_firstlineno
    except (OSError, TypeError) as e:
        raise ValueError(
            f"capture_scan: no source for {fn!r} ({e})") from e
    tree = ast.parse(textwrap.dedent(source))
    ast.increment_lineno(tree, first - 1)
    defs = [n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    if not defs:
        raise ValueError(f"capture_scan: {fn!r} is not a function def")
    node = defs[0]
    rel = _rel(path)
    if tensor_params is None:
        # default seeding: defaultless positional params are tensors (a
        # step's data args); params WITH defaults (update=True, axis=0)
        # are config knobs — seeding those would flag `if update:`
        a = node.args
        pos = list(a.posonlyargs) + list(a.args)
        n_defaults = len(a.defaults)
        seeded = pos[:len(pos) - n_defaults] if n_defaults else pos
        tensor_params = [p.arg for p in seeded
                        if p.arg not in ("self", "cls")]
    diags = _scan_fn_node(node, rel, tensor_params)
    meta = {"file": rel, "function": getattr(target, "__qualname__",
                                             node.name),
            "span": (node.lineno,
                     getattr(node, "end_lineno", node.lineno))}
    return diags, meta


def enclosing_function_scan(path: str, line: int):
    """Scan the innermost function containing ``line`` of ``path`` —
    how the planner turns a dynamic event origin into static coverage.
    Returns ``(diags, meta)`` or ``(None, None)`` when the line sits
    outside any function."""
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None, None
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lo, hi = node.lineno, getattr(node, "end_lineno", node.lineno)
            if lo <= line <= hi and (
                    best is None or lo > best.lineno):
                best = node
    if best is None:
        return None, None
    rel = _rel(path)
    diags = _scan_fn_node(best, rel, ())
    meta = {"file": rel, "function": best.name,
            "span": (best.lineno, getattr(best, "end_lineno",
                                          best.lineno)),
            "pragmas": _pragmas(source)}
    return diags, meta


# ---------------------------------------------------------------------------
# the repo's own step functions (satellite gate, run in tier-1)
# ---------------------------------------------------------------------------

# (relpath from repo root, dotted qualname, tensor param names)
REPO_STEPS: List[Tuple[str, str, Tuple[str, ...]]] = [
    ("paddle_tpu/hapi/model.py", "Model.train_batch",
     ("inputs", "labels")),
    ("paddle_tpu/hapi/model.py", "Model.eval_batch",
     ("inputs", "labels")),
    ("paddle_tpu/serving.py", "LlamaDecodeEngine._decode_impl",
     ("params", "k_cache", "v_cache", "last_ids", "pos")),
    ("paddle_tpu/serving.py", "LlamaDecodeEngine.step", ()),
    ("paddle_tpu/serving.py", "LlamaDecodeEngine.decode_steps", ()),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine._decode_impl",
     ("params", "kv", "last_ids", "pos", "tables", "act")),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine._prefill_impl",
     ("params", "kv", "ids", "table_row", "start", "nvalid",
      "true_len")),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine.step", ()),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine.decode_steps",
     ()),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine.prefill_chunk",
     ()),
    # prefix-sharing admission (ISSUE 16): the radix match/alias/COW
    # decision runs host-side at admission — begin_request is the
    # capture boundary, _device_cow dispatches the one jitted
    # boundary-block copy program
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine.begin_request",
     ()),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine._device_cow",
     ()),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine._propose_impl",
     ("params", "kv", "last_ids", "pos", "tables", "act")),
    ("paddle_tpu/serving.py",
     "PagedLlamaDecodeEngine._spec_verify_impl",
     ("params", "kv", "last_ids", "draft_tok", "pos", "tables",
      "act")),
    ("paddle_tpu/serving.py", "PagedLlamaDecodeEngine.spec_step", ()),
    ("paddle_tpu/serving.py", "LlamaDecodeEngine.swap_weights", ()),
    ("paddle_tpu/serving.py",
     "GenerationServer._apply_pending_swap", ()),
    ("paddle_tpu/serving.py",
     "PagedLlamaDecodeEngine._prewarm_entry", ()),
    ("paddle_tpu/serving.py",
     "PagedLlamaDecodeEngine.reset_state", ()),
    ("paddle_tpu/serving_supervisor.py",
     "ServingSupervisor._handle_death", ()),
    ("paddle_tpu/serving_supervisor.py",
     "AdaptiveAdmissionPolicy.on_step", ()),
    ("paddle_tpu/serving_supervisor.py", "rollout", ()),
    # fleet serving fabric (ISSUE 17): router placement and failover
    # are the HOST control plane between replica processes — scanned
    # so a tensor fetch or captured-state mutation sneaking into the
    # dispatch/fencing path fails tier-1
    ("paddle_tpu/serving_fleet.py", "FleetRouter._dispatch", ()),
    ("paddle_tpu/serving_fleet.py", "FleetRouter._replica_down", ()),
    ("paddle_tpu/jit/sot.py", "CapturedStep.prewarm", ()),
    ("paddle_tpu/distributed/dist_train.py", "DistTrainStep.__call__",
     ("batch_and_labels",)),
    ("paddle_tpu/distributed/dist_train.py", "_DistCapturedStep.step",
     ("inputs", "labels")),
    ("paddle_tpu/amp/grad_scaler.py", "GradScaler.step", ()),
    ("bench.py", "bench_llama", ()),
]


class CaptureScanResult:
    def __init__(self):
        self.diagnostics: List[Diagnostic] = []
        self.suppressed: List[Tuple[Diagnostic, str]] = []
        self.functions: List[Dict[str, Any]] = []

    def render(self) -> str:
        lines = [f"capture scan: {len(self.functions)} step function(s), "
                 f"{len(self.diagnostics)} finding(s), "
                 f"{len(self.suppressed)} allowlisted"]
        for d in self.diagnostics:
            lines.append(d.render())
        if self.suppressed:
            lines.append("  allowlisted (rule @ location — justification):")
            for d, why in self.suppressed:
                lines.append(f"    {d.rule} @ {d.location} — {why}")
        return "\n".join(lines)


def apply_allowlist(diags: List[Diagnostic],
                    pragma_map: Optional[Dict[int, Set[str]]] = None,
                    use_allowlist: bool = True):
    """Split raw PTC findings into (kept, suppressed) via the capture
    allowlist + inline pragmas — the matching rule is literally the
    linter's (``lint.allowlist_reason``), so the two surfaces cannot
    drift."""
    from .lint import allowlist_reason
    kept: List[Diagnostic] = []
    suppressed: List[Tuple[Diagnostic, str]] = []
    entries: List[Tuple[str, str, str]] = []
    if use_allowlist:
        from .allowlist import CAPTURE_ALLOWLIST
        entries = list(CAPTURE_ALLOWLIST)
    for d in diags:
        line_s = d.location.partition(":")[2]
        line = int(line_s) if line_s.isdigit() else -1
        if use_allowlist and pragma_map and \
                d.rule in pragma_map.get(line, ()):
            suppressed.append((d, "inline pragma"))
            continue
        why = allowlist_reason(d, entries)
        if why is not None:
            suppressed.append((d, why))
        else:
            kept.append(d)
    return kept, suppressed


def scan_repo_steps(use_allowlist: bool = True) -> CaptureScanResult:
    """Run the static capture pass over the repo's OWN step functions
    (the tier-1 gate: new unallowlisted PTC findings fail CI, the
    test_lint_clean.py pattern)."""
    result = CaptureScanResult()
    for rel, qual, params in REPO_STEPS:
        path = os.path.join(REPO_ROOT, rel)
        if not os.path.exists(path):
            continue
        diags, meta = scan_file_function(path, qual, params)
        result.functions.append(meta)
        kept, supp = apply_allowlist(diags, meta.get("pragmas"),
                                     use_allowlist)
        result.diagnostics.extend(kept)
        result.suppressed.extend(supp)
    result.diagnostics = sort_diagnostics(result.diagnostics)
    try:
        from ..observability import metrics as _om
        cd = _om.counter(
            "analysis.diagnostics_total",
            "Diagnostics emitted by the analysis plane, by rule")
        for d in result.diagnostics:
            cd.inc(rule=d.rule)
    except Exception:  # noqa: BLE001 — the scan must work standalone
        pass
    return result
