"""Instrumented-lock shim: acquisition-order recording + cycle detection.

The threaded subsystems (async checkpoint persist, serving drain,
elastic heartbeat/watch, the metrics registry) each grew their own
locks; nothing ever checked that they nest consistently. This module
provides:

- :class:`InstrumentedLock` — a drop-in ``threading.Lock``/``RLock``
  wrapper that records, per thread, the stack of currently-held locks,
  every nesting edge (lock B acquired while A is held), hold durations,
  and device work executed under a lock.
- :class:`LockAuditor` — owns the recording and turns it into
  diagnostics: **PTK001** lock-order cycles (AB/BA inversions, with both
  acquisition stacks) and **PTK002** locks held across device work /
  past the long-hold threshold.
- :func:`make_lock` — the factory the in-tree subsystems create their
  locks through. Normally it returns a plain ``threading.Lock`` (zero
  overhead); inside :func:`instrument` it returns named instrumented
  locks, so a test that constructs a ``CheckpointManager`` or
  ``GenerationServer`` under the context gets deterministic lock names
  ("checkpoint.manager", "serving.submit") in its report.
- :func:`instrument` — context manager that arms the factory AND
  patches ``threading.Lock``/``threading.RLock``, so locks created by
  code that doesn't know about this module (stdlib ``queue.Queue``
  included) are captured too.

Import-light by contract: stdlib only, so ``serving``/``checkpoint``/
``metrics`` can import :func:`make_lock` at module load with no cycle
(the ``analysis`` package ``__init__`` is lazy for the same reason).
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["InstrumentedLock", "LockAuditor", "make_lock", "instrument",
           "active_auditor", "caller_site"]

# the REAL primitives, captured before instrument() can patch
# threading.Lock/RLock — the shim's own internals must never route
# through the patched constructors (infinite recursion otherwise)
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

# armed by instrument(); make_lock() routes here when set
_active: Optional["LockAuditor"] = None
_active_lock = _REAL_LOCK()


def active_auditor() -> Optional["LockAuditor"]:
    return _active


def make_lock(name: str, rlock: bool = False):
    """Subsystem lock factory: a plain threading primitive normally, a
    named instrumented lock under :func:`instrument`. The name is the
    stable identity lock-order diagnostics report ("serving.submit" →
    "queue.mutex"), independent of construction site."""
    aud = _active
    if aud is not None:
        return aud.lock(name, rlock=rlock)
    return _REAL_RLOCK() if rlock else _REAL_LOCK()


def caller_site(skip_suffixes) -> str:
    """``pkg/file.py:line`` of the nearest stack frame whose filename
    ends with none of ``skip_suffixes`` — the shared attribution helper
    for the analysis plane (the auditor's sync/donation origins, lock
    acquisition sites). ``core/fusion.py`` keeps its own minimal copy:
    core must not depend on the analysis package."""
    import sys
    f = sys._getframe(1)
    skip = tuple(skip_suffixes)
    while f is not None:
        fn = f.f_code.co_filename.replace("\\", "/")
        if not fn.endswith(skip):
            parts = fn.split("/")
            short = "/".join(parts[-2:]) if len(parts) > 1 else fn
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _site(skip_modules=("analysis/locks.py", "threading.py", "queue.py")):
    """file.py:line of the nearest caller frame outside this machinery."""
    return caller_site(skip_modules)


class InstrumentedLock:
    """Wraps a real lock; every successful acquire/release reports to
    the auditor. API-compatible with the ``threading.Lock`` surface the
    repo uses (acquire/release/locked/context manager) plus RLock
    reentrancy when constructed with ``rlock=True``."""

    def __init__(self, auditor: "LockAuditor", name: str,
                 rlock: bool = False):
        self._auditor = auditor
        self.name = name
        self._rlock = rlock
        self._inner = _REAL_RLOCK() if rlock else _REAL_LOCK()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # objects built under instrument() (a server, a manager, a
        # queue) keep their instrumented locks for life; once the
        # auditor closes they must degrade to plain-lock cost — no
        # stack walk, no recording into a dead auditor
        if self._auditor.closed:
            return self._inner.acquire(blocking, timeout)
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._auditor._on_acquire(self, time.monotonic() - t0)
        return ok

    def release(self):
        if self._auditor.closed:
            # mirror of the acquire fast path: a surviving lock must
            # not walk stacks or contend on the dead auditor's _book
            self._inner.release()
            return
        self._auditor._on_release(self)
        self._inner.release()

    def locked(self):
        try:
            return self._inner.locked()
        except AttributeError:  # RLock pre-3.12 has no locked()
            if self._inner._is_owned():
                return True  # self-held: a trial acquire would succeed
            if self._inner.acquire(blocking=False):
                self._inner.release()
                return False
            return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # queue.Queue probes these on its mutex
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        return inner.locked()

    # threading.Condition probes these on its lock: without delegation a
    # Condition built on a patched RLock would fall back to releasing
    # ONE level in wait(), deadlocking any reentrant holder
    def _release_save(self):
        aud = self._auditor
        if not aud.closed:
            st = aud._stack()
            while any(h.lock is self for h in st):
                aud._on_release(self)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        aud = self._auditor
        if not aud.closed:
            # one hold regardless of restored depth: reentrant levels
            # record no self-edges anyway
            aud._on_acquire(self, 0.0)

    def __repr__(self):
        return f"InstrumentedLock({self.name!r})"


class _Hold:
    __slots__ = ("lock", "t0", "site", "device_ops", "owner_stack")

    def __init__(self, lock, site, owner_stack):
        self.lock = lock
        self.t0 = time.monotonic()
        self.site = site
        self.device_ops: List[str] = []
        # the acquiring thread's hold stack — kept so a release from a
        # DIFFERENT thread (legal lock handoff) can evict this hold
        # instead of leaving a phantom that poisons every later edge
        self.owner_stack = owner_stack


class LockAuditor:
    """Recording + analysis. One instance per scenario run; thread-safe
    (its own bookkeeping lock is a raw ``threading.Lock``, invisible to
    itself)."""

    def __init__(self, long_hold_s: float = 0.2):
        self.long_hold_s = long_hold_s
        # set when the owning instrument() exits: surviving
        # InstrumentedLocks then degrade to plain-lock behavior
        self.closed = False
        self._book = _REAL_LOCK()  # guards edges/holds bookkeeping
        self._tls = threading.local()
        # (held_name, acquired_name) -> (held_site, acquired_site) sample
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.acquisitions: Dict[str, int] = {}
        self.long_holds: List[Tuple[str, float, str]] = []
        self.device_under_lock: List[Tuple[str, str, str]] = []
        self.contention_s: Dict[str, float] = {}
        self._names: Dict[str, int] = {}
        # id(lock) -> live holds across ALL threads (acquisition order):
        # the cross-thread-release eviction index
        self._live_holds: Dict[int, List[_Hold]] = {}

    # -- factory ---------------------------------------------------------
    def _unique(self, name: str) -> str:
        with self._book:
            n = self._names.get(name, 0)
            self._names[name] = n + 1
        return name if n == 0 else f"{name}#{n + 1}"

    def lock(self, name: Optional[str] = None,
             rlock: bool = False) -> InstrumentedLock:
        return InstrumentedLock(self, self._unique(name or _site()), rlock)

    # -- recording -------------------------------------------------------
    def _stack(self) -> List[_Hold]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, lock: InstrumentedLock, waited: float) -> None:
        st = self._stack()
        site = _site()
        hold = _Hold(lock, site, st)
        with self._book:
            self.acquisitions[lock.name] = \
                self.acquisitions.get(lock.name, 0) + 1
            if waited > 1e-4:
                self.contention_s[lock.name] = \
                    self.contention_s.get(lock.name, 0.0) + waited
            for held in st:
                if held.lock is lock:  # RLock reentry: no self-edge
                    break
            else:
                for held in st:
                    key = (held.lock.name, lock.name)
                    if key not in self.edges and \
                            held.lock.name != lock.name:
                        self.edges[key] = (held.site, site)
            self._live_holds.setdefault(id(lock), []).append(hold)
        st.append(hold)

    def _on_release(self, lock: InstrumentedLock) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i].lock is lock:
                hold = st.pop(i)
                self._record_release(hold)
                return
        # released by a thread that didn't acquire it (legal handoff,
        # e.g. through a patched stdlib component): evict the
        # acquirer's hold via the global index, or every later
        # acquisition on that thread records a phantom nesting edge
        with self._book:
            holds = self._live_holds.get(id(lock))
            hold = holds.pop() if holds else None
            if hold is not None:
                # evict under _book: _on_acquire iterates the owner's
                # stack (edge recording) inside _book, so a foreign
                # remove must serialize with it or an edge can be
                # skipped mid-iteration
                try:
                    hold.owner_stack.remove(hold)
                except ValueError:
                    pass
        if hold is not None:
            self._record_release(hold, indexed=False)

    def _record_release(self, hold: _Hold, indexed: bool = True) -> None:
        if self.closed:
            return  # pre-close hold released after: pop only
        dt = time.monotonic() - hold.t0
        name = hold.lock.name
        with self._book:
            if indexed:
                holds = self._live_holds.get(id(hold.lock))
                if holds and hold in holds:
                    holds.remove(hold)
            if dt >= self.long_hold_s:
                self.long_holds.append((name, dt, hold.site))
            for op in hold.device_ops:
                self.device_under_lock.append((name, op, hold.site))

    def note_device_op(self, desc: str) -> None:
        """Called by the audit hooks when device work (a fusion flush, a
        donated executable) runs; attributes it to every lock the
        current thread holds."""
        for hold in self._stack():
            if len(hold.device_ops) < 16:
                hold.device_ops.append(desc)

    def held_now(self) -> List[str]:
        return [h.lock.name for h in self._stack()]

    # -- analysis --------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Distinct cycles in the acquired-while-held graph."""
        graph: Dict[str, List[str]] = {}
        with self._book:
            for a, b in self.edges:
                graph.setdefault(a, []).append(b)
        seen_cycles = set()
        out: List[List[str]] = []

        def dfs(node, path, on_path):
            for nxt in graph.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                    continue
                dfs(nxt, path + [nxt], on_path | {nxt})

        for start in list(graph):
            dfs(start, [start], {start})
        return out

    def diagnostics(self) -> List[Any]:
        from .diagnostics import Diagnostic
        diags: List[Any] = []
        for cyc in self.cycles():
            pairs = list(zip(cyc, cyc[1:]))
            with self._book:
                sites = {p: self.edges.get(p) for p in pairs}
            detail = "; ".join(
                f"{a}->{b} at {sites[(a, b)][1] if sites.get((a, b)) else '?'}"
                for a, b in pairs)
            diags.append(Diagnostic(
                "PTK001", "lock-cycle: " + " -> ".join(cyc),
                f"lock-order cycle: {detail}",
                hint="pick one global order for these locks (acquire "
                     "the same first lock on every path), or collapse "
                     "them into one lock"))
        with self._book:
            device = list(self.device_under_lock)
            longs = list(self.long_holds)
        for name, op, site in device:
            diags.append(Diagnostic(
                "PTK002", f"lock:{name} at {site}",
                f"device work ({op}) executed while holding {name}",
                hint="move the device call outside the critical "
                     "section; locks should guard bookkeeping, not "
                     "XLA execution"))
        for name, dt, site in longs:
            diags.append(Diagnostic(
                "PTK002", f"lock:{name} at {site}",
                f"{name} held {dt * 1e3:.1f} ms "
                f"(threshold {self.long_hold_s * 1e3:.0f} ms)",
                hint="shrink the critical section or snapshot state "
                     "and process outside the lock"))
        return diags

    def summary(self) -> Dict[str, Any]:
        # cycles() takes _book itself — compute before entering it
        cycles = [" -> ".join(c) for c in self.cycles()]
        with self._book:
            return {
                "locks": dict(self.acquisitions),
                "edges": {f"{a} -> {b}": list(v)
                          for (a, b), v in self.edges.items()},
                "cycles": cycles,
                "long_holds": [
                    {"lock": n, "seconds": round(dt, 6), "site": s}
                    for n, dt, s in self.long_holds],
                "device_under_lock": [
                    {"lock": n, "op": o, "site": s}
                    for n, o, s in self.device_under_lock],
                "contention_seconds": {
                    k: round(v, 6) for k, v in self.contention_s.items()},
            }


@contextmanager
def instrument(long_hold_s: float = 0.2, patch_threading: bool = True):
    """Arm lock instrumentation for the dynamic extent of the block:
    :func:`make_lock` returns named instrumented locks, and (by default)
    ``threading.Lock``/``threading.RLock`` are patched so anonymous
    locks — including stdlib ``queue.Queue`` internals — are recorded
    too, named by creation site. Yields the :class:`LockAuditor`.

    Device-op coupling: when ``core.fusion`` is already imported, its
    flush observer is chained for the duration so a fusion flush under
    a held lock becomes a PTK002 finding."""
    global _active
    aud = LockAuditor(long_hold_s=long_hold_s)
    with _active_lock:
        if _active is not None:
            raise RuntimeError("lock instrumentation is already active "
                               "(nested instrument() is not supported)")
        _active = aud
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    if patch_threading:
        threading.Lock = lambda: aud.lock(rlock=False)   # type: ignore
        threading.RLock = lambda: aud.lock(rlock=True)   # type: ignore
    # chain the fusion flush observer (lazy: never import the backend)
    import sys
    fusion = sys.modules.get("paddle_tpu.core.fusion")
    prev_obs = None
    if fusion is not None:
        prev_obs = fusion._flush_observer

        def chained(reason, nops, pkind, origin, _prev=prev_obs):
            aud.note_device_op(f"fusion_flush[{reason}]")
            if _prev is not None:
                _prev(reason, nops, pkind, origin)

        # origin is only consumed downstream: don't make fusion pay the
        # stack walk for pure lock instrumentation
        chained.needs_origin = (
            getattr(prev_obs, "needs_origin", True)
            if prev_obs is not None else False)
        fusion._flush_observer = chained
    try:
        yield aud
    finally:
        if patch_threading:
            threading.Lock, threading.RLock = orig_lock, orig_rlock
        if fusion is not None:
            fusion._flush_observer = prev_obs
        aud.closed = True  # surviving locks degrade to plain-lock cost
        with _active_lock:
            _active = None
