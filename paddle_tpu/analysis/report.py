"""One reporting surface over the three analyzers.

``report()`` composes a capture audit (when given a callable), a full
source-lint pass and, when a lock auditor is active, its summary into
one :class:`AnalysisReport` with a single ``diagnostics`` list and a
text/dict rendering. ``self_check()`` is the smoke contract the bench
``--dispatch-only`` path runs: one seeded bug per analyzer, each of
which must be detected by its rule id — proving the analysis plane
itself works before anyone trusts a clean report.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from .diagnostics import Diagnostic, RULES, sort_diagnostics

__all__ = ["AnalysisReport", "report", "self_check", "rules_table"]


class AnalysisReport:
    def __init__(self, capture=None, lint_result=None, locks_summary=None):
        self.capture = capture
        self.lint = lint_result
        self.locks_summary = locks_summary

    @property
    def diagnostics(self) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        if self.capture is not None:
            out.extend(self.capture.diagnostics)
        if self.lint is not None:
            out.extend(self.lint.diagnostics)
        return sort_diagnostics(out)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "diagnostics": [x.to_dict() for x in self.diagnostics]}
        if self.capture is not None:
            d["capture"] = self.capture.to_dict()
        if self.lint is not None:
            d["lint"] = {
                "files_scanned": self.lint.files_scanned,
                "findings": len(self.lint.diagnostics),
                "allowlisted": len(self.lint.suppressed),
            }
        if self.locks_summary is not None:
            d["locks"] = self.locks_summary
        return d

    def render(self) -> str:
        parts = ["paddle_tpu.analysis report",
                 "=" * 26]
        if self.capture is not None:
            parts.append(self.capture.render())
        if self.lint is not None:
            parts.append(self.lint.render())
        if self.locks_summary is not None:
            cyc = self.locks_summary.get("cycles", [])
            parts.append(f"locks: {len(self.locks_summary.get('locks', {}))}"
                         f" instrumented, {len(cyc)} cycle(s)"
                         + (": " + "; ".join(cyc) if cyc else ""))
        errs = self.errors
        parts.append(f"total: {len(self.diagnostics)} diagnostic(s), "
                     f"{len(errs)} error(s)")
        return "\n".join(parts)


def report(fn: Optional[Callable] = None, *args, lint: bool = True,
           warmup: int = 2, **kwargs) -> AnalysisReport:
    """The one-stop entry point. With ``fn``, runs a capture audit of
    ``fn(*args, **kwargs)`` (see :func:`analysis.audit` — e.g. one
    ``Model.fit`` step closure); with ``lint=True`` (default) also runs
    the source linter over ``paddle_tpu/``. When a lock auditor is
    active (``locks.instrument()``), its summary is attached."""
    capture = None
    if fn is not None:
        from .auditor import audit
        capture = audit(fn, *args, warmup=warmup, **kwargs)
    lint_result = None
    if lint:
        from .lint import lint as _lint
        lint_result = _lint()
    from . import locks as _locks
    la = _locks.active_auditor()
    locks_summary = la.summary() if la is not None else None
    return AnalysisReport(capture, lint_result, locks_summary)


def rules_table() -> str:
    lines = ["rule    analyzer  severity  title",
             "-" * 64]
    for rid, info in sorted(RULES.items()):
        lines.append(f"{rid:<7} {info.analyzer:<9} {info.severity:<9} "
                     f"{info.title}")
    return "\n".join(lines)


def self_check(verbose: bool = False) -> Dict[str, Any]:
    """Seed one bug per analyzer and assert its rule fires — the smoke
    proof that the analysis plane detects what it claims to: lint,
    audit, capture (one break per PTC rule), shapes (a wrong spec
    fails the golden run), flight (a synthetic crash leaves a dump
    containing the seeded event) and locks. Returns {"ok": bool,
    "checks": {name: bool}, "detail": str}. Cheap enough for the bench
    ``--dispatch-only`` path (~a second, CPU)."""
    checks: Dict[str, bool] = {}
    details: List[str] = []

    # 1) lint engine: bare except + unguarded registry sweep
    try:
        from .lint import lint_source
        diags = lint_source(
            "REG = {}\n"
            "def evict():\n"
            "    REG.clear()\n"
            "    try:\n"
            "        pass\n"
            "    except:\n"
            "        pass\n")
        rules = {d.rule for d in diags}
        checks["lint"] = {"PTL003", "PTL004"} <= rules
        if not checks["lint"]:
            details.append(f"lint fired {sorted(rules)}, "
                           f"wanted PTL003+PTL004")
    except Exception as e:  # noqa: BLE001 — a crash IS the failure
        checks["lint"] = False
        details.append(f"lint self-check crashed: {e!r}")

    # 2) auditor: a fused chain broken by a host sync must be captured
    #    with its flush reason and a PTA001 sync diagnostic
    try:
        import numpy as np
        from .auditor import audit

        def step():
            import paddle_tpu as paddle
            x = paddle.to_tensor(np.ones((4, 4), np.float32))
            y = paddle.add(paddle.multiply(x, 2.0), 1.0)
            return float(y.sum().item())  # lint-allow: PTL001 seeded bug

        rep = audit(step, warmup=1)
        checks["audit"] = (
            any(d.rule == "PTA001" for d in rep.diagnostics)
            and len(rep.flushes) > 0
            and all(f["origin"] != "<unknown>" for f in rep.flushes))
        if not checks["audit"]:
            details.append(
                f"audit: {len(rep.flushes)} flushes, rules "
                f"{sorted({d.rule for d in rep.diagnostics})}")
    except Exception as e:  # noqa: BLE001
        checks["audit"] = False
        details.append(f"audit self-check crashed: {e!r}")

    # 3) capture planner, static half: one seeded break per PTC rule —
    #    a tensor-valued branch, an in-place store, a tail host read and
    #    a boolean-mask gather — each detected by exact id
    try:
        from .capture import scan_source
        diags = scan_source(
            "def step(x):\n"
            "    import paddle_tpu as paddle\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    if t.sum().item() > 0:\n"          # PTC001
            "        t = paddle.add(t, 1.0)\n"
            "    t[0] = 0.0\n"                      # PTC002
            "    mask = t > 0.5\n"
            "    sel = t[mask]\n"                   # PTC004
            "    return sel.numpy()\n")             # PTC003
        rules = {d.rule for d in diags}
        want = {"PTC001", "PTC002", "PTC003", "PTC004"}
        checks["capture"] = want <= rules
        if not checks["capture"]:
            details.append(f"capture fired {sorted(rules)}, "
                           f"wanted {sorted(want)}")
    except Exception as e:  # noqa: BLE001
        checks["capture"] = False
        details.append(f"capture self-check crashed: {e!r}")

    # 4) shape specs: a deliberately wrong spec (sum graded as
    #    elementwise) must fail the golden run as PTC005, and the real
    #    table must pass it
    try:
        from .shapes import validate_op
        seeded = validate_op("sum", "elementwise")
        clean = validate_op("sum")
        checks["shapes"] = (
            any(d.rule == "PTC005" for d in seeded) and not clean)
        if not checks["shapes"]:
            details.append(
                f"shapes: seeded={[d.rule for d in seeded]}, "
                f"clean={[d.rule for d in clean]}")
    except Exception as e:  # noqa: BLE001
        checks["shapes"] = False
        details.append(f"shapes self-check crashed: {e!r}")

    # 5) flight recorder: a synthetic crash (unhandled exception on a
    #    thread, the serving-loop death mode) must leave a dump whose
    #    trail contains the event seeded just before the crash. The
    #    check runs against freshly installed hooks (a production
    #    install is torn down first and re-installed after — a second
    #    install_crash_hooks() is an idempotent no-op, so silencing the
    #    thread hook without this would disarm the live hooks and fail
    #    spuriously), forces the recorder ON (an operator kill switch
    #    must not read as a broken analysis plane), and afterwards
    #    removes its synthetic events from the production ring so a
    #    later REAL dump doesn't carry a fake prior crash. The one
    #    honest residue: dumps_total{trigger=exception} counts the
    #    synthetic dump it really wrote.
    try:
        import tempfile

        from ..core.flags import get_flags, set_flags
        from ..observability import flight

        _SEEDED_MSG = "flight self-check seeded crash"
        with tempfile.TemporaryDirectory() as d:
            prev_flags = get_flags(["FLAGS_flight_dump_dir",
                                    "FLAGS_flight_recorder"])
            was_installed = flight._hooks_installed
            # signal numbers bound by a production
            # install_crash_hooks(signals=...) must be re-bound on
            # re-install or the operator's live-dump trigger silently
            # reverts to SIG_DFL
            prev_signums = tuple(flight._prev_signals)
            if was_installed:
                flight.uninstall_crash_hooks()
            prev_hook = threading.excepthook
            # silence the default traceback print: the crash is seeded
            threading.excepthook = lambda args: None
            set_flags({"FLAGS_flight_dump_dir": d,
                       "FLAGS_flight_recorder": 1})
            flight.install_crash_hooks()
            try:
                flight.record("selfcheck", "seeded_event", probe=1)

                def boom():
                    raise RuntimeError(_SEEDED_MSG)

                t = threading.Thread(target=boom)
                t.start()
                t.join()
                dumps = flight.find_dumps(d)
                ok_flight = False
                if dumps:
                    _hdr, evs = flight.load_dump(dumps[0])
                    ok_flight = any(
                        e.get("cat") == "selfcheck"
                        and e.get("name") == "seeded_event"
                        for e in evs)
            finally:
                flight.uninstall_crash_hooks()
                threading.excepthook = prev_hook
                set_flags(prev_flags)
                if was_installed:
                    flight.install_crash_hooks(signals=prev_signums)
                flight._discard_events(
                    lambda ev: ev[1] == "selfcheck" or (
                        ev[1] == "crash"
                        and _SEEDED_MSG in str(ev[5] or "")))
        checks["flight"] = ok_flight
        if not ok_flight:
            details.append(
                f"flight: {len(dumps)} dump(s), seeded event missing")
    except Exception as e:  # noqa: BLE001
        checks["flight"] = False
        details.append(f"flight self-check crashed: {e!r}")

    # 6) lock shim: an AB/BA inversion must come back as a PTK001 cycle
    try:
        from .locks import LockAuditor
        aud = LockAuditor()
        a, b = aud.lock("selfcheck.A"), aud.lock("selfcheck.B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        ab()
        t = threading.Thread(target=ba)
        t.start()
        t.join()
        diags = aud.diagnostics()
        checks["locks"] = any(d.rule == "PTK001" for d in diags)
        if not checks["locks"]:
            details.append(f"locks: edges {list(aud.edges)}, no cycle")
    except Exception as e:  # noqa: BLE001
        checks["locks"] = False
        details.append(f"locks self-check crashed: {e!r}")

    ok = all(checks.values())
    out = {"ok": ok, "checks": checks, "detail": "; ".join(details)}
    if verbose:
        status = "OK" if ok else "FAIL"
        print(f"analysis self-check: {status} "
              + " ".join(f"{k}={'ok' if v else 'FAIL'}"
                         for k, v in checks.items())
              + (f" ({out['detail']})" if details else ""))
    return out
