"""paddle_tpu.analysis — static analysis & program auditing.

Three coordinated analyzers over one diagnostics currency:

- :mod:`.auditor` — run a callable in recording mode; capture report of
  flush boundaries (reason + origin), host syncs, donations,
  use-after-donate and recompile churn (rules PTA00x).
- :mod:`.lint` — AST source linter with repo-specific rules (PTL00x)
  and a checked-in, justified allowlist.
- :mod:`.locks` — instrumented-lock shim: acquisition-order recording,
  lock-order-cycle and lock-across-device-work detection (PTK00x).
- :mod:`.capture` + :mod:`.shapes` + :mod:`.planner` — the static
  capture planner: graph-break AST analysis (PTC00x), shape/dtype
  abstract interpretation over ops.yaml ``shape:`` specs, and
  :func:`capture_plan` merging both with the dynamic audit into one
  ranked whole-step-capture plan (ROADMAP Fusion III's input).

One reporting surface: :func:`report` here, or
``python -m paddle_tpu.analysis`` on the command line.

This ``__init__`` is lazy by contract: subsystems import
``paddle_tpu.analysis.locks.make_lock`` at module load, which executes
this file — nothing heavier than stdlib may be imported here.
"""
from __future__ import annotations

__all__ = ["audit", "lint", "report", "AnalysisReport", "RULES",
           "capture_plan", "CapturePlan"]

# `lint` and `report` (the callables) share names with their defining
# submodules. Importing a submodule binds it as a package attribute,
# which would permanently shadow a lazy __getattr__ — so e.g.
# `import paddle_tpu.analysis.report` followed by `analysis.report(fn)`
# would call the MODULE. Bind the callables eagerly, AFTER the
# submodule imports below have set the module attributes: later cached
# submodule imports never rebind parent attributes, so the callables
# stay. Both modules are stdlib-only, keeping this __init__
# import-light (lint's runtime imports live inside _check_ops_yaml).
from .lint import lint            # noqa: E402,F401
from .report import report        # noqa: E402,F401

_LAZY = {
    "audit": ("paddle_tpu.analysis.auditor", "audit"),
    "Auditor": ("paddle_tpu.analysis.auditor", "Auditor"),
    "CaptureReport": ("paddle_tpu.analysis.auditor", "CaptureReport"),
    "RULES": ("paddle_tpu.analysis.diagnostics", "RULES"),
    "Diagnostic": ("paddle_tpu.analysis.diagnostics", "Diagnostic"),
    "AnalysisReport": ("paddle_tpu.analysis.report", "AnalysisReport"),
    "self_check": ("paddle_tpu.analysis.report", "self_check"),
    "capture_plan": ("paddle_tpu.analysis.planner", "capture_plan"),
    "CapturePlan": ("paddle_tpu.analysis.planner", "CapturePlan"),
    "plan_repo_steps": ("paddle_tpu.analysis.planner",
                        "plan_repo_steps"),
    "capture_scan": ("paddle_tpu.analysis.capture", "capture_scan"),
    "scan_repo_steps": ("paddle_tpu.analysis.capture",
                        "scan_repo_steps"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    mod = importlib.import_module(entry[0])
    val = getattr(mod, entry[1])
    globals()[name] = val
    return val


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
