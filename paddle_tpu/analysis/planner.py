"""The capture planner: one ranked plan from the static PTC pass + the
dynamic capture audit — the document Fusion III implements.

``capture_plan(fn)`` composes three inputs:

1. **Static graph-break scan** (:mod:`.capture`) of ``fn``'s source —
   sees every branch, including paths a recording never executed.
2. **Dynamic capture audit** (:mod:`.auditor`) — one measured run's
   flush boundaries (reason + origin), host syncs, donations and
   recompile churn. Every dynamic event origin is then *closed over
   statically*: the planner locates the enclosing function of each
   origin and scans it too, so a sync attributed to
   ``hapi/model.py:96`` is covered by a PTC diagnostic at that line.
3. **SOT segment metadata** (:meth:`SOTFunction.capture_metadata`) when
   ``fn`` is already a traced function — recorded segments and guards
   are the ground-truth segmentation the plan refines.

The product is a **break table** ranked by measured flush cost (how
often the site actually flushed in the measured step) where every row
is classified:

- ``compatible`` — whole-step capture absorbs it (op/reduce/matmul
  boundaries become recorded segment ops; ``backward`` is the tape
  boundary the captured program owns; ``donation``/``cap`` vanish
  inside one executable), or a checked-in CAPTURE_ALLOWLIST entry
  explains it;
- ``hoist`` — a host read that postdominates device work, with the
  "move after step" fix (PTC003);
- ``guard`` — a data-dependent branch / mid-step read the SOT trace
  must guard (PTC001/PTC003);
- ``bucket`` — a shape-polymorphic site needing a BucketPolicy
  (PTC004, synthesized from PTA003 churn rows with the bounded-
  executables count from :mod:`.shapes`);
- ``side_effect`` — a PTC002 hazard that forces a region cut;
- ``unaccounted`` — a dynamic break no static finding covers: the plan
  is not trustworthy until it is (the consistency contract
  ``CapturePlan.consistent()`` that tests pin).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from .diagnostics import Diagnostic, sort_diagnostics
from .capture import (REPO_STEPS, apply_allowlist, capture_scan,
                      enclosing_function_scan, scan_file_function)
from .lint import REPO_ROOT

__all__ = ["CapturePlan", "capture_plan", "plan_repo_steps"]

# flush reasons whole-step capture absorbs by construction
_ABSORBED = {
    "op_boundary": "absorbed: a non-fusable consumer becomes a recorded "
                   "segment op inside the whole-step trace",
    "reduce_boundary": "absorbed: reduction joins the captured program",
    "matmul_boundary": "absorbed: contraction joins the captured "
                       "program",
    "backward": "absorbed: the tape boundary sits inside the captured "
                "step (the whole-step program owns the VJP)",
    "donation": "absorbed: the donated optimizer step is part of the "
                "captured executable",
    "cap": "absorbed: the chain-length cap is an eager-plane limit; "
           "capture has no per-chain cap",
    "grad_leaf": "absorbed: stop_gradient re-leafing is resolved at "
                 "trace time",
    "sot_capture": "absorbed: the segment handoff INTO a captured "
                   "whole-step executable — pending eager chains flush "
                   "at the capture boundary by design "
                   "(fusion.capture_handoff)",
}


def _file_match(a: str, b: str) -> bool:
    """Do two (possibly differently-shortened) file paths name the same
    file? Dynamic origins carry the last two components; static
    locations are repo-relative."""
    a, b = a.split(":")[0], b.split(":")[0]
    return a == b or a.endswith("/" + b) or b.endswith("/" + a)


def _origin_to_path(origin: str) -> Optional[str]:
    """Resolve a dynamic origin ('hapi/model.py:96') to a real file."""
    rel = origin.rsplit(":", 1)[0]
    for cand in (os.path.join(REPO_ROOT, "paddle_tpu", rel),
                 os.path.join(REPO_ROOT, rel),
                 os.path.join(REPO_ROOT, os.path.basename(rel))):
        if os.path.exists(cand):
            return cand
    return None


def _origin_line(origin: str) -> int:
    tail = origin.rsplit(":", 1)[-1]
    return int(tail) if tail.isdigit() else -1


class CapturePlan:
    """The segmentation proposal. ``breaks`` is the ranked work list;
    ``regions`` the per-function capture segments between breaks;
    ``diagnostics`` every static + synthesized finding."""

    def __init__(self):
        self.static_diags: List[Diagnostic] = []
        self.suppressed: List[Tuple[Diagnostic, str]] = []
        self.synthesized: List[Diagnostic] = []   # PTC004 from PTA003
        self.capture = None                       # dynamic CaptureReport
        self.functions: List[Dict[str, Any]] = []
        self.breaks: List[Dict[str, Any]] = []
        self.regions: List[Dict[str, Any]] = []
        self.sot: Optional[Dict[str, Any]] = None

    @property
    def diagnostics(self) -> List[Diagnostic]:
        return sort_diagnostics(self.static_diags + self.synthesized)

    def unaccounted(self) -> List[Dict[str, Any]]:
        return [b for b in self.breaks
                if b["classification"] == "unaccounted"]

    def consistent(self) -> bool:
        """The acceptance contract: every dynamic host sync and flush
        boundary is either covered by a PTC diagnostic with a fix hint
        or explicitly classified capture-compatible."""
        return not self.unaccounted()

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "breaks": self.breaks,
            "regions": self.regions,
            "diagnostics": [x.to_dict() for x in self.diagnostics],
            "suppressed": [{"rule": x.rule, "location": x.location,
                            "reason": why}
                           for x, why in self.suppressed],
            "consistent": self.consistent(),
        }
        if self.capture is not None:
            d["dynamic"] = {"flush_sites": self.capture.flush_sites(),
                            "syncs": len(self.capture.syncs),
                            "donations": len(self.capture.donations)}
        if self.sot is not None:
            d["sot"] = self.sot
        return d

    def render(self) -> str:
        lines = ["capture plan", "=" * 12]
        if self.breaks:
            lines.append(
                "  breaks (ranked by measured flush cost; each row is "
                "a Fusion III work item):")
            for b in self.breaks:
                cnt = b["count"]
                lines.append(
                    f"  #{b['rank']:<3}{b['site']:<44} "
                    f"{b['reason']:<16} x{cnt:<5} {b['classification']}")
                if b.get("fix"):
                    lines.append(f"        fix: {b['fix']}")
        else:
            lines.append("  breaks: none — the step captures whole")
        for r in self.regions:
            lines.append(
                f"  region {r['index']}: {r['function']} "
                f"[{r['file']}:{r['from_line']}-{r['to_line']}] "
                f"guards={len(r['guards'])} hoists={len(r['hoists'])}")
        if self.sot is not None:
            n_paths = len(self.sot.get("paths", []))
            lines.append(f"  sot: {n_paths} recorded path(s), "
                         f"fallback reasons "
                         f"{self.sot.get('fallback_reasons', {})}")
        if self.suppressed:
            lines.append("  capture-compatible by allowlist:")
            for d, why in self.suppressed:
                lines.append(f"    {d.rule} @ {d.location} — {why}")
        lines.append(f"  consistent: {self.consistent()}   "
                     f"diagnostics: {len(self.diagnostics)}   "
                     f"unaccounted: {len(self.unaccounted())}")
        return "\n".join(lines)


def _classify(d: Diagnostic) -> str:
    """Break classification for a static PTC finding — the ONE mapping
    both the dynamic-match path and the static-only rows use."""
    if d.rule == "PTC002":
        return "side_effect"
    if d.rule == "PTC004":
        return "bucket"
    if d.rule == "PTC003" and d.data.get("hoistable"):
        return "hoist"
    return "guard"  # PTC001, or a mid-step PTC003 read


def _match_static(plan: CapturePlan, origin: str):
    """Find the static finding covering a dynamic origin: exact
    file:line first, then any PTC diag inside the enclosing scanned
    function span. Searches suppressed (allowlisted) findings too —
    they classify the row as compatible."""
    line = _origin_line(origin)

    def hit(d: Diagnostic) -> bool:
        if not _file_match(d.location, origin):
            return False
        dline = _origin_line(d.location)
        if dline == line:
            return True
        for meta in plan.functions:
            lo, hi = meta["span"]
            if _file_match(meta["file"], origin) and lo <= line <= hi \
                    and lo <= dline <= hi:
                return True
        return False

    for d in plan.static_diags:
        if hit(d):
            return d, None
    for d, why in plan.suppressed:
        if hit(d):
            return d, why
    return None, None


def _scan_into(plan: CapturePlan, diags, meta, use_allowlist: bool):
    kept, supp = apply_allowlist(
        diags, (meta or {}).get("pragmas"), use_allowlist)
    plan.static_diags.extend(kept)
    plan.suppressed.extend(supp)
    if meta is not None:
        plan.functions.append(meta)


def capture_plan(fn: Optional[Callable] = None, *args,
                 warmup: int = 2, dynamic: bool = True,
                 use_allowlist: bool = True, **kwargs) -> CapturePlan:
    """Plan whole-step capture for ``fn`` (a train/decode step
    callable). ``dynamic=False`` skips running the function (static
    scan only — the CLI's mode). See module docstring for the merge
    semantics."""
    plan = CapturePlan()
    # dedupe scans by (file, span): the two scan paths name functions
    # differently (__qualname__ vs bare def name), but a source span
    # is unambiguous
    scanned_spans = set()
    if fn is not None:
        try:
            diags, meta = capture_scan(fn)
            _scan_into(plan, diags, meta, use_allowlist)
            scanned_spans.add((meta["file"], tuple(meta["span"])))
        except ValueError:
            pass  # no source (builtin/C callable): dynamic-only plan
    if fn is not None and dynamic:
        from .auditor import audit
        plan.capture = audit(fn, *args, warmup=warmup, **kwargs)
        # close dynamic origins over statically: scan every enclosing
        # function the audit attributed an event to
        origins = [ev["origin"] for ev in plan.capture.syncs]
        origins += [ev["origin"] for ev in plan.capture.flushes
                    if ev["reason"] == "host_read"]
        for origin in dict.fromkeys(origins):
            path = _origin_to_path(origin)
            line = _origin_line(origin)
            if path is None or line < 0:
                continue
            diags, meta = enclosing_function_scan(path, line)
            if meta is None:
                continue
            key = (meta["file"], tuple(meta["span"]))
            if key in scanned_spans:
                continue
            scanned_spans.add(key)
            _scan_into(plan, diags, meta, use_allowlist)
        _merge_dynamic(plan)
    # SOT segment/guard metadata, when fn is a traced function
    md = getattr(fn, "capture_metadata", None)
    if callable(md):
        try:
            plan.sot = md()
        except Exception:  # noqa: BLE001 — metadata is best-effort
            plan.sot = None
    _build_static_breaks(plan)
    _rank(plan)
    _build_regions(plan)
    _count_metrics(plan)
    return plan


def _merge_dynamic(plan: CapturePlan) -> None:
    rep = plan.capture
    # host_read flush sites only: a sync colocated with an absorbed
    # op_boundary/backward row still needs its own coverage row
    read_sites = set()
    # flush boundaries
    for row in rep.flush_sites(top_n=10 ** 9):
        site, reason, count = row["site"], row["reason"], row["count"]
        entry = {"site": site, "reason": reason, "count": count,
                 "rule": None, "fix": None}
        if reason in _ABSORBED:
            entry["classification"] = "compatible"
            entry["fix"] = _ABSORBED[reason]
        elif reason in ("host_read", "mutation", "hook"):
            d, why = _match_static(plan, site)
            if d is None:
                entry["classification"] = "unaccounted"
                entry["fix"] = ("no static finding covers this break — "
                                "scan the enclosing code or extend the "
                                "PTC detectors")
            else:
                entry["rule"] = d.rule
                if why is not None:
                    entry["classification"] = "compatible"
                    entry["fix"] = f"allowlisted: {why}"
                else:
                    entry["classification"] = _classify(d)
                    entry["fix"] = d.hint
        else:
            entry["classification"] = "compatible"
            entry["fix"] = f"eager-plane flush ({reason}); not a " \
                           f"capture boundary"
        if reason == "host_read":
            read_sites.add(site)
        plan.breaks.append(entry)
    # host syncs not already represented by a host_read flush site
    sync_sites: Dict[str, int] = {}
    for ev in rep.syncs:
        sync_sites[ev["origin"]] = sync_sites.get(ev["origin"], 0) + 1
    for site, count in sorted(sync_sites.items()):
        if any(_file_match(site, s) and
               _origin_line(site) == _origin_line(s)
               for s in read_sites):
            continue
        d, why = _match_static(plan, site)
        entry = {"site": site, "reason": "host_sync", "count": count,
                 "rule": d.rule if d else None}
        if d is None:
            entry["classification"] = "unaccounted"
            entry["fix"] = "no static finding covers this sync"
        elif why is not None:
            entry["classification"] = "compatible"
            entry["fix"] = f"allowlisted: {why}"
        else:
            entry["classification"] = _classify(d)
            entry["fix"] = d.hint
        plan.breaks.append(entry)
    # PTA003 churn -> PTC004 bucket rows (the dynamic cross-check)
    from .shapes import bucketed_leaf_signatures
    # illustrative bound, computed once: pow2 bucketing of ONE dynamic
    # axis over sizes <= 4096 (the site's real axis range may differ —
    # re-derive with its observed sizes when implementing the policy)
    pow2_bound = len(bucketed_leaf_signatures((1,), {0: "pow2"}, 4096))
    for d in rep.diagnostics:
        if d.rule != "PTA003" or "shape-polymorphic" not in d.message:
            continue
        syn = Diagnostic(
            "PTC004", d.location,
            f"shape-polymorphic call site (dynamic audit: {d.message})",
            hint=f"declare a BucketPolicy on the varying axis — e.g. "
                 f"pow2 buckets cap the compile cache at {pow2_bound} "
                 f"executables for sizes <= 4096, vs one per distinct "
                 f"size (re-derive with the site's observed sizes via "
                 f"shapes.bucketed_leaf_signatures)",
            data={"from": "PTA003"})
        plan.synthesized.append(syn)
        plan.breaks.append({
            "site": d.location, "reason": "recompile_churn",
            "count": 0, "rule": "PTC004",
            "classification": "bucket", "fix": syn.hint})


def _build_static_breaks(plan: CapturePlan) -> None:
    """Static findings with no dynamic row (paths the measured run
    never took) still enter the break table — that is the static
    pass's whole value — at count 0."""
    for d in plan.static_diags:
        line = _origin_line(d.location)
        if any(_file_match(d.location, b["site"])
               and _origin_line(b["site"]) == line
               for b in plan.breaks):
            continue
        plan.breaks.append({
            "site": d.location, "reason": "static", "count": 0,
            "rule": d.rule, "classification": _classify(d),
            "fix": d.hint})


def _rank(plan: CapturePlan) -> None:
    plan.breaks.sort(
        key=lambda b: (-b["count"],
                       b["classification"] == "compatible",
                       b["site"]))
    for i, b in enumerate(plan.breaks):
        b["rank"] = i + 1


def _build_regions(plan: CapturePlan) -> None:
    """Per scanned function: the capture segments between its
    non-compatible breaks, with the guards/hoists each needs."""
    for idx, meta in enumerate(plan.functions):
        lo, hi = meta["span"]
        inside = [b for b in plan.breaks
                  if _file_match(b["site"], meta["file"])
                  and lo <= _origin_line(b["site"]) <= hi
                  and b["classification"] not in ("compatible",)]
        guards = [b for b in inside
                  if b["classification"] in ("guard", "bucket")]
        hoists = [b for b in inside if b["classification"] == "hoist"]
        cuts = [b for b in inside
                if b["classification"] == "side_effect"]
        plan.regions.append({
            "index": idx, "file": meta["file"],
            "function": meta["function"],
            "from_line": lo, "to_line": hi,
            "segments": len(cuts) + len(guards) + 1,
            "guards": [b["site"] for b in guards],
            "hoists": [b["site"] for b in hoists],
            "cuts": [b["site"] for b in cuts]})


def _count_metrics(plan: CapturePlan) -> None:
    try:
        from ..observability import metrics as _om
        _om.counter("analysis.capture_plans_total",
                    "Capture plans produced by the analysis plane").inc()
        cd = _om.counter(
            "analysis.diagnostics_total",
            "Diagnostics emitted by the analysis plane, by rule")
        for d in plan.diagnostics:
            cd.inc(rule=d.rule)
    except Exception:  # noqa: BLE001 — planning must work standalone
        pass


def plan_repo_steps(use_allowlist: bool = True) -> CapturePlan:
    """Static-only plan over the repo's own step functions (the
    ``--capture-plan`` CLI default: no model run, just the source
    truth)."""
    plan = CapturePlan()
    for rel, qual, params in REPO_STEPS:
        path = os.path.join(REPO_ROOT, rel)
        if not os.path.exists(path):
            continue
        diags, meta = scan_file_function(path, qual, params)
        _scan_into(plan, diags, meta, use_allowlist)
    _build_static_breaks(plan)
    _rank(plan)
    _build_regions(plan)
    _count_metrics(plan)
    return plan
