"""Static-graph Executor: replays a Program tape as one jitted function.

ref: python/paddle/base/executor.py Executor.run -> StandaloneExecutor
(SURVEY.md §3.3). TPU-native: the whole Program (and, when an optimizer
was attached by minimize(), loss -> grads -> optimizer update) compiles to
ONE XLA executable per (feed shapes, fetch set) signature, cached.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .program import Program, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    def __init__(self):
        self.vars = {}

    def find_var(self, name):
        return self.vars.get(name)


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


def _replay(program: Program, feed_vals: Dict[str, jax.Array],
            ref_vals: Sequence[jax.Array], rng_vals: Sequence = (),
            overrides: Optional[Dict[int, jax.Array]] = None):
    """Pure replay of the tape. Returns env mapping tensor-id -> value.
    ``overrides`` substitutes a produced var's value right after its op —
    this is how gradients() differentiates w.r.t. an intermediate: the
    override value becomes the graph input at that cut point."""
    env: Dict[int, jax.Array] = {}

    def resolve(spec):
        kind, v = spec
        if kind == "feed":
            return feed_vals[v]
        if kind == "var":
            return env[v]
        if kind == "ref":
            return ref_vals[v]
        if kind == "rng":
            return rng_vals[v]
        return v

    for op in program.ops:
        vals = [resolve(spec) for spec in op.arg_specs]
        out = op.fn(*vals, **op.kwargs)
        outs = tuple(out) if isinstance(out, (tuple, list)) else (out,)
        for oid, o in zip(op.out_ids, outs):
            if oid is not None:
                env[oid] = o
                if overrides is not None and oid in overrides:
                    env[oid] = overrides[oid]
    return env


def _grad_fetches(program: Program, fetch_list, feed_arrays, ref_vals,
                  rng_vals):
    """Resolve gradient-handle fetches (append_backward / gradients) by
    differentiating the pure replay. Returns {fetch_index: value}.
    Handles are grouped by target expression so each group costs one
    jax.grad trace (XLA CSE merges the repeated forward subgraphs)."""
    groups: Dict[tuple, list] = {}
    for i, t in enumerate(fetch_list):
        req = program._grad_handles.get(id(t))
        if req is not None:
            targets, wrt_spec = req
            groups.setdefault(targets, []).append((i, wrt_spec))
    out: Dict[int, jax.Array] = {}
    for targets, members in groups.items():
        specs = [s for (_, s) in members]

        def scalar(vals, targets=targets, specs=specs):
            feeds2 = dict(feed_arrays)
            refs2 = list(ref_vals)
            overrides = {}
            for spec, v in zip(specs, vals):
                kind, key = spec
                if kind == "ref":
                    refs2[key] = v
                elif kind == "feed":
                    feeds2[key] = v
                elif kind == "var":
                    overrides[key] = v
            env = _replay(program, feeds2, refs2, rng_vals,
                          overrides=overrides)
            tot = jnp.float32(0.0)
            for tid, tg_spec in targets:
                tv = env[tid].astype(jnp.float32)
                if tg_spec is None:
                    tot = tot + jnp.sum(tv)
                else:
                    kind, key = tg_spec
                    tg = (env[key] if kind == "var" else
                          ref_vals[key] if kind == "ref" else
                          feed_arrays[key])
                    tot = tot + jnp.sum(tv * tg.astype(jnp.float32))
            return tot

        def current(spec):
            kind, key = spec
            if kind == "ref":
                return ref_vals[key]
            if kind == "feed":
                return feed_arrays[key]
            # var: its forward value from a plain replay
            env = _replay(program, feed_arrays, ref_vals, rng_vals)
            return env[key]

        vals = [current(s) for s in specs]
        gs = jax.grad(scalar)(vals)
        for (i, _), g in zip(members, gs):
            out[i] = g
    return out


def _lookup_fetch(program, env, feed_arrays, ref_vals, t: Tensor):
    tid = id(t)
    if tid in env:
        return env[tid]
    name = getattr(t, "_static_feed_name", None)
    if name is not None and name in feed_arrays:
        return feed_arrays[name]
    slot = program._refs.get(tid)
    if slot is not None:
        # resolve through ref_vals (a traced input), NOT t._data: inside
        # jit the latter would bake the current value in as a constant
        return ref_vals[slot]
    raise KeyError(
        f"fetch target {getattr(t, 'name', t)} is not produced by this "
        f"program (was it created outside the program_guard?)")


class Executor:
    """ref: static.Executor. `place` is accepted for API parity; execution
    always targets the default JAX backend."""

    _CACHE_MAX = 64  # LRU bound: cached closures pin their Program (and
    # its parameters), so an unbounded cache would leak retired programs

    def __init__(self, place=None):
        from collections import OrderedDict
        self.place = place
        self._cache: "OrderedDict[tuple, object]" = OrderedDict()

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence[Tensor]] = None,
            return_numpy: bool = True):
        # loaded inference programs carry their own compiled callable
        if program is not None and hasattr(program, "_exported_call"):
            return program.run(feed, fetch_list, return_numpy)
        if program is None:
            program = default_main_program()
        if not program.ops:  # e.g. the startup program: params are already
            return []        # initialized eagerly at Layer construction
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        feed_arrays = {k: jnp.asarray(np.asarray(v)) for k, v in
                       feed.items()}

        opt = program._optimizer
        key = (id(program), program.version,
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feed_arrays.items())),
               tuple(id(t) for t in fetch_list), id(opt) if opt else None)
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._compile(program, fetch_list, opt)
            self._cache[key] = compiled
            if len(self._cache) > self._CACHE_MAX:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(key)
        outs = compiled(feed_arrays)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    # -- compilation --------------------------------------------------------
    def _compile(self, program: Program, fetch_list, opt):
        ref_tensors = list(program._ref_tensors)
        buf_updates = list(program._buffer_updates)
        buf_src_ids = [sid for (_, sid, _) in buf_updates]

        def _apply_buffer_updates(buf_vals):
            for (buf, _, fn), val in zip(buf_updates, buf_vals):
                buf._data = fn(buf._data, val)

        n_rng = program._rng_count

        def _fresh_keys():
            from ..core import random as random_mod
            return [random_mod.next_key() for _ in range(n_rng)]

        grad_ids = {i for i, t in enumerate(fetch_list)
                    if id(t) in program._grad_handles}

        if opt is None:
            @jax.jit
            def pure(feed_arrays, ref_vals, rng_vals):
                env = _replay(program, feed_arrays, ref_vals, rng_vals)
                fetches = [None if i in grad_ids else
                           _lookup_fetch(program, env, feed_arrays,
                                         ref_vals, t)
                           for i, t in enumerate(fetch_list)]
                for i, g in _grad_fetches(program, fetch_list, feed_arrays,
                                          ref_vals, rng_vals).items():
                    fetches[i] = g
                return fetches, [env[sid] for sid in buf_src_ids]

            def run(feed_arrays):
                ref_vals = [t._data for t in ref_tensors]
                fetches, buf_vals = pure(feed_arrays, ref_vals,
                                         _fresh_keys())
                _apply_buffer_updates(buf_vals)
                return fetches

            return run

        # optimizer attached by minimize(): param slots get grads + updates
        if opt._grad_clip is not None:
            import warnings
            warnings.warn(
                "grad_clip is not yet applied on the static-graph path; "
                "use the dygraph path or clip-free optimizers here")
        loss_t = program._loss
        params = [t for t in ref_tensors
                  if not t.stop_gradient and
                  any(t is p for p in opt._parameter_list)]
        param_slots = [program._refs[id(p)] for p in params]

        def loss_of(param_vals, feed_arrays, ref_vals, rng_vals):
            full = list(ref_vals)
            for s, v in zip(param_slots, param_vals):
                full[s] = v
            env = _replay(program, feed_arrays, full, rng_vals)
            return env[id(loss_t)], env

        @jax.jit
        def pure(feed_arrays, ref_vals, param_vals, states, lr, rng_vals):
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals, feed_arrays, ref_vals,
                                       rng_vals)
            new_params, new_states = [], []
            for p_t, p, g, s in zip(params, param_vals, grads, states):
                # same per-param contract as eager step(): regularizer
                # penalty, then the pure update; _cur_param/_current_pid
                # feed trace-time metadata lookups (Lamb exclude fn,
                # AdamW apply_decay_param_fun)
                opt._cur_param = p_t
                opt._current_pid = id(p_t)
                g = opt._apply_regularizer(p, g)
                np_, ns = opt._update(p, g, s, lr)
                new_params.append(np_)
                new_states.append(ns)
            fetches = [None if i in grad_ids else
                       _lookup_fetch(program, env, feed_arrays, ref_vals, t)
                       for i, t in enumerate(fetch_list)]
            for i, g in _grad_fetches(program, fetch_list, feed_arrays,
                                      ref_vals, rng_vals).items():
                fetches[i] = g
            return fetches, new_params, new_states, \
                [env[sid] for sid in buf_src_ids]

        def run(feed_arrays):
            ref_vals = [t._data for t in ref_tensors]
            param_vals = [p._data for p in params]
            states = [opt._state_for(p) for p in params]
            lr = opt.get_lr()
            fetches, new_params, new_states, buf_vals = pure(
                feed_arrays, ref_vals, param_vals, states,
                jnp.float32(lr), _fresh_keys())
            opt._global_step += 1
            for p, v, ns in zip(params, new_params, new_states):
                p._data = v
                opt._states[id(p)] = ns
            _apply_buffer_updates(buf_vals)
            return fetches

        return run
