"""paddle.static equivalent: record/replay static graphs compiled by XLA.

ref: python/paddle/static/__init__.py. SURVEY.md layer 14 (paddle.static
Program/Executor). The reference's ProgramDesc + StandaloneExecutor pair
maps to an op tape recorded from the eager stream and replayed as one
jitted function (§3.3 call stack collapses to a single XLA launch).

    paddle.enable_static()
    x = paddle.static.data("x", [None, 4], "float32")
    y = paddle.matmul(x, w)
    loss = ...
    opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(paddle.static.default_startup_program())
    out, = exe.run(feed={"x": arr}, fetch_list=[loss])
"""
from __future__ import annotations

from ..jit.api import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    Program, data, default_main_program, default_startup_program,
    program_guard,
)
from .executor import Executor, global_scope  # noqa: F401
from .io import load_inference_model, save_inference_model  # noqa: F401
from .extras import (  # noqa: F401
    Variable, BuildStrategy, CompiledProgram, ExponentialMovingAverage,
    WeightNormParamAttr, Print, py_func, accuracy, auc,
    ctr_metric_bundle, append_backward, gradients, create_global_var,
    create_parameter, cpu_places, cuda_places, xpu_places, device_guard,
    name_scope, scope_guard, save, load, save_to_file, load_from_file,
    load_program_state, set_program_state, serialize_program,
    serialize_persistables, deserialize_program, deserialize_persistables,
    normalize_program, IpuCompiledProgram, IpuStrategy, ipu_shard_guard,
    set_ipu_shard,
)
from . import nn  # noqa: F401

__all__ = [
    "InputSpec", "Program", "data", "default_main_program",
    "default_startup_program", "program_guard", "Executor", "global_scope",
    "save_inference_model", "load_inference_model", "nn",
    "Variable", "BuildStrategy", "CompiledProgram",
    "ExponentialMovingAverage", "WeightNormParamAttr", "Print", "py_func",
    "accuracy", "auc", "ctr_metric_bundle", "append_backward",
    "gradients", "create_global_var", "create_parameter", "cpu_places",
    "cuda_places", "xpu_places", "device_guard", "name_scope",
    "scope_guard", "save", "load", "save_to_file", "load_from_file",
    "load_program_state", "set_program_state", "serialize_program",
    "serialize_persistables", "deserialize_program",
    "deserialize_persistables", "normalize_program", "IpuCompiledProgram",
    "IpuStrategy", "ipu_shard_guard", "set_ipu_shard",
]
