"""save/load_inference_model via jax.export (serialized StableHLO).

ref: python/paddle/static/io.py save_inference_model (the reference
serializes a pruned ProgramDesc + params; the TPU-native artifact is a
serialized StableHLO module with the parameters baked in as constants,
loadable and runnable with no Python model code).
"""
from __future__ import annotations

import json
import os
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core.tensor import Tensor
from .executor import _lookup_fetch, _replay
from .program import Program, current_program

__all__ = ["save_inference_model", "load_inference_model"]

_MODEL_SUFFIX = ".pdmodel"
_META_SUFFIX = ".pdmeta.json"


def save_inference_model(path_prefix: str, feed_vars: Sequence[Tensor],
                         fetch_vars: Sequence[Tensor], executor=None,
                         program: Program = None, **kwargs) -> None:
    if program is None:
        program = current_program()
    if program is None:
        from .program import default_main_program
        program = default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    feed_names = [t._static_feed_name for t in feed_vars]
    ref_vals = [t._data for t in program._ref_tensors]

    from ..core import random as random_mod
    # inference export: stochastic slots (dropout keys) get fixed values —
    # export eval-mode programs for deterministic serving
    rng_vals = [random_mod.next_key() for _ in range(program._rng_count)]

    def pure(*feed_arrays):
        feeds = dict(zip(feed_names, feed_arrays))
        env = _replay(program, feeds, ref_vals, rng_vals)
        return tuple(_lookup_fetch(program, env, feeds, ref_vals, t)
                     for t in fetch_vars)

    # export with a symbolic batch dim where the placeholder declared
    # None/-1 (recorded as size 1); fall back to the concrete trace shape
    specs, symbolic = [], True
    try:
        batch = jax_export.symbolic_shape("batch")[0]
        for t in feed_vars:
            shape = list(t._data.shape)
            if shape:
                shape[0] = batch
            specs.append(jax.ShapeDtypeStruct(tuple(shape), t._data.dtype))
        exported = jax_export.export(jax.jit(pure))(*specs)
    except Exception:
        symbolic = False
        specs = [jax.ShapeDtypeStruct(t._data.shape, t._data.dtype)
                 for t in feed_vars]
        exported = jax_export.export(jax.jit(pure))(*specs)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + _MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + _META_SUFFIX, "w") as f:
        json.dump({"feed_names": feed_names,
                   "num_fetch": len(fetch_vars),
                   "symbolic_batch": symbolic}, f)


class _LoadedProgram:
    """Stands in for the inference Program returned by
    load_inference_model; Executor.run dispatches to it."""

    def __init__(self, exported, feed_names, num_fetch):
        self._exported_call = exported.call
        self.feed_names = feed_names
        self.num_fetch = num_fetch

    def run(self, feed, fetch_list=None, return_numpy=True):
        arrays = [jnp.asarray(np.asarray(feed[n])) for n in self.feed_names]
        outs = self._exported_call(*arrays)
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] like the
    reference; fetch_targets are positional indices here (the serialized
    module has no variable names)."""
    with open(path_prefix + _MODEL_SUFFIX, "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + _META_SUFFIX) as f:
        meta = json.load(f)
    prog = _LoadedProgram(exported, meta["feed_names"], meta["num_fetch"])
    return [prog, meta["feed_names"], list(range(meta["num_fetch"]))]
