"""paddle.static facade long tail.

ref: python/paddle/static/__init__.py __all__ — the user-visible names
beyond Program/Executor/data. Everything here is implemented over the
record/replay Program machinery (program.py, executor.py): gradients are
resolved by differentiating the pure replay, serialization rides the
.pdmodel/state-dict formats, and places map onto the PJRT device list.
IPU names are documented capability exclusions (no IPU backend in a TPU
build) and fail loudly.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor, Parameter
from .program import (Program, current_program, default_main_program,
                      default_startup_program)

__all__ = [
    "Variable", "BuildStrategy", "CompiledProgram",
    "ExponentialMovingAverage", "WeightNormParamAttr", "Print",
    "py_func", "accuracy", "auc", "ctr_metric_bundle",
    "append_backward", "gradients", "create_global_var",
    "create_parameter", "cpu_places", "cuda_places", "xpu_places",
    "device_guard", "name_scope", "scope_guard", "save", "load",
    "save_to_file", "load_from_file", "load_program_state",
    "set_program_state", "serialize_program", "serialize_persistables",
    "deserialize_program", "deserialize_persistables", "normalize_program",
    "IpuCompiledProgram", "IpuStrategy", "ipu_shard_guard",
    "set_ipu_shard",
]

# The reference's static Variable is the graph-tensor handle
# (python/paddle/base/framework.py Variable); in the record/replay design
# the recorded Tensor IS that handle, so the name is an alias, not a
# parallel class hierarchy.
Variable = Tensor


class BuildStrategy:
    """ref: static.BuildStrategy — pass-selection knobs for the legacy
    graph engine (fuse_*, reduce strategy, …). Under XLA the fusion
    decisions belong to the compiler, so these knobs are accepted,
    recorded, and surfaced via repr for tooling parity; they do not steer
    XLA (which already performs the fusions they request)."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = True
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_auto_fusion = False
        self.memory_optimize = None
        self.enable_inplace = False
        self.build_cinn_pass = False
        self.debug_graphviz_path = ""

    def __repr__(self):
        flags = {k: v for k, v in self.__dict__.items()}
        return f"BuildStrategy({flags})"


class CompiledProgram:
    """ref: static.CompiledProgram(program, build_strategy). The reference
    wraps a Program for the ParallelExecutor path; here compilation is the
    Executor's per-signature jit cache, so this carries the program +
    strategy and the Executor unwraps it."""

    def __init__(self, program: Program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, name):
        return getattr(self.program, name)


class ExponentialMovingAverage:
    """ref: static.ExponentialMovingAverage (static/ema.py): shadow
    variables updated as ema = decay*ema + (1-decay)*param, with the
    bias-corrected apply/restore swap used for evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._step = 0
        self._shadow: dict = {}
        self._backup: dict = {}
        self._params: List[Parameter] = []

    def _ensure(self, params):
        import jax.numpy as jnp
        for p in params:
            if id(p) not in self._shadow:
                self._params.append(p)
                self._shadow[id(p)] = jnp.asarray(p._data,
                                                  jnp.float32)

    def update(self, parameters: Optional[Sequence] = None):
        """One EMA step over ``parameters`` (default: every Parameter of
        the default main program / previously tracked set)."""
        import jax.numpy as jnp
        if parameters is None:
            prog = current_program() or default_main_program()
            parameters = prog.parameters() or self._params
        self._ensure(parameters)
        self._step += 1
        d = self.decay
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1.0 - d) * p._data.astype(
                jnp.float32)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap EMA weights in (bias-corrected); restore on exit."""
        bias = 1.0 - self.decay ** max(self._step, 1)
        for p in self._params:
            self._backup[id(p)] = p._data
            p._data = (self._shadow[id(p)] / bias).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))


class WeightNormParamAttr:
    """ref: static.WeightNormParamAttr — parameter attribute requesting
    the w = g * v/||v|| reparameterization along ``dim``. Consumed by
    static.create_parameter below; dygraph layers get the same effect
    from paddle.nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=False,
          print_tensor_lod=False, print_phase="both"):
    """ref: static.Print — identity op that prints the tensor at run
    time. jax.debug.print fires on every replay of the compiled program
    (the reference prints from the op's Run)."""
    import jax
    from ..core.autograd import apply_op

    msg = message or getattr(input, "name", "var")

    def f(x):
        jax.debug.print(msg + ": {}", x)
        return x

    return apply_op(f, input, op_name="print")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref: static.py_func — wrap a host-side python callable as an op.
    TPU-native: jax.pure_callback (host round-trip per replay); the
    optional backward_func becomes the op's custom VJP."""
    import jax
    import jax.numpy as jnp
    from ..core.autograd import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
              for o in outs]

    def host(*arrs):
        res = func(*arrs)
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r) for r in res)

    def f(*vals):
        res = jax.pure_callback(host, shapes, *vals)
        return res if len(res) > 1 else res[0]

    if backward_func is not None:
        in_shapes = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
                     for t in xs]

        def bwd_host(*arrs):
            grads = backward_func(*arrs)
            grads = grads if isinstance(grads, (list, tuple)) else [grads]
            return tuple(np.asarray(g) for g in grads)

        @jax.custom_vjp
        def op(*vals):
            return f(*vals)

        def fwd(*vals):
            return f(*vals), vals

        def bwd(res_vals, g):
            # the backward is a host callable too — it must go through
            # pure_callback, not run on traced values
            gs = g if isinstance(g, (list, tuple)) else (g,)
            grads = jax.pure_callback(bwd_host, tuple(in_shapes),
                                      *res_vals, *gs)
            return tuple(grads)

        op.defvjp(fwd, bwd)
        return apply_op(op, *xs, op_name="py_func")
    return apply_op(f, *xs, op_name="py_func")


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """ref: static.accuracy — top-k accuracy over softmax scores.
    input [N, C] scores, label [N] or [N, 1] int."""
    import jax.numpy as jnp
    from ..core.autograd import apply_op

    def f(scores, lbl):
        if lbl.ndim == scores.ndim:
            lbl = lbl.reshape(lbl.shape[0])
        topk = jnp.argsort(-scores, axis=-1)[:, :k]
        hit = (topk == lbl[:, None].astype(topk.dtype)).any(axis=1)
        return hit.mean(dtype=jnp.float32)

    return apply_op(f, input, label, op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """ref: static.auc — streaming ROC-AUC via threshold buckets. Returns
    (auc_value, batch_auc, [stat_pos, stat_neg]) like the reference; the
    stat tensors are live buffers the caller can reset."""
    import jax.numpy as jnp
    from ..core.autograd import apply_op

    if curve != "ROC":
        raise ValueError(f"auc curve {curve!r} not supported (ROC only)")
    nb = num_thresholds + 1
    stat_pos = Tensor(jnp.zeros((nb,), jnp.float32))
    stat_neg = Tensor(jnp.zeros((nb,), jnp.float32))

    def f(scores, lbl, sp, sn):
        pos_score = scores[:, 1] if scores.ndim == 2 and \
            scores.shape[1] >= 2 else scores.reshape(-1)
        if lbl.ndim == 2:
            lbl = lbl.reshape(-1)
        bucket = jnp.clip((pos_score * num_thresholds).astype(jnp.int32),
                          0, num_thresholds)
        pos = (lbl > 0).astype(jnp.float32)
        bp = jnp.zeros((nb,), jnp.float32).at[bucket].add(pos)
        bn = jnp.zeros((nb,), jnp.float32).at[bucket].add(1.0 - pos)

        def _auc(p, n):
            # sweep thresholds high->low accumulating TP/FP trapezoids
            tp = jnp.cumsum(p[::-1])
            fp = jnp.cumsum(n[::-1])
            tot_p = jnp.maximum(tp[-1], 1e-12)
            tot_n = jnp.maximum(fp[-1], 1e-12)
            tpr = tp / tot_p
            fpr = fp / tot_n
            tpr0 = jnp.concatenate([jnp.zeros((1,)), tpr[:-1]])
            fpr0 = jnp.concatenate([jnp.zeros((1,)), fpr[:-1]])
            return jnp.sum((fpr - fpr0) * (tpr + tpr0) / 2.0)

        sp_new = sp + bp
        sn_new = sn + bn
        return _auc(sp_new, sn_new), _auc(bp, bn), sp_new, sn_new

    out = apply_op(f, input, label, stat_pos, stat_neg, op_name="auc")
    auc_val, batch_auc, sp_new, sn_new = out
    # streaming state: carry forward eagerly; under a recorded program the
    # buffer-update hook replays the accumulation every Executor.run
    prog = current_program()
    if prog is not None:
        prog.register_buffer_update(stat_pos, sp_new,
                                    lambda old, new: new)
        prog.register_buffer_update(stat_neg, sn_new,
                                    lambda old, new: new)
    else:
        stat_pos._data = sp_new._data
        stat_neg._data = sn_new._data
    return auc_val, batch_auc, [stat_pos, stat_neg]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """ref: static.ctr_metric_bundle — (auc, sqrerr, abserr, prob, q,
    pos, total) used by CTR jobs. Returns the locally computable subset
    with the same ordering contract."""
    import jax.numpy as jnp
    from ..core.autograd import apply_op

    auc_val, _, stats = auc(input, label)

    def f(scores, lbl):
        p = scores[:, 1] if scores.ndim == 2 and scores.shape[1] >= 2 \
            else scores.reshape(-1)
        y = (lbl.reshape(-1) > 0).astype(jnp.float32)
        sqrerr = jnp.sum((p - y) ** 2)
        abserr = jnp.sum(jnp.abs(p - y))
        prob = jnp.sum(p)
        q = jnp.sum(p * p)
        pos = jnp.sum(y)
        total = jnp.float32(y.shape[0])
        return sqrerr, abserr, prob, q, pos, total

    sqrerr, abserr, prob, q, pos, total = apply_op(
        f, input, label, op_name="ctr_metric_bundle")
    return auc_val, sqrerr, abserr, prob, q, pos, total


# -- gradients ------------------------------------------------------------

def _make_grad_handle(prog: Program, targets, wrt_spec, like: Tensor,
                      name: str):
    import jax.numpy as jnp
    h = Tensor(jnp.zeros_like(like._data))
    h.stop_gradient = True
    h.name = name
    prog._grad_handles[id(h)] = (targets, wrt_spec)
    # keep the handle alive with the program
    prog._produced.setdefault(id(h), h)
    return h


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """ref: static.append_backward — add the backward pass for ``loss``
    and return [(param, grad_var), ...]. The grad vars are fetchable from
    Executor.run; they resolve by differentiating the pure replay
    (executor._grad_fetches), the record/replay analog of appending grad
    ops to the ProgramDesc."""
    prog = current_program() or default_main_program()
    if parameter_list is None:
        parameter_list = prog.parameters()
    no_grad = set(id(t) for t in (no_grad_set or ()))
    targets = ((id(loss), None),)
    out = []
    for p in parameter_list:
        if id(p) in no_grad or p.stop_gradient:
            continue
        slot = prog._refs.get(id(p))
        if slot is None:
            slot = prog._ref_slot(p)
        h = _make_grad_handle(prog, targets, ("ref", slot), p,
                              f"{getattr(p, 'name', 'param')}@GRAD")
        out.append((p, h))
    prog._loss = loss
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref: static.gradients — d(sum targets)/d(inputs) as fetchable
    vars. ``target_gradients`` weights each target (implicit ones when
    None), matching the reference's output_grads contract."""
    prog = current_program() or default_main_program()
    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    tgs = target_gradients if target_gradients is not None else \
        [None] * len(ts)
    if not isinstance(tgs, (list, tuple)):
        tgs = [tgs]
    if len(tgs) != len(ts):
        raise ValueError(
            f"gradients: target_gradients has {len(tgs)} entries for "
            f"{len(ts)} targets — they must pair 1:1 (pass None entries "
            f"for implicit ones)")
    tspecs = []
    for t, tg in zip(ts, tgs):
        tg_spec = None
        if tg is not None:
            tg_spec = prog._spec_for(tg)
        tspecs.append((id(t), tg_spec))
    tspecs = tuple(tspecs)
    out = []
    for x in ins:
        spec = prog._spec_for(x)
        out.append(_make_grad_handle(
            prog, tspecs, spec, x, f"{getattr(x, 'name', 'x')}@GRAD"))
    return out


# -- variable / parameter creation ---------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """ref: static.create_global_var — a persistent filled var registered
    with the startup program semantics (initialized now, referenced by
    the main program through its live Tensor)."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)))
    t.stop_gradient = True
    t.name = name or f"global_var_{id(t):x}"
    # persistable/force_cpu are ProgramDesc attributes in the reference;
    # a live Tensor is inherently persistent here (Tensor uses __slots__,
    # so the flag is not carried)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref: static.create_parameter. A WeightNormParamAttr attr applies
    the g*v/||v|| reparameterization eagerly (dim per the attr)."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    from ..nn import initializer as I

    init = default_initializer
    if init is None and attr is not None and \
            getattr(attr, "initializer", None) is not None:
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    dt = convert_dtype(dtype)
    data = init(tuple(shape), dt)
    data = data._data if isinstance(data, Tensor) else jnp.asarray(data, dt)
    p = Parameter(data)
    p.name = name or (getattr(attr, "name", None) or
                      f"param_{id(p):x}")
    if isinstance(attr, WeightNormParamAttr):
        # the train-time g*v/||v|| reparameterization needs two trainable
        # tensors; that transform lives in nn.utils.weight_norm — apply
        # it to the layer holding this parameter. At creation the weight
        # value itself is unchanged (g initialises to ||v||).
        p._weight_norm_dim = attr.dim
    return p


# -- places / scopes / guards --------------------------------------------

def cpu_places(device_count=None):
    """ref: static.cpu_places. Count defaults to CPU_NUM (1)."""
    from ..core.device import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """ref: static.cuda_places — the accelerator places. This build's
    accelerator is TPU; the name is kept for source compatibility and
    returns the TPU places (there is no CUDA device to return)."""
    import jax
    from ..core.device import Place
    if device_ids is None:
        device_ids = range(len(jax.devices()))
    return [Place("tpu", i) for i in device_ids]


def xpu_places(device_ids=None):
    raise NotImplementedError(
        "xpu_places: the XPU backend is a documented exclusion of the "
        "TPU build (SURVEY.md non-goals); use cpu_places/cuda_places")


@contextlib.contextmanager
def device_guard(device=None):
    """ref: static.device_guard — pin ops in the block to a device. The
    compiled replay runs on the default backend; 'cpu' pins via
    jax.default_device so host-side ops (e.g. big embedding inits) stay
    off-chip."""
    import jax
    if device is None:
        yield
        return
    kind = device.split(":")[0]
    if kind == "gpu":
        kind = "tpu"  # the accelerator of this build
    devs = [d for d in jax.devices(kind)] if kind != "cpu" else \
        jax.devices("cpu")
    with jax.default_device(devs[0]):
        yield


_name_scope_stack = threading.local()


@contextlib.contextmanager
def name_scope(prefix=None):
    """ref: static.name_scope — hierarchical op-name prefix, visible in
    recorded op names (Program introspection / profiler labels)."""
    stack = getattr(_name_scope_stack, "stack", None)
    if stack is None:
        stack = _name_scope_stack.stack = []
    stack.append(prefix or "scope")
    try:
        yield "/".join(stack)
    finally:
        stack.pop()


def current_name_scope() -> str:
    stack = getattr(_name_scope_stack, "stack", None) or []
    return "/".join(stack)


@contextlib.contextmanager
def scope_guard(scope):
    """ref: static.scope_guard — swap the global variable Scope."""
    from . import executor as ex
    old = ex._GLOBAL_SCOPE
    ex._GLOBAL_SCOPE = scope
    try:
        yield
    finally:
        ex._GLOBAL_SCOPE = old


# -- program/params persistence ------------------------------------------

def _prog_state(program: Program) -> dict:
    state = {}
    for i, t in enumerate(program._ref_tensors):
        name = getattr(t, "name", None) or f"ref_{i}"
        state[name] = np.asarray(t._data)
    return state


def save(program: Program, model_path: str, protocol=4, **configs):
    """ref: static.save — persist the program's persistables
    (params + buffers) as <path>.pdparams (np archive)."""
    program = getattr(program, "program", program)  # CompiledProgram
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    np.savez(model_path + ".pdparams", **_prog_state(program))


def load(program: Program, model_path: str, executor=None, var_names=None):
    """ref: static.load — restore persistables saved by static.save."""
    program = getattr(program, "program", program)
    set_program_state(program, load_program_state(model_path),
                      var_names=var_names)


def load_program_state(model_path: str, var_list=None) -> dict:
    path = model_path + ".pdparams" if not model_path.endswith(".npz") \
        else model_path
    if not os.path.exists(path):
        path = model_path + ".pdparams.npz"
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def set_program_state(program: Program, state_dict: dict, var_names=None):
    import jax.numpy as jnp
    program = getattr(program, "program", program)
    by_name = {}
    for i, t in enumerate(program._ref_tensors):
        by_name[getattr(t, "name", None) or f"ref_{i}"] = t
    for name, arr in state_dict.items():
        if var_names is not None and name not in var_names:
            continue
        t = by_name.get(name)
        if t is None:
            continue
        if tuple(t._data.shape) != tuple(arr.shape):
            raise ValueError(
                f"shape mismatch for {name}: program has "
                f"{tuple(t._data.shape)}, state has {tuple(arr.shape)}")
        t._data = jnp.asarray(arr, t._data.dtype)


def save_to_file(path: str, content: bytes):
    """ref: static.save_to_file — raw bytes to disk."""
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs
                      ) -> bytes:
    """ref: static.serialize_program — the program as portable bytes.
    Record/replay tapes hold python closures, so the portable form is the
    jax.export serialization of the pruned replay (StableHLO): loadable
    without the recording process. Shapes are those of the recorded
    feeds."""
    import jax
    import pickle
    from .executor import _replay

    prog = program or default_main_program()
    prog = getattr(prog, "program", prog)
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else \
        [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else \
        [fetch_vars]
    names = [t._static_feed_name for t in feed_vars]
    ref_vals = [t._data for t in prog._ref_tensors]
    n_rng = prog._rng_count

    def fn(*feeds):
        import jax.numpy as jnp
        feed_map = dict(zip(names, feeds))
        keys = [jax.random.PRNGKey(0)] * n_rng
        env = _replay(prog, feed_map, ref_vals, keys)
        return tuple(env[id(t)] for t in fetch_vars)

    args = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
            for t in feed_vars]
    exported = jax.export.export(jax.jit(fn))(*args)
    return pickle.dumps({"stablehlo": exported.serialize(),
                         "feed_names": names})


def deserialize_program(data: bytes):
    """ref: static.deserialize_program — rebuild a runnable program-like
    object from serialize_program bytes. Returns an object Executor.run
    accepts (carries its own compiled callable)."""
    import jax
    import pickle
    payload = pickle.loads(data)
    exported = jax.export.deserialize(payload["stablehlo"])
    names = payload["feed_names"]

    class _Deserialized:
        _exported_call = True

        def run(self, feed=None, fetch_list=None, return_numpy=True):
            import jax.numpy as jnp
            feed = feed or {}
            args = [jnp.asarray(np.asarray(feed[n])) for n in names]
            outs = exported.call(*args)
            if return_numpy:
                outs = [np.asarray(o) for o in outs]
            return list(outs)

    return _Deserialized()


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs
                           ) -> bytes:
    """ref: static.serialize_persistables — params/buffers as bytes."""
    import pickle
    prog = program or default_main_program()
    prog = getattr(prog, "program", prog)
    return pickle.dumps(_prog_state(prog))


def deserialize_persistables(program: Program, data: bytes, executor=None):
    import pickle
    set_program_state(getattr(program, "program", program),
                      pickle.loads(data))


def normalize_program(program: Program, feed_vars, fetch_vars, **kwargs
                      ) -> Program:
    """ref: static.normalize_program — prune to the ops reachable from
    fetch_vars (the inference-export subgraph). Real reachability pass
    over the tape: ops whose outputs never flow into a fetch are
    dropped."""
    program = getattr(program, "program", program)
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else \
        [fetch_vars]
    needed = set(id(t) for t in fetch_vars)
    kept = []
    for op in reversed(program.ops):
        produces = [oid for oid in op.out_ids if oid is not None]
        if any(oid in needed for oid in produces):
            kept.append(op)
            for spec in op.arg_specs:
                if spec[0] == "var":
                    needed.add(spec[1])
    kept.reverse()
    out = Program()
    out.ops = kept
    out.feeds = dict(program.feeds)
    out._produced = {oid: program._produced[oid] for op in kept
                     for oid in op.out_ids
                     if oid is not None and oid in program._produced}
    out._refs = dict(program._refs)
    out._ref_tensors = list(program._ref_tensors)
    out._rng_count = program._rng_count
    out.version = program.version
    return out


# -- IPU: documented exclusions ------------------------------------------

def _no_ipu(*a, **k):
    raise NotImplementedError(
        "IPU support is a documented capability exclusion of the "
        "TPU-native build (no Graphcore backend); see SURVEY.md non-goals")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _no_ipu()


class IpuStrategy:
    def __init__(self, *a, **k):
        _no_ipu()


def ipu_shard_guard(*a, **k):
    _no_ipu()


def set_ipu_shard(*a, **k):
    _no_ipu()
