"""Static-graph Program: a record/replay tape over the eager op stream.

ref: python/paddle/base/framework.py Program / python/paddle/static. The
reference builds a ProgramDesc of op protos and runs it on the
StandaloneExecutor (SURVEY.md §3.3); the TPU-native equivalent records the
apply_op stream while the user's Python runs once on placeholder data, then
replays it as ONE pure jitted function per (feed-shape, fetch) signature —
trace -> StableHLO -> XLA, the single execution path of this framework.

Recorded argument kinds:
  ("feed", name)   static.data placeholder — bound from exe.run(feed=...)
  ("var", id)      output of an earlier recorded op
  ("ref", slot)    any leaf Tensor (Parameter, buffer, constant) — read
                   fresh from the live Tensor at run time, so optimizer
                   updates and buffer mutations are visible across runs
  ("raw", value)   non-Tensor python value, replayed verbatim
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..core import autograd as _autograd
from ..core.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data"]


class _OpRecord:
    __slots__ = ("fn", "kwargs", "arg_specs", "out_ids", "name")

    def __init__(self, fn, kwargs, arg_specs, out_ids, name):
        self.fn = fn
        self.kwargs = kwargs
        self.arg_specs = arg_specs
        self.out_ids = out_ids
        self.name = name


class Program:
    """An op tape + the tensors it references. Populated by running user
    code under program_guard (or after enable_static())."""

    def __init__(self):
        self.ops: List[_OpRecord] = []
        self.feeds: Dict[str, Tensor] = {}
        self._produced: Dict[int, Tensor] = {}  # id -> strong ref
        self._refs: Dict[int, int] = {}         # tensor id -> slot
        self._ref_tensors: List[Tensor] = []    # slot -> live Tensor
        self.version = 0
        # set by Optimizer.minimize under static mode
        self._optimizer = None
        self._loss = None
        self._layers: Dict[str, Any] = {}       # static.nn layer registry
        # (buffer_tensor, produced_tensor_id, pure update fn(old, val)):
        # replayed buffer mutations (e.g. BN running stats) applied by the
        # Executor after each run — see register_buffer_update
        self._buffer_updates: List[tuple] = []
        # count of ("rng", i) slots: PRNG keys passed through stochastic
        # ops (dropout masks...), refreshed with fresh keys on every
        # Executor.run so replays don't reuse the record-time randomness
        self._rng_count = 0
        # fetchable gradient handles (append_backward / gradients):
        # id(handle Tensor) -> (targets, wrt_spec) where targets is a
        # tuple of (target_tensor_id, tg_spec_or_None) and wrt_spec is a
        # replay arg spec ("ref", slot) / ("feed", name) / ("var", id).
        # The Executor differentiates the pure replay to resolve them.
        self._grad_handles: Dict[int, tuple] = {}

    # -- recording ----------------------------------------------------------
    def _ref_slot(self, t: Tensor) -> int:
        slot = self._refs.get(id(t))
        if slot is None:
            slot = len(self._ref_tensors)
            self._refs[id(t)] = slot
            self._ref_tensors.append(t)
        return slot

    def _spec_for(self, a) -> tuple:
        if isinstance(a, Tensor):
            name = getattr(a, "_static_feed_name", None)
            if name is not None:
                return ("feed", name)
            if getattr(a, "_static_rng", False):
                self._rng_count += 1
                return ("rng", self._rng_count - 1)
            if id(a) in self._produced:
                return ("var", id(a))
            return ("ref", self._ref_slot(a))
        return ("raw", a)

    def _record(self, fn: Callable, args, kwargs, outs, name: str):
        specs = tuple(self._spec_for(a) for a in args)
        out_ids = []
        for o in outs:
            if isinstance(o, Tensor):
                self._produced[id(o)] = o
                out_ids.append(id(o))
            else:
                out_ids.append(None)
        self.ops.append(_OpRecord(fn, dict(kwargs), specs, out_ids, name))
        self.version += 1

    def register_buffer_update(self, buffer: Tensor, src: Tensor, fn):
        """Arrange for ``buffer._data = fn(buffer._data, value_of(src))``
        after every Executor.run of this program. ``src`` must be an output
        of a recorded op (e.g. the batch-mean output of batch_norm); ``fn``
        must be pure/jittable. This is how eager in-place buffer mutations
        (BN running stats) survive the record/replay split."""
        self._buffer_updates.append((buffer, id(src), fn))
        self.version += 1

    # -- introspection ------------------------------------------------------
    def parameters(self):
        from ..core.tensor import Parameter
        return [t for t in self._ref_tensors if isinstance(t, Parameter)]

    def global_block(self):
        return self

    def __repr__(self):
        return (f"<Program ops={len(self.ops)} feeds={list(self.feeds)} "
                f"refs={len(self._ref_tensors)}>")


class _State(threading.local):
    def __init__(self):
        self.static_mode = False
        self.guard_stack: List[Program] = []


_state = _State()
# default programs are process-wide (like the reference's globals)
_defaults = {"main": Program(), "startup": Program()}
# the recorder hook in core.autograd is process-global; it stays installed
# while ANY thread has static mode / a program_guard active (refcounted),
# and resolves the target program thread-locally — so one thread leaving
# static mode cannot disable another thread's active recording
_active_lock = threading.Lock()
_active_count = 0


def _static_mode() -> bool:
    return _state.static_mode


def _set_static_mode(on: bool):
    if on == _state.static_mode:
        return
    _state.static_mode = on
    _adjust_active(1 if on else -1)


def current_program() -> Optional[Program]:
    """The program recording in this thread right now, if any."""
    if _state.guard_stack:
        return _state.guard_stack[-1]
    if _state.static_mode:
        return _defaults["main"]
    return None


def _recorder(fn, args, kwargs, outs, name):
    prog = current_program()
    if prog is not None:
        prog._record(fn, args, kwargs, outs, name)


def _adjust_active(delta: int):
    global _active_count
    with _active_lock:
        _active_count += delta
        _autograd._op_recorder = _recorder if _active_count > 0 else None


def default_main_program() -> Program:
    return _defaults["main"]


def default_startup_program() -> Program:
    return _defaults["startup"]


def _reset_default_programs():
    _defaults["main"] = Program()
    _defaults["startup"] = Program()


class program_guard:
    """Record ops into `main_program` (ref: static.program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _state.guard_stack.append(self.main)
        _adjust_active(1)
        return self.main

    def __exit__(self, *exc):
        _state.guard_stack.pop()
        _adjust_active(-1)
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Feed placeholder (ref: static.data). Records into the current
    program; None/-1 dims stand in as 1 during recording and are re-traced
    to the fed shape at exe.run time."""
    import jax.numpy as jnp
    import numpy as np

    prog = current_program()
    if prog is None:
        raise RuntimeError(
            "static.data requires enable_static() or a program_guard")
    concrete = tuple(1 if (d is None or (isinstance(d, int) and d < 0))
                     else int(d) for d in shape)
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.zeros(concrete, convert_dtype(dtype)))
    t.stop_gradient = True
    t._static_feed_name = name
    t.name = name
    prog.feeds[name] = t
    return t
