"""static.nn — layer-creating ops for static programs.

ref: python/paddle/static/nn/ (fc, embedding, conv2d, batch_norm...). Each
call creates the underlying nn.Layer once, keyed by name on the recording
Program so parameters persist across Executor.run calls.
"""
from __future__ import annotations

from .. import nn as _nn
from .program import current_program

__all__ = ["fc", "embedding", "conv2d", "batch_norm"]


def _layer(kind, name, factory):
    prog = current_program()
    if prog is None:
        raise RuntimeError("static.nn ops require enable_static() or a "
                           "program_guard")
    key = name or f"{kind}_{len(prog._layers)}"
    layer = prog._layers.get(key)
    if layer is None:
        layer = factory()
        prog._layers[key] = layer
    return layer


def fc(x, size, num_flatten_dims=1, activation=None, name=None):
    in_f = 1
    for d in x.shape[num_flatten_dims:]:
        in_f *= int(d)
    layer = _layer("fc", name, lambda: _nn.Linear(in_f, size))
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        # -1 on the leading (batch) dim: the recorded placeholder batch is
        # 1, but replay must re-trace to the fed batch size
        h = x.reshape([-1] + [int(d) for d in
                              x.shape[1:num_flatten_dims]] + [in_f])
    out = layer(h)
    if activation == "relu":
        out = _nn.functional.relu(out)
    elif activation == "tanh":
        out = _nn.functional.tanh(out)
    elif activation == "sigmoid":
        out = _nn.functional.sigmoid(out)
    elif activation:
        raise ValueError(f"unsupported activation {activation!r}")
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, name=None):
    layer = _layer("embedding", name,
                   lambda: _nn.Embedding(size[0], size[1],
                                         padding_idx=padding_idx))
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           groups=1, name=None, act=None):
    in_ch = int(input.shape[1])
    layer = _layer("conv2d", name,
                   lambda: _nn.Conv2D(in_ch, num_filters, filter_size,
                                      stride=stride, padding=padding,
                                      groups=groups))
    out = layer(input)
    if act == "relu":
        out = _nn.functional.relu(out)
    elif act:
        raise ValueError(f"unsupported act {act!r}")
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-05, name=None):
    ch = int(input.shape[1])
    layer = _layer("batch_norm", name, lambda: _nn.BatchNorm2D(
        ch, momentum=momentum, epsilon=epsilon))
    if is_test:
        layer.eval()
    out = layer(input)
    if act == "relu":
        out = _nn.functional.relu(out)
    elif act:
        raise ValueError(f"unsupported act {act!r}")
    return out
