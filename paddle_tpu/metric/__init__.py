"""paddle.metric equivalent. ref: python/paddle/metric/metrics.py:44
(Metric ABC), :195 (Accuracy), Precision, Recall, Auc."""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric(abc.ABC):
    """ref: metrics.py:44."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """ref: metrics.py:195 — top-k accuracy with streaming accumulation."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(__import__("jax.numpy", fromlist=["asarray"]).asarray(correct))

    def update(self, correct, *args):
        c = _np(correct)
        num_samples = int(np.prod(c.shape[:-1]))
        accs = []
        for k in self.topk:
            num_corrects = c[..., :k].sum()
            self.total[self.topk.index(k)] += num_corrects
            self.count[self.topk.index(k)] += num_samples
            accs.append(float(num_corrects) / max(num_samples, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """ref: metrics.py Precision (binary)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    """ref: metrics.py Auc — trapezoidal AUC via thresholded bins."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self.num_thresholds).astype(np.int64),
                       0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos * tot_neg > 0 else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy (ref: metrics.py accuracy op)."""
    import jax.numpy as jnp
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    correct = (idx == lab[..., None]).any(axis=-1).mean()
    return Tensor(jnp.asarray(np.float32(correct)))
