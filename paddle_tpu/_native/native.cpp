// paddle_tpu native runtime: the components the reference implements in
// C++ and that stay host-side in a TPU build.
//
//  - flags registry        (ref: paddle/common/flags.h:336-375, impl
//                           flags_native.cc — FLAGS_* env parsing, typed
//                           get/set, export map)
//  - host tracer           (ref: paddle/fluid/platform/profiler/
//                           host_tracer.h:26 — RecordEvent spans collected
//                           into a buffer, dumped as Chrome trace JSON,
//                           chrometracing_logger.cc)
//  - TCPStore              (ref: paddle/phi/core/distributed/store/
//                           tcp_store.h:121, socket.cpp — rank-0 TCP KV
//                           server with set/get/add/wait, the rendezvous
//                           bootstrap for multi-host meshes)
//  - memory stats          (ref: paddle/phi/core/memory/stats.h —
//                           current/peak counters per stat kind)
//
// Exposed through the CPython C API (no pybind11 in this image).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// flags registry
// ---------------------------------------------------------------------------
class FlagRegistry {
 public:
  static FlagRegistry& Instance() {
    static FlagRegistry r;
    return r;
  }

  void Define(const std::string& name, const std::string& def,
              const std::string& help) {
    std::lock_guard<std::mutex> g(mu_);
    if (values_.count(name)) return;
    defaults_[name] = def;
    help_[name] = help;
    // env override: FLAGS_<name>
    std::string env_key = "FLAGS_" + name;
    const char* env = std::getenv(env_key.c_str());
    values_[name] = env ? std::string(env) : def;
  }

  bool Set(const std::string& name, const std::string& v) {
    std::lock_guard<std::mutex> g(mu_);
    if (!values_.count(name)) return false;
    values_[name] = v;
    return true;
  }

  bool Get(const std::string& name, std::string* out) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = values_.find(name);
    if (it == values_.end()) return false;
    *out = it->second;
    return true;
  }

  std::vector<std::string> Names() const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<std::string> out;
    out.reserve(values_.size());
    for (auto& kv : values_) out.push_back(kv.first);
    return out;
  }

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, std::string> values_, defaults_, help_;
};

// ---------------------------------------------------------------------------
// host tracer
// ---------------------------------------------------------------------------
struct TraceEvent {
  std::string name;
  uint64_t tid;
  double t0_us;
  double t1_us;
};

class HostTracer {
 public:
  static HostTracer& Instance() {
    static HostTracer t;
    return t;
  }

  void Start() {
    std::lock_guard<std::mutex> g(mu_);
    enabled_ = true;
    events_.clear();
  }

  void Stop() {
    std::lock_guard<std::mutex> g(mu_);
    enabled_ = false;
  }

  bool enabled() const { return enabled_; }

  double NowUs() const {
    return std::chrono::duration<double, std::micro>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Record(const std::string& name, double t0, double t1) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> g(mu_);
    events_.push_back(TraceEvent{
        name,
        std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0xffff,
        t0, t1});
  }

  static void EscapeJson(const std::string& s, std::ostringstream& os) {
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
  }

  // Chrome trace format (ref: chrometracing_logger.cc)
  std::string DumpJson() const {
    std::lock_guard<std::mutex> g(mu_);
    std::ostringstream os;
    os << "{\"traceEvents\":[";
    for (size_t i = 0; i < events_.size(); ++i) {
      const auto& e = events_[i];
      if (i) os << ",";
      os << "{\"name\":\"";
      EscapeJson(e.name, os);
      os << "\",\"ph\":\"X\",\"pid\":0,"
         << "\"tid\":" << e.tid << ",\"ts\":" << e.t0_us
         << ",\"dur\":" << (e.t1_us - e.t0_us) << "}";
    }
    os << "]}";
    return os.str();
  }

  size_t Size() const {
    std::lock_guard<std::mutex> g(mu_);
    return events_.size();
  }

 private:
  mutable std::mutex mu_;
  std::atomic<bool> enabled_{false};
  std::vector<TraceEvent> events_;
};

// ---------------------------------------------------------------------------
// memory stats
// ---------------------------------------------------------------------------
class MemStats {
 public:
  static MemStats& Instance() {
    static MemStats s;
    return s;
  }

  void Update(const std::string& key, long long delta) {
    std::lock_guard<std::mutex> g(mu_);
    auto& e = stats_[key];
    e.current += delta;
    if (e.current > e.peak) e.peak = e.current;
  }

  bool Get(const std::string& key, long long* cur, long long* peak) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = stats_.find(key);
    if (it == stats_.end()) return false;
    *cur = it->second.current;
    *peak = it->second.peak;
    return true;
  }

  // Reset the peak watermark to the current value (the reference's
  // reset_max_memory_allocated / ResetPeakValue semantics,
  // ref: paddle/phi/core/memory/stats.h).
  void ResetPeak(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = stats_.find(key);
    if (it != stats_.end()) it->second.peak = it->second.current;
  }

  // Force current to an externally-measured value (used to reconcile the
  // op-boundary tracker against an exact live-buffer scan).
  void SetCurrent(const std::string& key, long long cur) {
    std::lock_guard<std::mutex> g(mu_);
    auto& e = stats_[key];
    e.current = cur;
    if (e.current > e.peak) e.peak = e.current;
  }

 private:
  struct Entry {
    long long current = 0, peak = 0;
  };
  mutable std::mutex mu_;
  std::map<std::string, Entry> stats_;
};

// ---------------------------------------------------------------------------
// TCPStore: length-prefixed protocol
//   request : u8 op ('S','G','A','W') | u32 klen | key | (u32 vlen | value)
//   response: u32 vlen | value            (GET/ADD/WAIT)
// ---------------------------------------------------------------------------
bool SendAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class TCPStoreServer {
 public:
  ~TCPStoreServer() { StopNow(); }

  bool Start(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    if (::listen(listen_fd_, 64) != 0) return false;
    running_ = true;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void StopNow() {
    if (!running_.exchange(false)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    cv_.notify_all();
    {
      // unblock workers parked in recv() on their client sockets
      std::lock_guard<std::mutex> g(mu_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      {
        std::lock_guard<std::mutex> g(mu_);
        client_fds_.push_back(fd);
      }
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (running_) {
      uint8_t op;
      if (!RecvAll(fd, &op, 1)) break;
      uint32_t klen;
      if (!RecvAll(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !RecvAll(fd, &key[0], klen)) break;
      if (op == 'S') {  // set (acked, so a later get on any conn sees it)
        uint32_t vlen;
        if (!RecvAll(fd, &vlen, 4)) break;
        std::string val(vlen, '\0');
        if (vlen && !RecvAll(fd, &val[0], vlen)) break;
        {
          std::lock_guard<std::mutex> g(mu_);
          kv_[key] = val;
        }
        cv_.notify_all();
        uint8_t ack = 1;
        if (!SendAll(fd, &ack, 1)) break;
      } else if (op == 'G' || op == 'W' || op == 'T') {
        // get / wait-get / take (wait-get-delete, atomic — backs the p2p
        // channel transport so consumed messages don't accumulate)
        std::unique_lock<std::mutex> lk(mu_);
        if (op == 'W' || op == 'T')
          cv_.wait(lk, [&] { return kv_.count(key) || !running_; });
        uint8_t found = kv_.count(key) ? 1 : 0;
        std::string val = found ? kv_[key] : std::string();
        if (op == 'T' && found) kv_.erase(key);
        lk.unlock();
        uint32_t vlen = static_cast<uint32_t>(val.size());
        if (!SendAll(fd, &found, 1)) break;
        if (!SendAll(fd, &vlen, 4)) break;
        if (vlen && !SendAll(fd, val.data(), vlen)) break;
      } else if (op == 'D') {  // delete key (fire-and-ack)
        {
          std::lock_guard<std::mutex> g(mu_);
          kv_.erase(key);
        }
        uint8_t ack = 1;
        if (!SendAll(fd, &ack, 1)) break;
      } else if (op == 'A') {  // add (atomic counter), value = i64 delta
        int64_t delta;
        uint32_t vlen;
        if (!RecvAll(fd, &vlen, 4) || vlen != 8) break;
        if (!RecvAll(fd, &delta, 8)) break;
        int64_t result;
        {
          std::lock_guard<std::mutex> g(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          result = cur + delta;
          std::string v(8, '\0');
          std::memcpy(&v[0], &result, 8);
          kv_[key] = v;
        }
        cv_.notify_all();
        uint32_t rlen = 8;
        if (!SendAll(fd, &rlen, 4)) break;
        if (!SendAll(fd, &result, 8)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, std::string> kv_;
  std::vector<int> client_fds_;
};

class TCPStoreClient {
 public:
  bool Connect(const std::string& host, int port, double timeout_s) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    while (std::chrono::steady_clock::now() < deadline) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      ::inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    return false;
  }

  ~TCPStoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = 'S';
    uint32_t klen = key.size(), vlen = val.size();
    if (!(SendAll(fd_, &op, 1) && SendAll(fd_, &klen, 4) &&
          SendAll(fd_, key.data(), klen) && SendAll(fd_, &vlen, 4) &&
          (vlen == 0 || SendAll(fd_, val.data(), vlen))))
      return false;
    uint8_t ack;
    return RecvAll(fd_, &ack, 1) && ack == 1;
  }

  // returns false on transport error; *found distinguishes a missing key
  // from a key holding an empty value. mode: 'G' get, 'W' wait-get,
  // 'T' take (wait-get-delete, atomic)
  bool Get(const std::string& key, char mode, std::string* out,
           bool* found) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = static_cast<uint8_t>(mode);
    uint32_t klen = key.size();
    if (!SendAll(fd_, &op, 1) || !SendAll(fd_, &klen, 4) ||
        !SendAll(fd_, key.data(), klen))
      return false;
    uint8_t f;
    if (!RecvAll(fd_, &f, 1)) return false;
    *found = f != 0;
    uint32_t vlen;
    if (!RecvAll(fd_, &vlen, 4)) return false;
    out->assign(vlen, '\0');
    return vlen == 0 || RecvAll(fd_, &(*out)[0], vlen);
  }

  bool Delete(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = 'D';
    uint32_t klen = key.size();
    if (!SendAll(fd_, &op, 1) || !SendAll(fd_, &klen, 4) ||
        !SendAll(fd_, key.data(), klen))
      return false;
    uint8_t ack;
    return RecvAll(fd_, &ack, 1) && ack == 1;
  }

  bool Add(const std::string& key, int64_t delta, int64_t* result) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = 'A';
    uint32_t klen = key.size(), vlen = 8;
    if (!SendAll(fd_, &op, 1) || !SendAll(fd_, &klen, 4) ||
        !SendAll(fd_, key.data(), klen) || !SendAll(fd_, &vlen, 4) ||
        !SendAll(fd_, &delta, 8))
      return false;
    uint32_t rlen;
    if (!RecvAll(fd_, &rlen, 4) || rlen != 8) return false;
    return RecvAll(fd_, result, 8);
  }

 private:
  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace

// ---------------------------------------------------------------------------
// Op registry + executable cache (kernel dispatch)
//
// ref: paddle/phi/core/kernel_factory.h:58 KernelKey / :240 KernelFactory —
// the reference keys kernels by (name, backend, layout, dtype) in a global
// C++ factory. TPU mapping: the "kernel" for an op signature is a compiled
// XLA executable; the registry stores per-op descriptors (arity, vjp,
// SPMD rule name) populated from the YAML op table, and the cache maps
// (op, signature) -> the jitted callable with hit/miss stats.
// ---------------------------------------------------------------------------
struct OpDesc {
  int nin = 0;    // required tensor-ish inputs
  int nargs = 1;  // total positional parameters
  bool has_vjp = true;
  std::string spmd_rule;
};

class OpRegistry {
 public:
  static OpRegistry& Instance() {
    static OpRegistry r;
    return r;
  }
  void Register(const std::string& name, const OpDesc& d) {
    std::lock_guard<std::mutex> g(mu_);
    ops_[name] = d;
  }
  bool Lookup(const std::string& name, OpDesc* out) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = ops_.find(name);
    if (it == ops_.end()) return false;
    *out = it->second;
    return true;
  }
  std::vector<std::string> Names() const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<std::string> out;
    out.reserve(ops_.size());
    for (auto& kv : ops_) out.push_back(kv.first);
    return out;
  }
  size_t Count() const {
    std::lock_guard<std::mutex> g(mu_);
    return ops_.size();
  }

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, OpDesc> ops_;
};

// Holds PyObject* callables; all entry points run with the GIL held (they
// are CPython binding calls), so refcount ops are safe.
class ExecCache {
 public:
  static ExecCache& Instance() {
    static ExecCache c;
    return c;
  }
  PyObject* Get(const std::string& key) {  // returns NEW ref or nullptr
    auto it = cache_.find(key);
    if (it == cache_.end()) {
      ++misses_;
      return nullptr;
    }
    ++hits_;
    Py_INCREF(it->second);
    return it->second;
  }
  void Put(const std::string& key, PyObject* obj) {
    // DECREF can run arbitrary Python (tp_dealloc / weakref callbacks)
    // that may reenter the cache — detach entries from the map BEFORE
    // any DECREF so no live iterator spans Python execution.
    std::vector<PyObject*> dead;
    auto it = cache_.find(key);
    if (it != cache_.end()) {
      dead.push_back(it->second);
      cache_.erase(it);
    } else if (cache_.size() >= kMaxEntries) {
      // bounded cache: entries pin their callables (and anything those
      // close over, e.g. model weights), so evict rather than grow
      auto victim = cache_.begin();
      dead.push_back(victim->second);
      cache_.erase(victim);
    }
    Py_INCREF(obj);
    cache_[key] = obj;
    for (PyObject* p : dead) Py_DECREF(p);
  }
  void EvictPrefix(const std::string& prefix) {
    std::vector<PyObject*> dead;
    for (auto it = cache_.begin(); it != cache_.end();) {
      if (it->first.compare(0, prefix.size(), prefix) == 0) {
        dead.push_back(it->second);
        it = cache_.erase(it);
      } else {
        ++it;
      }
    }
    for (PyObject* p : dead) Py_DECREF(p);
  }
  void Clear() {
    std::vector<PyObject*> dead;
    dead.reserve(cache_.size());
    for (auto& kv : cache_) dead.push_back(kv.second);
    cache_.clear();
    hits_ = misses_ = 0;
    for (PyObject* p : dead) Py_DECREF(p);
  }
  size_t size() const { return cache_.size(); }
  long long hits() const { return hits_; }
  long long misses() const { return misses_; }

 private:
  static constexpr size_t kMaxEntries = 16;
  std::unordered_map<std::string, PyObject*> cache_;
  long long hits_ = 0;
  long long misses_ = 0;
};

// ---------------------------------------------------------------------------
// Python bindings (CPython C API)
// ---------------------------------------------------------------------------
extern "C" {

static PyObject* py_op_register(PyObject*, PyObject* args) {
  const char *name, *spmd = "";
  int nin, nargs, has_vjp;
  if (!PyArg_ParseTuple(args, "siip|s", &name, &nin, &nargs, &has_vjp,
                        &spmd))
    return nullptr;
  OpDesc d;
  d.nin = nin;
  d.nargs = nargs;
  d.has_vjp = has_vjp != 0;
  d.spmd_rule = spmd;
  OpRegistry::Instance().Register(name, d);
  Py_RETURN_NONE;
}

static PyObject* py_op_lookup(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  OpDesc d;
  if (!OpRegistry::Instance().Lookup(name, &d)) Py_RETURN_NONE;
  return Py_BuildValue("{s:i,s:i,s:O,s:s}", "nin", d.nin, "nargs",
                       d.nargs,
                       "has_vjp", d.has_vjp ? Py_True : Py_False,
                       "spmd_rule", d.spmd_rule.c_str());
}

static PyObject* py_op_names(PyObject*, PyObject*) {
  auto names = OpRegistry::Instance().Names();
  PyObject* list = PyList_New(names.size());
  for (size_t i = 0; i < names.size(); ++i)
    PyList_SET_ITEM(list, i, PyUnicode_FromString(names[i].c_str()));
  return list;
}

static PyObject* py_op_count(PyObject*, PyObject*) {
  return PyLong_FromSize_t(OpRegistry::Instance().Count());
}

static PyObject* py_exec_cache_get(PyObject*, PyObject* args) {
  const char* key;
  if (!PyArg_ParseTuple(args, "s", &key)) return nullptr;
  PyObject* obj = ExecCache::Instance().Get(key);
  if (obj == nullptr) Py_RETURN_NONE;
  return obj;
}

static PyObject* py_exec_cache_put(PyObject*, PyObject* args) {
  const char* key;
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "sO", &key, &obj)) return nullptr;
  ExecCache::Instance().Put(key, obj);
  Py_RETURN_NONE;
}

static PyObject* py_exec_cache_stats(PyObject*, PyObject*) {
  auto& c = ExecCache::Instance();
  return Py_BuildValue("(LLn)", c.hits(), c.misses(), (Py_ssize_t)c.size());
}

static PyObject* py_exec_cache_clear(PyObject*, PyObject*) {
  ExecCache::Instance().Clear();
  Py_RETURN_NONE;
}

static PyObject* py_exec_cache_evict_prefix(PyObject*, PyObject* args) {
  const char* prefix;
  if (!PyArg_ParseTuple(args, "s", &prefix)) return nullptr;
  ExecCache::Instance().EvictPrefix(prefix);
  Py_RETURN_NONE;
}

static PyObject* py_flag_define(PyObject*, PyObject* args) {
  const char *name, *def, *help = "";
  if (!PyArg_ParseTuple(args, "ss|s", &name, &def, &help)) return nullptr;
  FlagRegistry::Instance().Define(name, def, help);
  Py_RETURN_NONE;
}

static PyObject* py_flag_set(PyObject*, PyObject* args) {
  const char *name, *val;
  if (!PyArg_ParseTuple(args, "ss", &name, &val)) return nullptr;
  if (!FlagRegistry::Instance().Set(name, val)) {
    PyErr_Format(PyExc_KeyError, "unknown flag %s", name);
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* py_flag_get(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  std::string out;
  if (!FlagRegistry::Instance().Get(name, &out)) {
    PyErr_Format(PyExc_KeyError, "unknown flag %s", name);
    return nullptr;
  }
  return PyUnicode_FromStringAndSize(out.data(), out.size());
}

static PyObject* py_flag_names(PyObject*, PyObject*) {
  auto names = FlagRegistry::Instance().Names();
  PyObject* list = PyList_New(names.size());
  for (size_t i = 0; i < names.size(); ++i)
    PyList_SET_ITEM(list, i, PyUnicode_FromString(names[i].c_str()));
  return list;
}

static PyObject* py_tracer_start(PyObject*, PyObject*) {
  HostTracer::Instance().Start();
  Py_RETURN_NONE;
}

static PyObject* py_tracer_stop(PyObject*, PyObject*) {
  HostTracer::Instance().Stop();
  Py_RETURN_NONE;
}

static PyObject* py_tracer_now(PyObject*, PyObject*) {
  return PyFloat_FromDouble(HostTracer::Instance().NowUs());
}

static PyObject* py_tracer_record(PyObject*, PyObject* args) {
  const char* name;
  double t0, t1;
  if (!PyArg_ParseTuple(args, "sdd", &name, &t0, &t1)) return nullptr;
  HostTracer::Instance().Record(name, t0, t1);
  Py_RETURN_NONE;
}

static PyObject* py_tracer_enabled(PyObject*, PyObject*) {
  if (HostTracer::Instance().enabled()) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

static PyObject* py_tracer_dump(PyObject*, PyObject*) {
  std::string s = HostTracer::Instance().DumpJson();
  return PyUnicode_FromStringAndSize(s.data(), s.size());
}

static PyObject* py_tracer_size(PyObject*, PyObject*) {
  return PyLong_FromSize_t(HostTracer::Instance().Size());
}

static PyObject* py_stat_update(PyObject*, PyObject* args) {
  const char* key;
  long long delta;
  if (!PyArg_ParseTuple(args, "sL", &key, &delta)) return nullptr;
  MemStats::Instance().Update(key, delta);
  Py_RETURN_NONE;
}

static PyObject* py_stat_get(PyObject*, PyObject* args) {
  const char* key;
  if (!PyArg_ParseTuple(args, "s", &key)) return nullptr;
  long long cur = 0, peak = 0;
  MemStats::Instance().Get(key, &cur, &peak);
  return Py_BuildValue("(LL)", cur, peak);
}

static PyObject* py_stat_reset_peak(PyObject*, PyObject* args) {
  const char* key;
  if (!PyArg_ParseTuple(args, "s", &key)) return nullptr;
  MemStats::Instance().ResetPeak(key);
  Py_RETURN_NONE;
}

static PyObject* py_stat_set_current(PyObject*, PyObject* args) {
  const char* key;
  long long cur;
  if (!PyArg_ParseTuple(args, "sL", &key, &cur)) return nullptr;
  MemStats::Instance().SetCurrent(key, cur);
  Py_RETURN_NONE;
}

// --- TCPStore capsules ---
static void server_capsule_destructor(PyObject* cap) {
  auto* s = static_cast<TCPStoreServer*>(
      PyCapsule_GetPointer(cap, "TCPStoreServer"));
  delete s;
}

static void client_capsule_destructor(PyObject* cap) {
  auto* c = static_cast<TCPStoreClient*>(
      PyCapsule_GetPointer(cap, "TCPStoreClient"));
  delete c;
}

static PyObject* py_store_server_start(PyObject*, PyObject* args) {
  int port;
  if (!PyArg_ParseTuple(args, "i", &port)) return nullptr;
  auto* s = new TCPStoreServer();
  bool ok;
  Py_BEGIN_ALLOW_THREADS ok = s->Start(port);
  Py_END_ALLOW_THREADS
  if (!ok) {
    delete s;
    PyErr_Format(PyExc_OSError, "TCPStore server failed to bind port %d",
                 port);
    return nullptr;
  }
  return PyCapsule_New(s, "TCPStoreServer", server_capsule_destructor);
}

static PyObject* py_store_client_connect(PyObject*, PyObject* args) {
  const char* host;
  int port;
  double timeout;
  if (!PyArg_ParseTuple(args, "sid", &host, &port, &timeout)) return nullptr;
  auto* c = new TCPStoreClient();
  bool ok;
  Py_BEGIN_ALLOW_THREADS ok = c->Connect(host, port, timeout);
  Py_END_ALLOW_THREADS
  if (!ok) {
    delete c;
    PyErr_Format(PyExc_ConnectionError, "TCPStore connect %s:%d timed out",
                 host, port);
    return nullptr;
  }
  return PyCapsule_New(c, "TCPStoreClient", client_capsule_destructor);
}

static TCPStoreClient* GetClient(PyObject* cap) {
  return static_cast<TCPStoreClient*>(
      PyCapsule_GetPointer(cap, "TCPStoreClient"));
}

static PyObject* py_store_set(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  Py_buffer val;
  if (!PyArg_ParseTuple(args, "Osy*", &cap, &key, &val)) return nullptr;
  auto* c = GetClient(cap);
  if (!c) return nullptr;
  bool ok;
  std::string v(static_cast<const char*>(val.buf),
                static_cast<size_t>(val.len));
  PyBuffer_Release(&val);
  Py_BEGIN_ALLOW_THREADS ok = c->Set(key, v);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "TCPStore set failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* py_store_get(PyObject*, PyObject* args) {
  // returns bytes, or None when the key does not exist (non-wait mode)
  PyObject* cap;
  const char* key;
  int wait;
  if (!PyArg_ParseTuple(args, "Osp", &cap, &key, &wait)) return nullptr;
  auto* c = GetClient(cap);
  if (!c) return nullptr;
  std::string out;
  bool ok, found = false;
  Py_BEGIN_ALLOW_THREADS ok = c->Get(key, wait ? 'W' : 'G', &out, &found);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "TCPStore get failed");
    return nullptr;
  }
  if (!found) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(out.data(), out.size());
}

static PyObject* py_store_take(PyObject*, PyObject* args) {
  // wait-get-delete (atomic): the channel primitive for eager p2p
  PyObject* cap;
  const char* key;
  if (!PyArg_ParseTuple(args, "Os", &cap, &key)) return nullptr;
  auto* c = GetClient(cap);
  if (!c) return nullptr;
  std::string out;
  bool ok, found = false;
  Py_BEGIN_ALLOW_THREADS ok = c->Get(key, 'T', &out, &found);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "TCPStore take failed");
    return nullptr;
  }
  if (!found) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(out.data(), out.size());
}

static PyObject* py_store_delete(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  if (!PyArg_ParseTuple(args, "Os", &cap, &key)) return nullptr;
  auto* c = GetClient(cap);
  if (!c) return nullptr;
  bool ok;
  Py_BEGIN_ALLOW_THREADS ok = c->Delete(key);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "TCPStore delete failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* py_store_add(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  long long delta;
  if (!PyArg_ParseTuple(args, "OsL", &cap, &key, &delta)) return nullptr;
  auto* c = GetClient(cap);
  if (!c) return nullptr;
  int64_t result = 0;
  bool ok;
  Py_BEGIN_ALLOW_THREADS ok = c->Add(key, delta, &result);
  Py_END_ALLOW_THREADS
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "TCPStore add failed");
    return nullptr;
  }
  return PyLong_FromLongLong(result);
}

static PyObject* py_store_server_stop(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  auto* s = static_cast<TCPStoreServer*>(
      PyCapsule_GetPointer(cap, "TCPStoreServer"));
  if (s) {
    Py_BEGIN_ALLOW_THREADS s->StopNow();
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

static PyMethodDef Methods[] = {
    {"flag_define", py_flag_define, METH_VARARGS, "define a flag"},
    {"flag_set", py_flag_set, METH_VARARGS, "set a flag"},
    {"flag_get", py_flag_get, METH_VARARGS, "get a flag"},
    {"flag_names", py_flag_names, METH_NOARGS, "list flags"},
    {"tracer_start", py_tracer_start, METH_NOARGS, "start host tracer"},
    {"tracer_stop", py_tracer_stop, METH_NOARGS, "stop host tracer"},
    {"tracer_now", py_tracer_now, METH_NOARGS, "monotonic us"},
    {"tracer_record", py_tracer_record, METH_VARARGS, "record span"},
    {"tracer_enabled", py_tracer_enabled, METH_NOARGS, "tracer on?"},
    {"tracer_dump", py_tracer_dump, METH_NOARGS, "chrome trace json"},
    {"tracer_size", py_tracer_size, METH_NOARGS, "event count"},
    {"stat_update", py_stat_update, METH_VARARGS, "update mem stat"},
    {"stat_get", py_stat_get, METH_VARARGS, "(current, peak)"},
    {"stat_reset_peak", py_stat_reset_peak, METH_VARARGS,
     "peak = current"},
    {"stat_set_current", py_stat_set_current, METH_VARARGS,
     "current = value (reconcile)"},
    {"store_server_start", py_store_server_start, METH_VARARGS,
     "start TCPStore server"},
    {"store_server_stop", py_store_server_stop, METH_VARARGS,
     "stop TCPStore server"},
    {"store_client_connect", py_store_client_connect, METH_VARARGS,
     "connect TCPStore client"},
    {"store_set", py_store_set, METH_VARARGS, "set key"},
    {"store_get", py_store_get, METH_VARARGS, "get key (optionally wait)"},
    {"store_take", py_store_take, METH_VARARGS,
     "wait-get-delete a key (atomic take)"},
    {"store_delete", py_store_delete, METH_VARARGS, "delete key"},
    {"store_add", py_store_add, METH_VARARGS, "atomic add"},
    {"op_register", py_op_register, METH_VARARGS, "register op descriptor"},
    {"op_lookup", py_op_lookup, METH_VARARGS, "lookup op descriptor"},
    {"op_names", py_op_names, METH_NOARGS, "registered op names"},
    {"op_count", py_op_count, METH_NOARGS, "registered op count"},
    {"exec_cache_get", py_exec_cache_get, METH_VARARGS,
     "executable cache lookup"},
    {"exec_cache_put", py_exec_cache_put, METH_VARARGS,
     "executable cache insert"},
    {"exec_cache_stats", py_exec_cache_stats, METH_NOARGS,
     "(hits, misses, size)"},
    {"exec_cache_clear", py_exec_cache_clear, METH_NOARGS,
     "clear executable cache"},
    {"exec_cache_evict_prefix", py_exec_cache_evict_prefix, METH_VARARGS,
     "drop all cache entries whose key starts with prefix"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                                       "_paddle_native",
                                       "paddle_tpu native runtime",
                                       -1,
                                       Methods,
                                       nullptr,
                                       nullptr,
                                       nullptr,
                                       nullptr};

PyMODINIT_FUNC PyInit__paddle_native(void) {
  return PyModule_Create(&moduledef);
}

}  // extern "C"
