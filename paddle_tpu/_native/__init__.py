"""Native runtime loader: compiles native.cpp with the system toolchain on
first import (cached as _paddle_native.so next to the source), mirroring
the reference's compiled core (`paddle.base.core`). Falls back to None if
no compiler is available — callers must degrade gracefully.
"""
from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

_here = os.path.dirname(os.path.abspath(__file__))
_src = os.path.join(_here, "native.cpp")
_so = os.path.join(_here, "_paddle_native.so")


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", _src, "-o", _so, "-lpthread",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if proc.returncode != 0:
        sys.stderr.write(
            f"paddle_tpu: native build failed:\n{proc.stderr[-2000:]}\n")
        return False
    return True


def _load():
    if not os.path.exists(_so) or (
            os.path.getmtime(_so) < os.path.getmtime(_src)):
        if not _build():
            return None
    spec = importlib.util.spec_from_file_location("_paddle_native", _so)
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except ImportError:
        return None


lib = _load()

if lib is not None:
    # back-fill flags that paddle_tpu.core.flags defined before the native
    # registry existed (the python side mirrors lazily; see flags._native_lib)
    try:
        from ..core import flags as _flags
        for _name, _info in _flags._registry.items():
            lib.flag_define(_name, str(_info.value), _info.help)
    except Exception:
        pass
