"""Benchmarks for the five BASELINE.md workloads.

Default run = the FULL suite, one JSON line per BASELINE workload so the
driver artifact (BENCH_r*.json) captures every bar, not just the
headline. Line 1 is the headline: Llama causal-LM training
tokens/sec/chip — a ~1.17B-param Llama (Llama-2 geometry scaled to one
v5e chip's HBM) in bf16 with bf16 AdamW state through the compiled
whole-train-step path (DistTrainStep: fwd + bwd + optimizer in one XLA
executable, attention on the Pallas flash kernel). Then ResNet-50 img/s,
BERT-base static+fusion MFU, GPT-13B-geometry MFU, ERNIE-MoE dispatch.
``--headline-only`` runs just the Llama line.

MFU uses the standard 6*N_params FLOPs/token estimate, which EXCLUDES
attention score FLOPs (~12*L*h*s extra per token) — reported MFU is
therefore conservative by a few percent at long sequence.

vs_baseline: the reference publishes no numbers (BASELINE.md); for the
transformer workloads the agreed bar is "A100+NCCL MFU" ~0.45, so
vs_baseline = our_MFU / 0.45 with bf16 peak detected per chip. For
ResNet-50 the bar is the public A100 fp16 training rate (~2500 img/s).
For the MoE dispatch vs_baseline = measured useful-FLOPs MFU / 0.40
(absolute expert-FFN utilization bar; the dense one-hot dispatch
oracle's speedup stays in detail.dense_speedup). The dispatch
micro-bench's bar is the stated µs/op budget.

Prints ONE json line per workload:
{"metric", "value", "unit", "vs_baseline", "detail"}.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


# chip bf16 peak FLOP/s by device_kind substring
_PEAKS = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v6", 918e12), ("v3", 123e12), ("v2", 46e12),
]
_BASELINE_MFU = 0.45  # well-tuned A100 Llama pretraining MFU


def _peak_flops():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 197e12


def _on_tpu():
    import jax
    return jax.default_backend() in ("tpu", "axon")


# Every metric line is ALSO appended to this driver-durable artifact:
# the driver captures only the stdout tail, which truncated round 4's
# eager-dispatch line (it must run first for µs fidelity but then
# scrolls off). A file survives regardless of emission order.
# (ref role: tools/check_op_benchmark_result.py — results as files.)
_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_ALL.json")


def _reset_artifact():
    try:
        with open(_ARTIFACT, "w"):
            pass
    except OSError:
        pass


def _emit(metric, value, unit, vs_baseline, detail):
    line = json.dumps({
        "metric": metric,
        "value": None if value is None else round(value, 2),
        "unit": unit, "vs_baseline": round(vs_baseline, 4),
        "detail": detail,
    })
    print(line, flush=True)
    try:
        with open(_ARTIFACT, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


def _hbm_detail(step, *args, **kw):
    """peak_hbm_bytes of the compiled train step (args + outputs + temps
    - donation aliases, from XLA's per-device memory analysis via
    TrainStep/DistTrainStep.compile_stats). Best-effort: an analysis
    failure must not kill a bench line.

    Cost note: the AOT lower().compile() here does NOT share the jit
    dispatch cache the timed warmup filled, so each workload pays a
    second XLA compile (outside the timed window). Accepted: the driver
    runs bench once per round and the memory-parity artifact is worth
    the extra minutes; driving the returned Compiled for the timed loop
    instead would bypass __call__'s donation/rng handling."""
    try:
        ma = step.compile_stats(*args, **kw)
        peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        return {"peak_hbm_bytes": int(peak),
                "hbm_temp_bytes": int(ma.temp_size_in_bytes)}
    except Exception as e:  # noqa: BLE001
        return {"peak_hbm_bytes": None,
                "hbm_error": f"{type(e).__name__}: {e}"[:120]}


def bench_llama():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    on_tpu = _on_tpu()
    if on_tpu:
        # ~1.2B-param Llama geometry chosen to saturate one v5e chip's HBM
        # (AdamW fp32 state + bf16 params/grads + flash-attention
        # activations); wide layers keep the MXU fed
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=3584, intermediate_size=9728,
            num_hidden_layers=6, num_attention_heads=28,
            num_key_value_heads=28, max_position_embeddings=2048)
        batch, seq, steps = 4, 2048, 10
    else:  # CI smoke path
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 2, 32, 2

    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    # multi_precision=False stores Adam moments in the param dtype (bf16),
    # the reference's own default for AdamW — halves optimizer-state HBM
    # traffic (+14% step time on v5e). bf16 keeps fp32's exponent range,
    # so the moments lose mantissa only, not range.
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)
    crit = LlamaPretrainingCriterion()
    step = DistTrainStep(model, lambda lg, lb: crit(lg, lb), opt)

    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    # device-resident feed: per-step host->device uploads would serialize
    # on the tunnel RTT and measure the link, not the chip
    ids = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    with jax.default_matmul_precision("bfloat16"):
        # compile + warmup with a full host sync (float(loss): a value
        # transfer is the only trustworthy barrier over the tunnel)
        float(step(ids, ids))
        float(step(ids, ids))
        # timed region: steps chain on-device (donated buffers); ONE final
        # loss fetch closes the timing — per-step fetches would add a
        # ~100 ms tunnel round-trip to every step
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, ids)
        loss = float(loss)
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    flops_per_token = 6 * n_params  # standard fwd+bwd estimate
    mfu = tokens_per_sec * flops_per_token / _peak_flops()
    _emit("llama_train_tokens_per_sec_per_chip", tokens_per_sec,
          "tokens/s", mfu / _BASELINE_MFU, {
              "params": n_params, "batch": batch, "seq": seq,
              "mfu": round(mfu, 4), "loss": loss,
              "backend": jax.default_backend(),
              **_hbm_detail(step, ids, ids)})


def bench_llama7b_geometry():
    """BASELINE workload 3's north-star geometry: Llama-2 7B per-layer
    shapes EXACTLY (hidden 4096, intermediate 11008, 32 heads — ref:
    test/auto_parallel/hybrid_strategy/semi_auto_llama.py), depth-scaled
    to one chip's HBM like the GPT-13B row; the full-depth 7B ZeRO-3
    (fsdp) mesh program is validated by the dryrun '7b' regime
    (MULTICHIP json). MFU vs the 0.45 bar — per-layer compute is
    geometry-identical to 7B."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    if _on_tpu():
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=4, num_attention_heads=32,
            num_key_value_heads=32, max_position_embeddings=2048)
        batch, seq, steps = 4, 2048, 8
    else:
        cfg = LlamaConfig.tiny(hidden_size=32, intermediate_size=88,
                               num_attention_heads=2,
                               num_key_value_heads=2)
        batch, seq, steps = 2, 16, 2
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)
    crit = LlamaPretrainingCriterion()
    step = DistTrainStep(model, lambda lg, lb: crit(lg, lb), opt)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                   (batch, seq)).astype(np.int32))
    with jax.default_matmul_precision("bfloat16"):
        float(step(ids, ids))
        float(step(ids, ids))
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = step(ids, ids)
        loss = float(loss)
        dt = time.perf_counter() - t0
    tok = batch * seq * steps / dt
    mfu = tok * 6 * n_params / _peak_flops()
    _emit("llama7b_geometry_tokens_per_sec_per_chip", tok, "tokens/s",
          mfu / _BASELINE_MFU, {
              "params": n_params, "hidden": cfg.hidden_size,
              "intermediate": cfg.intermediate_size,
              "heads": cfg.num_attention_heads,
              "layers_on_chip": cfg.num_hidden_layers,
              "batch": batch, "seq": seq, "mfu": round(mfu, 4),
              "loss": round(loss, 4),
              "mesh_validated_by": "MULTICHIP dryrun '7b' (ZeRO-3 fsdp)",
              "backend": jax.default_backend(),
              **_hbm_detail(step, ids, ids)})


def bench_resnet50():
    """BASELINE workload 1: ResNet-50 training img/s, single chip.
    Bar: public A100 fp16 training ~2500 img/s."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.vision.models import resnet50

    baseline_imgs = 2500.0
    if _on_tpu():
        # 96 chained steps: short chains measure the tunnel dispatch
        # pipeline fill, not the chip (identical program: ~2120 img/s at
        # 8 steps, 2468 at 32, 2541 at 96 — device-only time from the
        # xplane trace is 49.1 ms/step = 2606 img/s, so the residual gap
        # at small step counts is tunnel RTT, absent on a real host)
        batch, hw, steps = 128, 224, 96
    else:
        batch, hw, steps = 4, 32, 2
    paddle.seed(0)
    # NHWC end-to-end: TPU-native conv layout (channels in the 128-lane
    # minor dim; BN stats reduce over contiguous dims). Measured vs NCHW
    # on v5e: 1378 -> 2550 img/s together with the custom-VJP batch norm;
    # r5's running-mean-anchored ONE-PASS BN stats (fused into the conv
    # epilogue by XLA — the trace shows (f32[C], f32[C], conv) tuple
    # fusions) lifted 2538 -> 2649.
    model = resnet50(num_classes=1000, data_format="NHWC")
    model.bfloat16()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    crit = paddle.nn.CrossEntropyLoss()
    step = TrainStep(model, lambda out, y: crit(out, y), opt)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(
        (batch, hw, hw, 3)).astype(np.float32) * 0.1, jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, (batch,)).astype(np.int32))
    with jax.default_matmul_precision("bfloat16"):
        float(step(x, y))
        float(step(x, y))
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = step(x, y)
        loss = float(loss)
        dt = time.perf_counter() - t0
    imgs = batch * steps / dt
    _emit("resnet50_train_imgs_per_sec", imgs, "imgs/s",
          imgs / baseline_imgs, {
              "batch": batch, "hw": hw, "loss": round(loss, 4),
              "baseline": "A100 fp16 ~2500 img/s",
              "backend": jax.default_backend(),
              **_hbm_detail(step, x, y)})


def bench_llama_decode():
    """Serving decode throughput (the r5 generation-serving path):
    fixed-slot continuous-batching engine, single-token steps advancing
    all slots, device-chained feedback. Decode streams the FULL weight
    set every step, so the honest bar is the weight-streaming roofline
    tokens/s = slots / (weight_bytes / HBM_BW); the bench grades
    against 50% of it (kernel + cache traffic take the rest)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import LlamaDecodeEngine

    if _on_tpu():
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=3584, intermediate_size=9728,
            num_hidden_layers=6, num_attention_heads=28,
            num_key_value_heads=28, max_position_embeddings=2048,
            dtype="bfloat16")
        slots, max_seq, steps = 8, 1024, 192
        hbm_bw = 819e9  # v5e
    else:
        cfg = LlamaConfig.tiny()
        cfg.dtype = "float32"
        slots, max_seq, steps = 2, 64, 4
        hbm_bw = 100e9
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        model.bfloat16()
    eng = LlamaDecodeEngine(model, max_slots=slots, max_seq=max_seq)
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    weight_bytes = sum(
        int(np.prod(p.shape)) for p in model.parameters()) * itemsize
    # mandatory per-step HBM traffic: the full weight set + every
    # active slot's K/V history (read by the attention dots)
    cache_bytes = (cfg.num_hidden_layers * slots * max_seq *
                   cfg.num_key_value_heads *
                   (cfg.hidden_size // cfg.num_attention_heads) *
                   2 * itemsize)
    rng = np.random.default_rng(0)
    for s in range(slots):
        eng.prefill(s, rng.integers(0, cfg.vocab_size, (16,)))
    # warm with the SAME n as the timed call: decode_steps' token
    # buffer is [slots, n], so a different warm n would leave the
    # timed call to compile its own variant inside the window
    eng.decode_steps(steps)
    t0 = time.perf_counter()
    toks = eng.decode_steps(steps)
    dt = time.perf_counter() - t0
    tok_s = slots * steps / dt
    roofline = slots / ((weight_bytes + cache_bytes) / hbm_bw)
    _emit("llama_decode_tokens_per_sec", tok_s, "tokens/s",
          tok_s / (0.5 * roofline), {
              "slots": slots, "max_seq": max_seq, "steps": steps,
              "params_bytes": int(weight_bytes),
              "kv_cache_bytes": int(cache_bytes),
              "traffic_roofline_tok_s": round(roofline, 1),
              "baseline": "50% of the weights+KV-cache streaming "
                          "roofline",
              "sample_tokens": [int(t) for t in toks[0, :4]],
              "backend": jax.default_backend()})


def bench_llama_decode_paged():
    """Paged-KV decode throughput + concurrency at fixed HBM (ISSUE
    11). Same model/slots/max_seq geometry as the dense engine,
    measured back to back: the paged engine's tiled block-table
    attention walks only the ACTIVE history (max(pos)//block_size + 1
    tiles) while the dense step streams all max_seq columns, so paged
    must be >= dense tokens/s. The roofline denominator folds the
    paged cache term as O(active tokens), not O(slots x max_seq) —
    the bar the block pool exists to move. A second line,
    paged_kv_concurrency, admits requests into a pool sized to the
    dense engine's HBM budget until exhaustion: the acceptance is
    >= 2x the dense slot count."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (LlamaDecodeEngine,
                                    PagedLlamaDecodeEngine)

    if _on_tpu():
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=3584, intermediate_size=9728,
            num_hidden_layers=6, num_attention_heads=28,
            num_key_value_heads=28, max_position_embeddings=2048,
            dtype="bfloat16")
        slots, max_seq, steps, prompt_len = 8, 1024, 192, 64
        hbm_bw = 819e9  # v5e
    else:
        cfg = LlamaConfig.tiny()
        cfg.dtype = "float32"
        slots, max_seq, steps, prompt_len = 2, 512, 16, 16
        hbm_bw = 100e9
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        model.bfloat16()
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    weight_bytes = sum(
        int(np.prod(p.shape)) for p in model.parameters()) * itemsize
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               for _ in range(slots)]

    def timed_window(eng, budget=None):
        """Best-of-3 decode windows (shared bench hosts are noisy;
        the structural gap — dense streams max_seq columns, paged
        only the active tiles — is what's being measured)."""
        for s in range(slots):
            kw = {} if budget is None else {"budget": budget}
            eng.prefill(s, prompts[s], **kw)
        eng.decode_steps(steps)            # warm: same window shape
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            toks = eng.decode_steps(steps)
            best = min(best, time.perf_counter() - t0)
        return slots * steps / best, toks

    dense_tok_s, _ = timed_window(
        LlamaDecodeEngine(model, max_slots=slots, max_seq=max_seq))
    paged = PagedLlamaDecodeEngine(model, max_slots=slots,
                                   max_seq=max_seq)
    paged_tok_s, toks = timed_window(paged, budget=4 * steps + 2)
    # mandatory per-step traffic with the block pool: weights + the
    # ACTIVE tokens' K/V (what the tiled walk actually streams), not
    # slots x max_seq rows
    active_tokens = paged._kv.active_tokens(paged.pos, paged.active)
    kv_active_bytes = (active_tokens * cfg.num_hidden_layers *
                       cfg.num_key_value_heads *
                       (cfg.hidden_size // cfg.num_attention_heads) *
                       2 * itemsize)
    roofline = slots / ((weight_bytes + kv_active_bytes) / hbm_bw)
    ratio = paged_tok_s / max(dense_tok_s, 1e-9)
    _emit("llama_decode_paged_tokens_per_sec", paged_tok_s, "tokens/s",
          paged_tok_s / (0.5 * roofline), {
              "slots": slots, "max_seq": max_seq, "steps": steps,
              "block_size": paged.block_size,
              "blocks_used": paged._kv.stats()["blocks_used"],
              "active_tokens": active_tokens,
              "kv_active_bytes": int(kv_active_bytes),
              "params_bytes": int(weight_bytes),
              "traffic_roofline_tok_s": round(roofline, 1),
              "dense_tokens_per_sec": round(dense_tok_s, 2),
              "paged_vs_dense": round(ratio, 3),
              "baseline": "50% of the weights + ACTIVE-token KV "
                          "streaming roofline",
              "bar": "paged >= dense tokens/s on the same geometry",
              "sample_tokens": [int(t) for t in toks[0, :4]],
              "backend": jax.default_backend()})
    assert ratio >= 1.0, (
        f"paged decode ({paged_tok_s:.1f} tok/s) slower than dense "
        f"({dense_tok_s:.1f} tok/s) on the same geometry")

    # -- concurrency at equal HBM: tiny model, pool == dense budget ------
    tiny = LlamaConfig.tiny()
    tiny.dtype = "float32"
    paddle.seed(0)
    tmodel = LlamaForCausalLM(tiny)
    dense_slots, c_seq, bs = 2, 256, 16
    pool_blocks = dense_slots * c_seq // bs   # == dense HBM budget
    probe = PagedLlamaDecodeEngine(tmodel, max_slots=64,
                                   max_seq=c_seq, block_size=bs,
                                   num_blocks=pool_blocks)
    admitted = 0
    for slot in range(probe.max_slots):
        if not probe.begin_request(slot, [1] * 16, 16):
            break
        admitted += 1
    ratio_c = admitted / dense_slots
    assert ratio_c >= 2.0, (
        f"paged admitted only {admitted} slots vs {dense_slots} dense "
        f"at equal HBM")
    _emit("paged_kv_concurrency", ratio_c, "x", ratio_c / 2.0, {
        "dense_slots": dense_slots, "paged_admitted": admitted,
        "pool_blocks": pool_blocks, "block_size": bs,
        "max_seq": c_seq,
        "request_shape": "16-token prompt + 16-token budget",
        "bar": ">=2x the dense engine's concurrent slots at equal "
               "KV HBM"})


def bench_prefix_sharing_kv():
    """Prefix-sharing KV cache vs the unshared allocator (ISSUE 16):
    64 requests sharing a 256-token prefix (16 blocks at block_size
    16) with 4 unique tail tokens each, served both ways. Three bars:
    streams BIT-equal to the unshared oracle (sharing must be
    invisible in the tokens), served tokens/s >= 1.5x (aliased
    admissions skip 256 of 260 prefill tokens), and admitted slots
    >= 2x on a fixed pool (a shared block is charged once however
    many slots alias it)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import PagedLlamaDecodeEngine

    cfg = LlamaConfig.tiny()
    cfg.dtype = "float32"
    n_req, prefix_len, tail_len, new_tok = 64, 256, 4, 8
    bs, max_seq = 16, 320
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (prefix_len,)).tolist()
    warm_prefix = rng.integers(0, cfg.vocab_size,
                               (prefix_len,)).tolist()
    prompts = [prefix + rng.integers(
        0, cfg.vocab_size, (tail_len,)).tolist() for _ in range(n_req)]

    def build(prefix_cache_on, slots, num_blocks=0):
        prev = paddle.get_flags(["FLAGS_serving_prefix_cache"])
        paddle.set_flags(
            {"FLAGS_serving_prefix_cache": int(prefix_cache_on)})
        try:
            return PagedLlamaDecodeEngine(
                model, max_slots=slots, max_seq=max_seq,
                block_size=bs, num_blocks=num_blocks,
                prefill_chunk=64)
        finally:
            paddle.set_flags(prev)

    def serve_all(eng):
        """Sequential single-slot serve of every request (prefill +
        decode + release) — the wall clock covers the whole request
        lifecycle, which is where prefix reuse pays."""
        eng.generate(warm_prefix + [1] * tail_len,
                     max_new_tokens=new_tok)     # warm both buckets
        streams = []
        t0 = time.perf_counter()
        for p in prompts:
            streams.append(eng.generate(p, max_new_tokens=new_tok))
        dt = time.perf_counter() - t0
        return streams, n_req * new_tok / dt

    off_streams, off_tok_s = serve_all(build(False, slots=2))
    on_eng = build(True, slots=2)
    on_streams, on_tok_s = serve_all(on_eng)
    assert on_streams == off_streams, (
        "prefix-shared streams diverge from the unshared oracle")
    st = on_eng._kv.stats()
    assert st["prefix_hits"] >= n_req - 1, st
    speedup = on_tok_s / max(off_tok_s, 1e-9)

    # -- admissions on a FIXED pool: shared blocks charge once ----------
    pool = 64                     # unshared: 17 blocks/request -> 3 fit
    probe_off = build(False, slots=n_req, num_blocks=pool)
    admitted_off = 0
    for s in range(n_req):
        if not probe_off.begin_request(s, prompts[s], new_tok):
            break
        admitted_off += 1
    probe_on = build(True, slots=n_req, num_blocks=pool)
    probe_on.prefill(0, prompts[0], budget=new_tok)  # seed the tree
    admitted_on = 1
    for s in range(1, n_req):
        if not probe_on.begin_request(s, prompts[s], new_tok):
            break
        admitted_on += 1
    ratio_adm = admitted_on / max(admitted_off, 1)

    _emit("prefix_sharing_kv", speedup, "x", speedup / 1.5, {
        "requests": n_req, "prefix_tokens": prefix_len,
        "tail_tokens": tail_len, "new_tokens": new_tok,
        "block_size": bs,
        "tokens_per_sec_shared": round(on_tok_s, 1),
        "tokens_per_sec_unshared": round(off_tok_s, 1),
        "prefix_hits": st["prefix_hits"],
        "prefix_tokens_reused": st["prefix_tokens_reused"],
        "pool_blocks": pool,
        "admitted_shared": admitted_on,
        "admitted_unshared": admitted_off,
        "admitted_ratio": round(ratio_adm, 2),
        "streams_bit_equal": True,
        "bar": ">=1.5x tokens/s AND >=2x admitted slots vs "
               "FLAGS_serving_prefix_cache=0, streams bit-equal",
        "backend": jax.default_backend()})
    assert speedup >= 1.5, (
        f"prefix sharing served only {speedup:.2f}x the unshared "
        f"tokens/s ({on_tok_s:.1f} vs {off_tok_s:.1f})")
    assert ratio_adm >= 2.0, (
        f"prefix sharing admitted only {admitted_on} slots vs "
        f"{admitted_off} unshared on a {pool}-block pool")


def bench_llama_decode_speculative():
    """Speculative paged decode vs plain paged decode, same geometry
    (ISSUE 12). The draft is the truncated-layer view with the
    target's TAIL residual contributions zeroed (o_proj/down_proj = 0
    — those layers add exactly 0 to the stream), so draft and target
    compute the same function: the repeat-friendly upper bound where
    every window is accepted. What the line grades is the real
    mechanics balance — k cheap draft forwards + ONE batched verify +
    accept/rollback bookkeeping against k plain decode steps (each a
    host round-trip, the continuous-batching server contract on both
    sides). Acceptance/rollback counters ride in detail; bars:
    spec tokens/s >= plain tokens/s AND > 1 committed token per
    target step."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import PagedLlamaDecodeEngine

    if _on_tpu():
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=3584, intermediate_size=9728,
            num_hidden_layers=6, num_attention_heads=28,
            num_key_value_heads=28, max_position_embeddings=2048,
            dtype="bfloat16")
        slots, max_seq, windows, prompt_len = 8, 1024, 24, 64
        spec_k, draft_layers = 4, 3
    else:
        cfg = LlamaConfig.tiny(num_hidden_layers=4)
        cfg.dtype = "float32"
        slots, max_seq, windows, prompt_len = 2, 512, 8, 16
        spec_k, draft_layers = 4, 2
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if cfg.dtype == "bfloat16":
        model.bfloat16()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               for _ in range(slots)]
    steps = windows * spec_k
    reps = 3                 # best-of: shared bench hosts are noisy
    budget = reps * steps + 2 * spec_k + 8

    def _zero_tail(eng):
        for lp in eng.params["layers"][draft_layers:]:
            lp["o_proj"] = jnp.zeros_like(lp["o_proj"])
            lp["down_proj"] = jnp.zeros_like(lp["down_proj"])

    def _prefill_all(eng):
        for s in range(slots):
            eng.prefill(s, prompts[s], budget=budget)

    # plain per-step paged decode (the pre-spec server loop),
    # best-of-reps against host noise
    plain = PagedLlamaDecodeEngine(model, max_slots=slots,
                                   max_seq=max_seq)
    _zero_tail(plain)
    _prefill_all(plain)
    for _ in range(4):
        plain.step()                       # warm
    plain_dt = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            plain.step()
        plain_dt = min(plain_dt, time.perf_counter() - t0)
    plain_tok_s = slots * steps / plain_dt

    # speculative: k draft proposals + one batched verify per window
    spec = PagedLlamaDecodeEngine(model, max_slots=slots,
                                  max_seq=max_seq)
    _zero_tail(spec)
    spec.attach_draft(spec.make_draft(model, num_layers=draft_layers),
                      spec_tokens=spec_k)
    _prefill_all(spec)
    for _ in range(2):
        spec.spec_step()                   # warm propose + verify
    spec_dt, committed = float("inf"), 0
    for _ in range(reps):
        got = 0
        t0 = time.perf_counter()
        for _ in range(windows):
            _, counts = spec.spec_step()
            got += int(counts[spec.active].sum())
        dt = time.perf_counter() - t0
        if got / dt > committed / spec_dt:   # 0/inf == 0.0 first rep
            spec_dt, committed = dt, got
    spec_tok_s = committed / spec_dt
    per_step = committed / (windows * slots)
    ratio = spec_tok_s / max(plain_tok_s, 1e-9)
    from paddle_tpu.observability import metrics as om
    snap = om.snapshot().get("serving", {})
    proposed = snap.get("spec_proposed_total", 0)
    accepted = snap.get("spec_accepted_total", 0)
    _emit("llama_decode_speculative_tokens_per_sec", spec_tok_s,
          "tokens/s", ratio, {
              "slots": slots, "max_seq": max_seq,
              "spec_tokens": spec_k, "draft_layers": draft_layers,
              "target_layers": cfg.num_hidden_layers,
              "windows": windows,
              "committed_per_target_step": round(per_step, 3),
              "acceptance_rate": round(accepted / max(proposed, 1), 3),
              "rolled_back_blocks":
                  snap.get("spec_rolled_back_total", 0),
              "plain_tokens_per_sec": round(plain_tok_s, 2),
              "spec_vs_plain": round(ratio, 3),
              "draft": "truncated-layer view, tail residual "
                       "contributions zeroed (exact-agreement = the "
                       "repeat-friendly acceptance upper bound)",
              "bar": "spec >= plain tokens/s AND > 1 committed "
                     "token per target step",
              "backend": jax.default_backend()})
    assert per_step > 1.0, (
        f"speculative decode committed only {per_step:.2f} tokens per "
        f"target step (needs > 1 to beat plain stepping)")
    assert ratio >= 1.0, (
        f"speculative decode ({spec_tok_s:.1f} tok/s) slower than "
        f"plain paged decode ({plain_tok_s:.1f} tok/s)")


def bench_paged_attention_paths():
    """The two implementations behind the serving_cache.paged_attention
    seam: PARITY of the Pallas block-table kernel against the jnp tile
    walk (its numerics oracle) on the decode geometry, plus the walk's
    per-call latency. On CPU hosts the kernel runs through the Pallas
    interpreter for the parity check only (interpreter latency is
    meaningless); on a real TPU the kernel path is timed too and its
    speedup rides in detail. Value = jnp-walk µs per decode-step call;
    grade = parity (1.0 when the paths agree to tolerance)."""
    import functools
    import jax
    import jax.numpy as jnp
    from paddle_tpu import serving_cache as sc
    from paddle_tpu.ops.pallas import paged_attention as pk

    rng = np.random.default_rng(0)

    def build(S, T, H, K, D, bs, MB):
        NB = S * MB
        q = jnp.asarray(rng.standard_normal((S, T, H, D)),
                        jnp.float32)
        kp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((NB, bs, K, D)),
                         jnp.float32)
        tables = jnp.asarray(
            rng.permutation(NB).reshape(S, MB).astype(np.int32))
        pos = jnp.asarray(
            rng.integers(bs * (MB - 1), bs * MB - T,
                         (S, 1)).astype(np.int32)
            + np.arange(T, dtype=np.int32)[None, :])
        return q, kp, vp, tables, pos

    # latency: the serving decode-step geometry (full tables walk)
    S, T, H, K, D, bs, MB = 8, 1, 8, 2, 64, 16, 32
    q, kp, vp, tables, pos = build(S, T, H, K, D, bs, MB)
    walk = jax.jit(functools.partial(sc.paged_attention,
                                     block_size=bs, n_rep=H // K,
                                     use_kernel=False))
    walk(q, kp, vp, tables, pos).block_until_ready()   # warm
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        out = walk(q, kp, vp, tables, pos)
    out.block_until_ready()
    walk_us = (time.perf_counter() - t0) / reps * 1e6

    detail = {"geometry": {"slots": S, "q_tokens": T, "heads": H,
                           "kv_heads": K, "head_dim": D,
                           "block_size": bs, "max_blocks": MB},
              "walk_us_per_call": round(walk_us, 1),
              "pallas_available": pk._HAS_PALLAS,
              "kernel_on_backend": pk.kernel_available(),
              "backend": jax.default_backend()}
    parity_ok = True
    if pk._HAS_PALLAS:
        # parity on a smaller geometry (the interpreter pays per grid
        # program); tolerance matches the seam's CPU parity test
        qs, kps, vps, ts_, ps = build(4, 2, 8, 2, 64, 16, 8)
        ref = sc.paged_attention(qs, kps, vps, ts_, ps, block_size=16,
                                 n_rep=4, use_kernel=False)
        interp = not pk.kernel_available()
        got = pk.paged_attention_kernel(qs, kps, vps, ts_, ps,
                                        block_size=16, n_rep=4,
                                        interpret=interp)
        diff = float(jnp.max(jnp.abs(ref - got)))
        parity_ok = diff <= 1e-5
        detail["parity_max_abs_diff"] = diff
        detail["parity_mode"] = "interpret" if interp else "tpu"
        if pk.kernel_available():
            kern = jax.jit(functools.partial(
                sc.paged_attention, block_size=bs, n_rep=H // K,
                use_kernel=True))
            kern(q, kp, vp, tables, pos).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(reps):
                out = kern(q, kp, vp, tables, pos)
            out.block_until_ready()
            kernel_us = (time.perf_counter() - t0) / reps * 1e6
            detail["kernel_us_per_call"] = round(kernel_us, 1)
            detail["kernel_speedup"] = round(walk_us / kernel_us, 2)
    else:
        detail["parity"] = "skipped — Pallas unavailable (jnp walk " \
                           "is the only path)"
    _emit("paged_attention_paths", walk_us, "us/call",
          1.0 if parity_ok else 0.0, detail)
    assert parity_ok, detail


def bench_bert_base():
    """BASELINE workload 2: BERT-base MLM, static graph + fusion — the
    whole step through one compiled executable (the CINN-fusion analog).
    MFU vs the 0.45 A100 bar."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models import BertConfig, BertForMaskedLM

    if _on_tpu():
        cfg = BertConfig()  # base: L12 H768 A12
        # 24 chained steps: steady-state rate (short chains pay the
        # tunnel dispatch pipeline fill — see the ResNet note).
        # batch 24: the xplane trace showed batch 64 at the 16GB HBM
        # edge — XLA re-materialized every FFN fusion (~21 ms/step of
        # re-execution) and spilled; 24 clears the pressure (measured
        # 113K -> 131K tok/s, MFU 0.46 -> 0.535)
        batch, seq, steps = 24, 512, 24
    else:
        cfg = BertConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         intermediate_size=64, max_position_embeddings=64)
        batch, seq, steps = 2, 16, 2
    paddle.seed(0)
    model = BertForMaskedLM(cfg)
    model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)

    crit = paddle.nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        # 3-D logits go straight to CrossEntropyLoss, whose big-vocab
        # dispatch routes to the chunked fused CE: the old flatten-to-2D
        # reshape bypassed that routing, so plain CE converted the full
        # [B, L, 30522] logits to f32 (2x 1.2 ms/step in the xplane
        # trace) and XLA materialized a 1.9 GB logits copy (5.9 ms/step)
        return crit(logits, labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                   (batch, seq)).astype(np.int32))
    with jax.default_matmul_precision("bfloat16"):
        float(step(ids, ids))
        float(step(ids, ids))
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = step(ids, ids)
        loss = float(loss)
        dt = time.perf_counter() - t0
    tok = batch * seq * steps / dt
    mfu = tok * 6 * n_params / _peak_flops()
    _emit("bert_base_mlm_tokens_per_sec", tok, "tokens/s",
          mfu / _BASELINE_MFU, {
              "params": n_params, "batch": batch, "seq": seq,
              "mfu": round(mfu, 4), "loss": round(loss, 4),
              "backend": jax.default_backend(),
              **_hbm_detail(step, ids, ids)})


def bench_gpt13b_geometry():
    """BASELINE workload 4: GPT-3 13B geometry (hidden 5120, 40 heads),
    depth-scaled to one chip's HBM; the full 13B TP x PP x sharding mesh
    program is validated by dryrun_multichip (MULTICHIP json). MFU vs the
    0.45 bar — per-layer compute is geometry-identical to 13B."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if _on_tpu():
        cfg = GPTConfig(vocab_size=50304, hidden_size=5120,
                        num_hidden_layers=3, num_attention_heads=40,
                        intermediate_size=20480,
                        max_position_embeddings=2048)
        batch, seq, steps = 4, 2048, 8
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=2,
                        intermediate_size=64, max_position_embeddings=64)
        batch, seq, steps = 2, 16, 2
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)
    crit = paddle.nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return crit(logits.reshape([-1, cfg.vocab_size]),
                    labels.reshape([-1]))

    step = DistTrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                   (batch, seq)).astype(np.int32))
    with jax.default_matmul_precision("bfloat16"):
        float(step(ids, ids))
        float(step(ids, ids))
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = step(ids, ids)
        loss = float(loss)
        dt = time.perf_counter() - t0
    tok = batch * seq * steps / dt
    mfu = tok * 6 * n_params / _peak_flops()
    _emit("gpt13b_geometry_tokens_per_sec_per_chip", tok, "tokens/s",
          mfu / _BASELINE_MFU, {
              "params": n_params, "hidden": cfg.hidden_size,
              "heads": cfg.num_attention_heads, "layers_on_chip":
              cfg.num_hidden_layers, "mfu": round(mfu, 4),
              "loss": round(loss, 4),
              "mesh_validated_by": "MULTICHIP dryrun (tp x pp x fsdp)",
              "backend": jax.default_backend(),
              **_hbm_detail(step, ids, ids)})


def bench_moe_dispatch():
    """BASELINE workload 5: ERNIE-MoE expert dispatch throughput.
    vs_baseline is an ABSOLUTE bar: measured MFU over the useful MoE
    FLOPs (gate + dispatched tokens' expert FFNs, fwd+bwd) against 0.40
    — the utilization the reference's CUTLASS fused MoE GEMM exists to
    deliver (ref: phi/kernels/fusion/cutlass/fused_moe_kernel.cu).
    The dense one-hot dispatch oracle (reference global_scatter algebra)
    is kept in detail as dense_oracle_ms/dense_speedup."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.incubate.moe import _gshard_dispatch
    from paddle_tpu.incubate.moe_dispatch import moe_forward_indices

    if _on_tpu():
        # 32K tokens: an expert-parallel global batch, and the regime
        # the index path exists for — dense one-hot dispatch/combine
        # einsums are quadratic in T (~T * E*C * H with E*C ~ 2.5T), so
        # tiny-T measurements flatter the dense algebra instead of
        # measuring the scalable path (MoELayer's dispatch_mode="auto"
        # routes small batches to dense for exactly that reason)
        # 24 chained steps: the closing value fetch costs one ~70-100ms
        # tunnel round-trip (xplane shows the 6-step run's device steps
        # back-to-back at 30.9 ms each, yet 6 steps measured 42.9 —
        # the fetch amortized over too few steps)
        T, E, H, F, steps = 32768, 16, 1024, 4096, 24
    else:
        T, E, H, F, steps = 64, 4, 16, 32, 2
    cap = max(1, int(1.25 * T * 2 / E))
    rng = np.random.default_rng(0)
    # bf16 activations/weights, like every other workload here (and the
    # reference's fp16 CUTLASS MoE GEMM); gate logits stay fp32. The
    # grouped-matmul kernel accumulates in fp32 either way.
    wdt = jnp.bfloat16 if _on_tpu() else jnp.float32
    tokens = jnp.asarray(rng.standard_normal((T, H)).astype(np.float32)
                         * 0.1, wdt)
    gw = jnp.asarray(rng.standard_normal((H, E)).astype(np.float32))
    wi = jnp.asarray(rng.standard_normal((E, H, F)).astype(np.float32)
                     * 0.02, wdt)
    wo = jnp.asarray(rng.standard_normal((E, F, H)).astype(np.float32)
                     * 0.02, wdt)

    def dense_fwd(tk, wi_, wo_):
        logits = tk @ gw
        combine, dispatch, aux = _gshard_dispatch(logits, 2, cap)
        xs = jnp.einsum("tec,th->ech", dispatch.astype(tk.dtype), tk)
        hdn = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", xs, wi_))
        ys = jnp.einsum("ecf,efh->ech", hdn, wo_)
        return jnp.einsum("tec,ech->th", combine.astype(tk.dtype), ys)

    def index_fwd(tk, wi_, wo_):
        return moe_forward_indices(tk, gw, wi_, wo_, 2, cap,
                                   jax.nn.gelu)[0]

    def train(fwd):
        @jax.jit
        def f(tk, wi_, wo_):
            def loss(wi2, wo2):
                out = fwd(tk, wi2, wo2).astype(jnp.float32)
                return jnp.sum(out ** 2)
            l, g = jax.value_and_grad(loss, argnums=(0, 1))(wi_, wo_)
            return l, g
        return f

    def timeit(f):
        l, _ = f(tokens, wi, wo)
        float(l)
        best = float("inf")
        for _ in range(3):  # best-of windows: the tunnel wobbles ±5%
            t0 = time.perf_counter()
            l = None
            for _ in range(steps):
                l, _ = f(tokens, wi, wo)
            float(l)
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    t_dense = timeit(train(dense_fwd))
    t_index = timeit(train(index_fwd))
    tok_s = T / t_index
    # absolute utilization: useful MoE FLOPs = gate matmul + the
    # dispatched tokens' expert FFNs, fwd ~1x + bwd ~2x (dx through
    # combine + dw for wi/wo). Capacity padding is NOT counted useful.
    moe_bar = 0.40
    dispatched = min(T * 2, E * cap)
    flops_fwd = 2 * T * H * E + dispatched * 2 * (2 * H * F)
    mfu = 3 * flops_fwd / t_index / _peak_flops()
    _emit("ernie_moe_dispatch_tokens_per_sec", tok_s, "tokens/s",
          mfu / moe_bar, {
              "tokens": T, "experts": E, "capacity": cap,
              "index_ms": round(t_index * 1e3, 2),
              "dense_oracle_ms": round(t_dense * 1e3, 2),
              "dense_speedup": round(t_dense / t_index, 2),
              "mfu": round(mfu, 4), "mfu_bar": moe_bar,
              "baseline": "absolute expert-FFN utilization bar 0.40 "
                          "(CUTLASS fused MoE GEMM role)",
              "backend": "tpu" if _on_tpu() else "cpu"})


def bench_dispatch_overhead():
    """Eager dispatch µs/op on the cached-hit path (VERDICT r3 item 6;
    ref: the reference's sub-10µs eager hot loop, SURVEY §3.1 +
    test/cpp/eager/performance_tests/benchmark_eager_cuda.cc). Measures
    the grad-recording path — forward through the cached jitted pair +
    GradNode wiring — which was 1.5 ms/op before the fast path. Budget:
    150 µs/op on the tunneled dev chip (raw jnp dispatch itself is
    ~32 µs there); vs_baseline = budget / measured."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle

    budget_us = 150.0
    # quiesce: this bench runs after the big workloads; pending
    # finalizers/garbage distort µs-level host timing
    import gc
    gc.collect()
    a = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((128, 128))
        .astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones((128, 128), np.float32))

    def one():
        return paddle.add(a, b)

    for _ in range(5):
        one()
    jax.block_until_ready(jnp.zeros(()))
    n = 500

    def best_of(fn, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e6

    us = best_of(one)
    raw = best_of(lambda: jnp.add(a._data, b._data))
    # overhead above the raw-jnp floor is the framework's own cost; the
    # floor itself is environment (tunnel/host load) and is reported
    # alongside so a loaded run is readable
    _emit("eager_dispatch_overhead_us", us, "us/op", budget_us / us, {
        "path": "grad-recording add, cached jit pair",
        "raw_jnp_dispatch_us": round(raw, 1),
        "overhead_above_floor_us": round(us - raw, 1),
        "budget_us": budget_us,
        "backend": jax.default_backend()})


def bench_metrics_overhead():
    """metrics_overhead: per-dispatch telemetry cost with FLAGS_metrics
    on, as % of the cached-hit eager dispatch time — the always-on
    claim's ≤5% bar, enforced rather than asserted.

    The hot path carries exactly ONE instrument operation per dispatch
    (a guarded counter bump in _op_gate; all per-op attribution is
    snapshot-time collectors), so the graded number multiplies the
    DIRECTLY measured cost of that operation against the measured
    dispatch µs. An end-to-end on/off A/B of the same dispatch loop is
    reported alongside in detail — on this class of shared bench host
    its run-to-run load noise (±15µs/op observed across identical
    configs) cannot resolve the ~0.1µs quantity under test, which is
    why it informs but does not grade."""
    import gc

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.observability import metrics as om

    gc.collect()
    a = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((128, 128))
        .astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones((128, 128), np.float32))

    def one():
        return paddle.add(a, b)

    # fusion OFF: with it on, every 32nd add pays a chain flush inside
    # the timed window and that jitter swamps the per-op number; the
    # plain cached-jit-pair dispatch is the hot path the bar is over
    prev_fusion = paddle.get_flags("FLAGS_eager_fusion")
    prev = paddle.get_flags("FLAGS_metrics")
    paddle.set_flags({"FLAGS_eager_fusion": 0})
    for _ in range(5):
        one()
    jax.block_until_ready(jnp.zeros(()))
    n = 500

    def window():
        t0 = time.perf_counter()
        for _ in range(n):
            one()
        return (time.perf_counter() - t0) / n * 1e6

    # direct cost of the per-dispatch instrument op (the exact code
    # _op_gate runs): guarded attribute bump, loop overhead included
    flag = om.flag_info()
    probe = om.counter("bench.metrics_probe_total")
    m = 200_000

    def inc_window():
        t0 = time.perf_counter()
        for _ in range(m):
            if flag.value:
                probe._v += 1
        return (time.perf_counter() - t0) / m * 1e6

    on_us = off_us = inc_us = float("inf")
    try:
        paddle.set_flags({"FLAGS_metrics": 1})
        for _ in range(5):
            inc_us = min(inc_us, inc_window())
        for _ in range(7):  # interleaved best-of: shared-host load drift
            paddle.set_flags({"FLAGS_metrics": 1})
            on_us = min(on_us, window())
            paddle.set_flags({"FLAGS_metrics": 0})
            off_us = min(off_us, window())
    finally:
        paddle.set_flags(prev)
        paddle.set_flags(prev_fusion)
    overhead_pct = inc_us / off_us * 100.0
    e2e_pct = (on_us - off_us) / off_us * 100.0
    _emit("metrics_overhead", overhead_pct, "%",
          5.0 / max(overhead_pct, 0.01), {
              "per_dispatch_instrument_us": round(inc_us, 4),
              "dispatch_us_per_op": round(off_us, 2),
              "e2e_on_us_per_op": round(on_us, 2),
              "e2e_off_us_per_op": round(off_us, 2),
              "e2e_delta_pct_noisy": round(e2e_pct, 2),
              "bar": "<=5% dispatch overhead with FLAGS_metrics on",
              "path": "grad-recording add, cached jit pair",
              "backend": jax.default_backend()})


def bench_flight_overhead():
    """flight_recorder_overhead: direct per-event append cost of the
    always-on flight recorder with FLAGS_flight_recorder on, as % of
    the cached-hit eager dispatch time — the ≤5% bar metrics_overhead
    set, applied to the black-box journal.

    Like metrics_overhead, the graded number is the DIRECTLY measured
    append cost (clock read + tuple + ring append through the public
    record() path, steady-state with the ring full so eviction cost is
    included) divided by the measured dispatch µs: shared-host e2e A/B
    noise (±15µs/op) cannot resolve a sub-µs quantity, so the e2e
    delta is reported in detail but does not grade. NOTE the hot
    dispatch path records NO event per op (events come from chain
    flushes, syncs and lifecycle edges); per-event-per-dispatch is the
    conservative worst case."""
    import gc

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.observability import flight

    gc.collect()
    a = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((128, 128))
        .astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones((128, 128), np.float32))

    def one():
        return paddle.add(a, b)

    prev_fusion = paddle.get_flags("FLAGS_eager_fusion")
    prev = paddle.get_flags("FLAGS_flight_recorder")
    paddle.set_flags({"FLAGS_eager_fusion": 0})
    for _ in range(5):
        one()
    jax.block_until_ready(jnp.zeros(()))
    n = 500

    def window():
        t0 = time.perf_counter()
        for _ in range(n):
            one()
        return (time.perf_counter() - t0) / n * 1e6

    m = 200_000

    def append_window():
        t0 = time.perf_counter()
        for _ in range(m):
            flight.record("bench", "probe")
        return (time.perf_counter() - t0) / m * 1e6

    on_us = off_us = ev_us = float("inf")
    try:
        paddle.set_flags({"FLAGS_flight_recorder": 1})
        for _ in range(5):
            ev_us = min(ev_us, append_window())
        for _ in range(7):  # interleaved best-of: shared-host drift
            paddle.set_flags({"FLAGS_flight_recorder": 1})
            on_us = min(on_us, window())
            paddle.set_flags({"FLAGS_flight_recorder": 0})
            off_us = min(off_us, window())
    finally:
        paddle.set_flags(prev)
        paddle.set_flags(prev_fusion)
        flight.clear()  # drop the bench probes from the black box
    overhead_pct = ev_us / off_us * 100.0
    e2e_pct = (on_us - off_us) / off_us * 100.0
    _emit("flight_recorder_overhead", overhead_pct, "%",
          5.0 / max(overhead_pct, 0.01), {
              "per_event_append_us": round(ev_us, 4),
              "dispatch_us_per_op": round(off_us, 2),
              "ring_capacity": flight._capacity(),
              "e2e_on_us_per_op": round(on_us, 2),
              "e2e_off_us_per_op": round(off_us, 2),
              "e2e_delta_pct_noisy": round(e2e_pct, 2),
              "bar": "<=5% of dispatch per event with "
                     "FLAGS_flight_recorder on",
              "path": "record() into a full ring, steady state",
              "backend": jax.default_backend()})


def bench_eager_fusion():
    """eager_fusion_speedup: µs/op for a cached 12-op elementwise chain
    on the grad-recording eager path, lazy-eager fusion ON (one jitted
    executable per chain, core/fusion.py) vs OFF (per-op dispatch,
    FLAGS_eager_fusion=0). The fused chain does ONE dispatch and ONE
    memory pass where the unfused path does 12 of each — the locality
    win chain fusion exists for. Bar: >=4x lower µs/op fused."""
    import gc

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import fusion

    gc.collect()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((256, 256))
                         .astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal((256, 256))
                         .astype(np.float32))

    def chain(t):
        for _ in range(4):
            t = paddle.multiply(t, b)
            t = paddle.add(t, b)
            t = paddle.subtract(t, 0.125)
        return t

    def measure(n=150, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                chain(x).numpy()  # host read closes every chain
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e6 / 12.0

    prev = paddle.get_flags("FLAGS_eager_fusion")
    try:
        paddle.set_flags({"FLAGS_eager_fusion": 1})
        for _ in range(20):
            chain(x).numpy()
        s0 = fusion.stats()
        fused_us = measure()
        s1 = fusion.stats()
        paddle.set_flags({"FLAGS_eager_fusion": 0})
        for _ in range(20):
            chain(x).numpy()
        unfused_us = measure()
    finally:
        paddle.set_flags(prev)
    flushes = max(s1["chains_flushed"] - s0["chains_flushed"], 1)
    hit_rate = (s1["cache_hits"] - s0["cache_hits"]) / flushes
    speedup = unfused_us / fused_us
    _emit("eager_fusion_speedup", speedup, "x", speedup / 4.0, {
        "fused_us_per_op": round(fused_us, 1),
        "unfused_us_per_op": round(unfused_us, 1),
        "chain_ops": 12, "shape": [256, 256], "grad_recording": True,
        "steady_state_cache_hit_rate": round(hit_rate, 4),
        "new_compiles_in_timed_window":
            s1["cache_misses"] - s0["cache_misses"],
        "bar": ">=4x lower us/op for the cached 12-op chain",
        "backend": jax.default_backend()})


def bench_reduction_fusion():
    """reduction_fusion_speedup: direct µs/op for (a) a cached
    reduction-TERMINATED chain — 16 elementwise ops + square + mean
    (RED_OPS=18), one fused executable through a host scalar read per
    iteration — and (b) a
    matmul-epilogue chain (x@w + b -> tanh), each vs the identical loop
    under FLAGS_eager_fusion=0 (per-op dispatch). Graded on the DIRECT
    best-of cost ratio of the reduction chain: on this class of shared
    bench host the ±15 µs/op e2e load noise cannot resolve small A/B
    deltas, but the quantity under test here is the whole multiple-x
    dispatch-count collapse, which best-of interleaved windows resolve
    fine. The epilogue ratio is reported in detail but NOT graded: on a
    CPU bench host the 256^3 dot dominates both paths (~1 ms) and
    XLA:CPU trades its library-GEMM fast path when an elementwise
    epilogue fuses into the dot, so the A/B there sits at ~1x inside
    host noise — the epilogue win this measures for regression is the
    TPU MXU/HBM-locality one. Bar: >=3x lower µs/op fused for the
    reduction chain, 100% steady-state cache hits."""
    import gc

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import fusion

    gc.collect()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((256, 256))
                         .astype(np.float32), stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal((256, 256))
                         .astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((256, 256))
                         .astype(np.float32), stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal((256,))
                            .astype(np.float32))

    RED_OPS = 18  # 8x(mul, add) + square + mean

    def _red_build():
        # loss built in its own frame, loss-fn style: the requires-grad
        # intermediates are DEAD by flush time, so the whole chain is
        # one executable (a live named rg intermediate would be a tape
        # edge and cut the program there — eager semantics)
        t = x
        for _ in range(8):
            t = paddle.multiply(t, b)
            t = paddle.add(t, 0.125)
        return paddle.mean(paddle.square(t))

    def red_loss():
        return float(_red_build().numpy())

    EPI_OPS = 3  # matmul + add + tanh

    def epi_step():
        return paddle.tanh(
            paddle.add(paddle.matmul(x, w), bias)).numpy()

    def measure(fn, ops, n=120, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e6 / ops

    prev = paddle.get_flags(["FLAGS_eager_fusion",
                             "FLAGS_eager_fusion_reduce",
                             "FLAGS_eager_fusion_epilogue"])
    try:
        paddle.set_flags({"FLAGS_eager_fusion": 1,
                          "FLAGS_eager_fusion_reduce": 1,
                          "FLAGS_eager_fusion_epilogue": 1})
        for _ in range(20):
            red_loss()
            epi_step()
        s0 = fusion.stats()
        red_fused = measure(red_loss, RED_OPS)
        s1 = fusion.stats()
        epi_fused = measure(epi_step, EPI_OPS)
        paddle.set_flags({"FLAGS_eager_fusion": 0})
        for _ in range(20):
            red_loss()
            epi_step()
        red_unfused = measure(red_loss, RED_OPS)
        epi_unfused = measure(epi_step, EPI_OPS)
    finally:
        paddle.set_flags(prev)
    flushes = max(s1["chains_flushed"] - s0["chains_flushed"], 1)
    hit_rate = (s1["cache_hits"] - s0["cache_hits"]) / flushes
    red_speedup = red_unfused / red_fused
    epi_speedup = epi_unfused / epi_fused
    _emit("reduction_fusion_speedup", red_speedup, "x",
          red_speedup / 3.0, {
              "reduce_chain_ops": RED_OPS,
              "reduce_fused_us_per_op": round(red_fused, 1),
              "reduce_unfused_us_per_op": round(red_unfused, 1),
              "epilogue_chain_ops": EPI_OPS,
              "epilogue_fused_us_per_op": round(epi_fused, 1),
              "epilogue_unfused_us_per_op": round(epi_unfused, 1),
              "epilogue_speedup": round(epi_speedup, 2),
              "shape": [256, 256], "grad_recording": True,
              "steady_state_cache_hit_rate": round(hit_rate, 4),
              "new_compiles_in_timed_window":
                  s1["cache_misses"] - s0["cache_misses"],
              "reductions_fused_in_window":
                  s1["reductions_fused"] - s0["reductions_fused"],
              "bar": ">=3x lower direct us/op for the reduction-"
                     "terminated chain (graded on direct cost; shared-"
                     "host e2e noise ±15us/op documented in detail)",
              "backend": jax.default_backend()})


def bench_fused_optimizer_step():
    """fused_optimizer_step_us: direct per-param cost of one optimizer
    step for a 64-param model — AdamW + global-norm clip + a changing
    (cosine) LR schedule — with the step fused into ONE buffer-donated
    executable (FLAGS_fused_optimizer=1) vs the per-param eager update
    loop (=0, ~10 tiny dispatches per param plus a full clip pass).
    Graded on the directly measured step cost per the ±15µs host-noise
    rule (an e2e train-loop A/B can't resolve the delta on this host);
    bar: >= 3x lower per-param cost fused, with 100% steady-state cache
    hits and <= 1 compile across the whole changing-LR schedule."""
    import gc
    import time as _t

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.optimizer import fused_step

    gc.collect()
    n_params, shape, steps = 64, (64, 64), 20
    rng = np.random.default_rng(0)
    grads = [rng.normal(size=shape).astype(np.float32) * 1e-3
             for _ in range(n_params)]

    def build():
        ps = [paddle.Parameter(
            np.random.default_rng(i).standard_normal(shape)
            .astype(np.float32)) for i in range(n_params)]
        sched = paddle.optimizer.lr.CosineAnnealingDecay(
            learning_rate=1e-3, T_max=200)
        opt = paddle.optimizer.AdamW(
            learning_rate=sched, parameters=ps,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        # grads persist across steps: the plain fused path donates only
        # params + state, so the same grad buffers are reusable
        for p, g in zip(ps, grads):
            p.grad = paddle.to_tensor(g)
        return ps, sched, opt

    def measure(reps=3):
        ps, sched, opt = build()
        for _ in range(3):  # first sighting + compile + one hit
            opt.step()
            sched.step()
        jax.block_until_ready(ps[0]._data)
        best = float("inf")
        for _ in range(reps):
            t0 = _t.perf_counter()
            for _ in range(steps):
                opt.step()
                sched.step()
            jax.block_until_ready(ps[0]._data)
            best = min(best, (_t.perf_counter() - t0) / steps)
        return best * 1e6  # µs per whole step

    prev = paddle.get_flags("FLAGS_fused_optimizer")
    try:
        paddle.set_flags({"FLAGS_fused_optimizer": 1})
        fused_step.clear_cache()
        before = dict(om.snapshot().get("optimizer", {}))
        fused_us = measure()
        after = dict(om.snapshot().get("optimizer", {}))
        paddle.set_flags({"FLAGS_fused_optimizer": 0})
        eager_us = measure()
    finally:
        paddle.set_flags(prev)

    def delta(k):
        return int(after.get(k, 0) - before.get(k, 0))

    compiles = delta("fused_compiles_total")
    hits = delta("cache_hits_total")
    fused_steps = delta("fused_steps_total")
    fused_pp = fused_us / n_params
    eager_pp = eager_us / n_params
    speedup = eager_pp / max(fused_pp, 1e-9)
    # steady state = every step after the first sighting + the compile
    hit_rate = hits / max(fused_steps - 2, 1) * 100.0
    _emit("fused_optimizer_step_us", fused_pp, "us/param", speedup / 3.0, {
        "fused_us_per_param": round(fused_pp, 3),
        "unfused_us_per_param": round(eager_pp, 3),
        "speedup": round(speedup, 1),
        "fused_step_us": round(fused_us, 1),
        "unfused_step_us": round(eager_us, 1),
        "n_params": n_params,
        "compiles_across_changing_lr_schedule": compiles,
        "steady_state_cache_hit_pct": round(hit_rate, 1),
        "donated_bytes_per_step": delta("donated_bytes") // max(
            hits + compiles, 1),
        "optimizer": "AdamW + ClipGradByGlobalNorm + CosineAnnealingDecay",
        "bar": ">=3x lower direct per-param cost, 100% steady-state "
               "hits, <=1 compile across the LR schedule",
        "backend": jax.default_backend()})


def bench_whole_step_capture():
    """whole_step_capture_speedup: steady-state per-step wall time of a
    llama tiny ``Model.fit``-shape train step with SOT whole-step
    capture ON (one cached, donated fwd+bwd+optimizer executable,
    FLAGS_sot_capture=1) vs OFF (per-chain eager fusion + the fused
    optimizer step — today's path). The captured step is ONE dispatch
    where the eager path pays ~8.5µs/op between fused chains
    (BENCH_ALL eager_dispatch_overhead_us — the gap this metric closes;
    this line also lands the dispatch-overhead number BENCH_r05 was
    missing). Asserted: >= 1 captured compile then 100% steady-state
    cache hits. Bar: >= 2x lower per-step wall time captured."""
    import gc
    import time as _t

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.hapi import Model
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)
    from paddle_tpu.observability import metrics as om

    gc.collect()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 32)).astype(np.int64)

    def build():
        paddle.seed(0)
        net = LlamaForCausalLM(LlamaConfig.tiny())
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=net.parameters()),
            loss=LlamaPretrainingCriterion())
        return m

    def measure(m, steps=30, reps=3):
        for _ in range(4):  # sighting + compile + hits
            m.train_batch([ids], [ids])
        # a value transfer is the only trustworthy barrier; the timed
        # loop itself stays fetch-free (the lazy-loss contract)
        float(m.train_batch([ids], [ids])[0])
        best = float("inf")
        last = None
        for _ in range(reps):
            t0 = _t.perf_counter()
            for _ in range(steps):
                last = m.train_batch([ids], [ids])[0]
            float(last)  # one fetch closes the timed window
            best = min(best, (_t.perf_counter() - t0) / steps)
        return best * 1e6

    prev = paddle.get_flags("FLAGS_sot_capture")
    try:
        paddle.set_flags({"FLAGS_sot_capture": 1})
        m = build()
        before = dict(om.snapshot().get("sot", {}))
        captured_us = measure(m)
        after = dict(om.snapshot().get("sot", {}))
        eng_stats = dict(m._captured.stats)
        paddle.set_flags({"FLAGS_sot_capture": 0})
        eager_us = measure(build())
    finally:
        paddle.set_flags(prev)

    def delta(k):
        v = after.get(k, 0)
        b = before.get(k, 0)
        if isinstance(v, dict) or isinstance(b, dict):
            v = sum(v.values()) if isinstance(v, dict) else v
            b = sum(b.values()) if isinstance(b, dict) else b
        return int(v - b)

    compiles = delta("captured_compiles_total")
    captured = delta("captured_steps_total")
    hits = delta("cache_hits_total")
    # steady state = every call after the sighting and the compile
    hit_rate = hits / max(captured - 1, 1) * 100.0
    assert compiles >= 1, "the captured step must compile at least once"
    assert hit_rate >= 99.9, f"steady state must be 100% hits, got " \
                             f"{hit_rate}"
    speedup = eager_us / max(captured_us, 1e-9)
    _emit("whole_step_capture_speedup", speedup, "x", speedup / 2.0, {
        "captured_step_us": round(captured_us, 1),
        "eager_step_us": round(eager_us, 1),
        "captured_compiles": compiles,
        "captured_steps": captured,
        "steady_state_cache_hit_pct": round(hit_rate, 1),
        "guard_misses": delta("guard_misses_total"),
        "fallbacks": eng_stats["fallbacks"],
        "model": "llama tiny (2L/64H) AdamW, batch [2, 32]",
        "bar": ">=2x lower per-step wall time; >=1 compile then 100% "
               "steady-state cache hits",
        "backend": jax.default_backend()})


def bench_amp_captured_step():
    """amp_captured_step_us: steady-state per-step wall time of a llama
    tiny ``Model.fit``-shape AMP/GradScaler train step with whole-step
    capture ON (the ENTIRE iteration — autocast forward, loss scale,
    backward, grad unscale + finite check, device-masked update, scale
    bookkeeping — as ONE donated executable; the PR 10 ``amp``
    fallback residue, now a capture path) vs OFF (eager autocast +
    the fused try_step_scaled path). Asserted: >= 1 captured compile,
    100% steady-state cache hits, ZERO amp-reason fallbacks, and
    captured no slower than eager (>= 1x). Bar: >= 1x."""
    import gc
    import time as _t

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.hapi import Model
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)
    from paddle_tpu.observability import metrics as om

    gc.collect()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 32)).astype(np.int64)

    def build():
        paddle.seed(0)
        net = LlamaForCausalLM(LlamaConfig.tiny())
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=net.parameters()),
            loss=LlamaPretrainingCriterion(),
            amp_configs={"level": "O1", "init_loss_scaling": 1024.0})
        return m

    def measure(m, steps=30, reps=3):
        for _ in range(4):  # sighting + compile + hits
            m.train_batch([ids], [ids])
        float(m.train_batch([ids], [ids])[0])  # barrier
        best = float("inf")
        last = None
        for _ in range(reps):
            t0 = _t.perf_counter()
            for _ in range(steps):
                last = m.train_batch([ids], [ids])[0]
            float(last)  # one fetch closes the timed window
            best = min(best, (_t.perf_counter() - t0) / steps)
        return best * 1e6

    prev = paddle.get_flags("FLAGS_sot_capture")
    try:
        paddle.set_flags({"FLAGS_sot_capture": 1})
        m = build()
        captured_us = measure(m)
        eng_stats = dict(m._captured.stats)
        amp_fallbacks = om.default_registry().get(
            "sot.fallbacks_total").value(reason="amp")
        paddle.set_flags({"FLAGS_sot_capture": 0})
        eager_us = measure(build())
    finally:
        paddle.set_flags(prev)

    assert eng_stats["compiles"] >= 1, eng_stats
    assert eng_stats["fallbacks"] == {}, eng_stats
    assert amp_fallbacks == 0, amp_fallbacks
    hit_rate = eng_stats["cache_hits"] / \
        max(eng_stats["captured_steps"] - 1, 1) * 100.0
    assert hit_rate >= 99.9, eng_stats
    speedup = eager_us / max(captured_us, 1e-9)
    assert speedup >= 1.0, (captured_us, eager_us)
    _emit("amp_captured_step_us", captured_us, "us/step", speedup, {
        "captured_step_us": round(captured_us, 1),
        "eager_amp_step_us": round(eager_us, 1),
        "speedup": round(speedup, 2),
        "captured_compiles": eng_stats["compiles"],
        "steady_state_cache_hit_pct": round(hit_rate, 1),
        "amp_reason_fallbacks": int(amp_fallbacks),
        "scaler": "GradScaler dynamic, init 1024",
        "model": "llama tiny (2L/64H) AdamW O1 bf16, batch [2, 32]",
        "bar": ">= 1x vs eager AMP; >= 1 compile then 100% hits; "
               "0 amp fallbacks",
        "backend": jax.default_backend()})


def _dist_overlap_impl():
    """Worker body for dist_overlap_dryrun (runs under 8 virtual CPU
    devices): both MULTICHIP-validated geometries through the captured
    DistTrainStep with small grad buckets, reporting buckets/step,
    per-bucket bytes, HLO collective sites and captured-vs-epilogue
    (FLAGS_dist_grad_bucket_bytes=0, the pre-T3 program shape)
    compile + step wall time."""
    import re
    import time as _t

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion,
                                   shard_llama)

    n = len(jax.devices())
    crit = LlamaPretrainingCriterion()
    rng = np.random.default_rng(0)
    out = {"devices": n}

    def run_geometry(label, make, ids):
        geo = {}
        for mode, bucket_bytes in (("bucketed", 16384), ("epilogue", 0)):
            paddle.set_flags(
                {"FLAGS_dist_grad_bucket_bytes": bucket_bytes})
            paddle.seed(0)
            step = make()
            t0 = _t.perf_counter()
            float(step(ids, ids))            # trace + compile + run
            compile_s = _t.perf_counter() - t0
            float(step(ids, ids))            # warm
            t0 = _t.perf_counter()
            loss = None
            for _ in range(5):
                loss = step(ids, ids)
            float(loss)
            step_ms = (_t.perf_counter() - t0) / 5 * 1e3
            geo[mode] = {"compile_s": round(compile_s, 2),
                         "step_ms": round(step_ms, 2)}
            if mode == "bucketed":
                plan = step.bucket_plan()
                _, compiled, _ = step.compile_stats(
                    ids, ids, return_compiled=True)
                n_coll = len(re.findall(
                    r"(all-reduce|reduce-scatter)\(",
                    compiled.as_text()))
                geo["buckets_per_step"] = len(plan)
                geo["per_bucket_bytes"] = [b["bytes"] for b in plan]
                geo["hlo_collective_sites"] = n_coll
        out[label] = geo
        return geo

    # geometry 1: llama 7b-ratio shapes under pure ZeRO-3 (fsdp) —
    # the MULTICHIP dryrun '7b' regime
    flat = ProcessMesh(np.arange(n), dim_names=["fsdp"])

    def make_7b():
        cfg = LlamaConfig.tiny(
            num_hidden_layers=2, hidden_size=64, intermediate_size=172,
            num_attention_heads=4, num_key_value_heads=4,
            vocab_size=128, use_flash_attention=False)
        m = LlamaForCausalLM(cfg)
        shard_llama(m, flat, tp_axis=None, fsdp_axis="fsdp")
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        return DistTrainStep(
            m, lambda lg, lb: crit(lg, lb), opt,
            data_sharding=NamedSharding(flat.to_jax_mesh(),
                                        P("fsdp", None)))

    ids7 = rng.integers(0, 128, (n, 16)).astype(np.int32)
    run_geometry("llama7b_fsdp", make_7b, ids7)

    # geometry 2: the gpt13b-style 3-axis mesh (dp x fsdp x tp) the
    # MULTICHIP dryrun validates
    dp, fsdp, mp = max(n // 4, 1), 2 if n % 2 == 0 else 1, \
        2 if n % 4 == 0 else 1
    mesh = ProcessMesh(np.arange(dp * fsdp * mp).reshape(dp, fsdp, mp),
                       dim_names=["dp", "fsdp", "mp"])

    def make_3axis():
        cfg = LlamaConfig.tiny(
            num_hidden_layers=2, hidden_size=16 * mp * fsdp,
            intermediate_size=32 * mp * fsdp,
            num_attention_heads=2 * mp, num_key_value_heads=mp,
            vocab_size=64 * mp, use_flash_attention=False)
        m = LlamaForCausalLM(cfg)
        shard_llama(m, mesh, tp_axis="mp", fsdp_axis="fsdp")
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        return DistTrainStep(
            m, lambda lg, lb: crit(lg, lb), opt,
            data_sharding=NamedSharding(mesh.to_jax_mesh(),
                                        P("dp", None)))

    ids3 = rng.integers(0, 64 * mp, (2 * dp, 16)).astype(np.int32)
    run_geometry("gpt13b_style_3axis", make_3axis, ids3)
    return out


def bench_dist_overlap_dryrun():
    """dist_overlap_dryrun: structural line for the captured
    distributed step's bucketed compute–collective overlap on the two
    MULTICHIP-validated geometries (llama7b fsdp; gpt13b-style
    dp x fsdp x tp), run in a subprocess with 8 virtual CPU devices
    (the tier-1 mesh harness — overlap WALL-TIME wins need real ICI;
    this line pins the program SHAPE: >= 2 buckets per step, their
    payload bytes, the HLO collective sites, and captured-vs-epilogue
    compile+step cost). Bar: both geometries carry >= 2 buckets."""
    import json as _json
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    xf = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        env["XLA_FLAGS"] = \
            (xf + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--dist-overlap-worker"],
        env=env, capture_output=True, text=True, timeout=360)
    if r.returncode != 0:
        raise RuntimeError(
            f"overlap worker rc={r.returncode}: {(r.stderr or '')[-400:]}")
    detail = _json.loads(r.stdout.strip().splitlines()[-1])
    b1 = detail["llama7b_fsdp"]["buckets_per_step"]
    b2 = detail["gpt13b_style_3axis"]["buckets_per_step"]
    assert b1 >= 2 and b2 >= 2, (b1, b2)
    detail["bar"] = ">= 2 gradient sync buckets per step on both " \
                    "MULTICHIP geometries; bucketed == epilogue loss " \
                    "(pinned in tests/test_dist_capture.py)"
    _emit("dist_overlap_dryrun", float(min(b1, b2)), "buckets",
          min(b1, b2) / 2.0, detail)


def _hot_start_impl():
    """Worker body for hot_start_time_to_first_step: ONE process boot
    — build a hapi model + captured train steps and a paged decode
    engine, optionally pre-warmed from HS_BUNDLE — timing from before
    model construction to the first captured-step loss fetch + first
    decode tokens. HS_EXPORT additionally exports the warm bundle and
    seals it (prewarm in-process so the AOT-lowered flavors persist
    too). Cache dir arrives as FLAGS_executable_cache_dir in the
    subprocess env."""
    import time as _t

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.jit import warmup
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import PagedLlamaDecodeEngine

    bundle = os.environ.get("HS_BUNDLE") or None
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.int64)

    t0 = _t.perf_counter()
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(), warm_bundle=bundle)
    loss = None
    for _ in range(3):
        loss = m.train_batch([X], [y])
    float(loss[0])                       # the first-step fetch
    t_train = _t.perf_counter() - t0

    paddle.seed(1)
    lm = LlamaForCausalLM(LlamaConfig.tiny(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, use_flash_attention=False))
    eng = PagedLlamaDecodeEngine(lm, max_slots=2, max_seq=64,
                                 block_size=8, prefill_chunk=16)
    if bundle:
        warmup.prewarm(bundle, engine=eng)
    toks = eng.generate([1, 2, 3, 4], max_new_tokens=4)
    total = _t.perf_counter() - t0

    export = os.environ.get("HS_EXPORT")
    if export:
        warmup.export_bundle(export)
        warmup.prewarm(export, captured=m._captured, engine=eng)
    return {"seconds": round(total, 3),
            "train_seconds": round(t_train, 3),
            "cache": warmup.cache_stats(),
            "captured": dict(m._captured.stats, fallbacks=None),
            "toks": [int(t) for t in toks]}


def bench_hot_start():
    """hot_start_time_to_first_step: cold boot vs pre-warmed boot in
    capped subprocesses sharing ONE executable cache dir. The cold
    worker compiles everything, persists it and exports the warm
    bundle; the warm worker pre-warms from the bundle and must reach
    its first captured train step + first decode tokens with 100%
    persistent-cache hits (misses == 0 asserted) at >= 1x the cold
    wall time (asserted) — the restart-without-compile-storm contract
    (ROADMAP item 5)."""
    import json as _json
    import shutil
    import subprocess
    import sys
    import tempfile

    cache = tempfile.mkdtemp(prefix="hot_start_cache_")
    try:
        bundle = os.path.join(cache, "warm_bundle.json")

        def run(extra):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       FLAGS_executable_cache_dir=cache, **extra)
            env.pop("FLAGS_warmup_bundle", None)
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--hot-start-worker"],
                env=env, capture_output=True, text=True, timeout=390)
            if r.returncode != 0:
                raise RuntimeError(
                    f"hot-start worker rc={r.returncode}: "
                    f"{(r.stderr or '')[-400:]}")
            return _json.loads(r.stdout.strip().splitlines()[-1])

        cold = run({"HS_EXPORT": bundle})
        warm = run({"HS_BUNDLE": bundle})
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    assert warm["cache"]["misses"] == 0, warm["cache"]
    assert warm["cache"]["hits"] > 0, warm["cache"]
    assert warm["toks"] == cold["toks"], (warm, cold)
    speedup = cold["seconds"] / max(warm["seconds"], 1e-9)
    assert speedup >= 1.0, (cold["seconds"], warm["seconds"])
    _emit("hot_start_time_to_first_step", warm["seconds"], "s",
          speedup, {
              "cold_boot_s": cold["seconds"],
              "warm_boot_s": warm["seconds"],
              "cold_train_s": cold["train_seconds"],
              "warm_train_s": warm["train_seconds"],
              "speedup": round(speedup, 2),
              "cold_compiles": cold["cache"]["writes"],
              "warm_cache": warm["cache"],
              "warm_first_batch_captured":
                  warm["captured"]["eager_steps"] == 0,
              "bar": "warm boot >= 1x cold AND 100% executable-cache "
                     "hits (0 fresh XLA compiles, counters pinned)"})


def bench_fleet_failover():
    """fleet_failover_recovery_seconds: SIGKILL one of 2 real replica
    processes mid-decode (armed fleet.apply site — the kill lands the
    moment the router applies that replica's first streamed batch) and
    measure (a) kill -> every accepted stream finished (failover
    recovery; the survivors absorb the re-dispatched work) and
    (b) kill -> the replacement replica rejoined AND served tokens,
    A/B: warm resurrection (shared executable cache + warm bundle,
    misses pinned at 0) vs cold (no cache, no bundle: the replacement
    re-compiles before it is useful). vs_baseline = cold time-to-
    serving / warm (the resurrection speedup the warm plane buys)."""
    import shutil
    import signal as _signal
    import tempfile

    from paddle_tpu.serving_fleet import (ReplicaClient, ReplicaHandle,
                                          launch_replica, spawn_fleet)
    from paddle_tpu.utils import fault_injection as fi

    base = {"model": {"kind": "tiny_llama", "seed": 7, "config": dict(
                vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, use_flash_attention=False)},
            "max_slots": 2, "max_seq": 64, "block_size": 8,
            "prefill_chunk": 8, "supervised": True}
    cache = tempfile.mkdtemp(prefix="fleet_bench_cache_")
    try:
        bundle = os.path.join(cache, "warm.npz")
        env = {"FLAGS_executable_cache_dir": cache}
        # one cold boot seeds the shared cache + seals the bundle
        proc, port, _boot = launch_replica(
            dict(base, prime=[1, 2, 3, 4], export_bundle=bundle),
            env=env)
        ReplicaHandle(0, "127.0.0.1", port, pid=proc.pid,
                      proc=proc).call({"op": "shutdown", "drain": True})
        proc.wait(timeout=120)

        def run(warm):
            cfg = dict(base, warm_bundle=bundle) if warm else dict(base)
            router = spawn_fleet(
                2, cfg, env=(env if warm else None),
                router_kwargs=dict(policy="rr", heartbeat_seconds=0.2,
                                   heartbeat_misses=2,
                                   restart_backoff=0.05,
                                   max_restarts=6))
            try:
                victim = router.replicas[0]
                fi.inject(f"fleet.apply.r{victim.idx}", times=1)
                reqs = [router.submit([i + 1, i + 2, i + 3], 24)
                        for i in range(4)]
                deadline = time.monotonic() + 120
                while victim.proc.poll() is None \
                        and time.monotonic() < deadline:
                    time.sleep(0.002)
                assert victim.proc.poll() is not None, \
                    "armed SIGKILL never fired (streams too short?)"
                t_kill = time.monotonic()
                for r in reqs:
                    assert r["done"].wait(300), "stream stalled"
                    assert r["error"] is None, r["error"]
                recovery = time.monotonic() - t_kill
                while router.stats()["live"] < 2 \
                        and time.monotonic() - t_kill < 300:
                    time.sleep(0.05)
                assert router.stats()["live"] == 2, "no resurrection"
                # "rejoined" means USEFUL: the reborn replica serves
                # tokens (a cold one pays its compiles right here)
                cli = ReplicaClient(victim.host, victim.port,
                                    timeout=300)
                toks = cli.generate([9, 9], 4, timeout=300)
                cli.close()
                assert len(toks) == 4
                tts = time.monotonic() - t_kill
                cache_stats = victim.call(
                    {"op": "cache_stats"})["cache"]
                return recovery, tts, cache_stats, router.stats()
            finally:
                fi.clear()
                router.shutdown(drain=False, timeout=60)

        w_rec, w_tts, w_cache, w_stats = run(warm=True)
        c_rec, c_tts, _c_cache, _ = run(warm=False)
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    assert w_cache["misses"] == 0, w_cache  # warm rejoin: 0 compiles
    speedup = c_tts / max(w_tts, 1e-9)
    assert speedup >= 1.0, (c_tts, w_tts)
    _emit("fleet_failover_recovery_seconds", w_rec, "s", speedup, {
        "warm_recovery_s": round(w_rec, 3),
        "cold_recovery_s": round(c_rec, 3),
        "warm_time_to_serving_s": round(w_tts, 2),
        "cold_time_to_serving_s": round(c_tts, 2),
        "resurrection_speedup": round(speedup, 2),
        "warm_cache": w_cache,
        "failovers": w_stats["failovers"],
        "bar": "every accepted stream survives a replica SIGKILL; "
               "warm resurrection rejoins with 0 fresh XLA compiles "
               "and >= 1x cold time-to-serving"})


def bench_analysis_selfcheck():
    """analysis_selfcheck: the analysis plane's seeded-bug smoke
    (python -m paddle_tpu.analysis --self-check in-process): one bug
    per analyzer — a lint violation, a host-sync'd fused chain, a
    seeded graph break per PTC rule (the static capture planner), a
    wrong ops.yaml shape spec, a synthetic crash that must leave a
    flight dump with its seeded event, a lock-order inversion — each
    must be detected before anyone trusts a clean report, a capture
    plan or the black box. Bar: all six detector families fire."""
    import time as _t
    from paddle_tpu.analysis.report import self_check
    t0 = _t.perf_counter()
    out = self_check()
    dt = (_t.perf_counter() - t0) * 1e3
    # the PTC detectors are load-bearing for capture planning: require
    # them EXPLICITLY, not just via the aggregate ok
    ptc_fired = bool(out["checks"].get("capture")) and \
        bool(out["checks"].get("shapes"))
    flight_fired = bool(out["checks"].get("flight"))
    ok = out["ok"] and ptc_fired and flight_fired
    _emit("analysis_selfcheck", 1.0 if ok else 0.0, "pass",
          1.0 if ok else 0.0, {
              "checks": {k: ("ok" if v else "FAIL")
                         for k, v in out["checks"].items()},
              "wall_ms": round(dt, 1),
              "detail": out.get("detail", ""),
              "bar": "lint + audit + capture(PTC) + shapes + flight "
                     "+ locks detectors all fire on seeded bugs"})


def bench_checkpoint_roundtrip():
    """checkpoint_roundtrip: durable (sync) vs async save wall time +
    verified restore time for a small model state_dict through
    CheckpointManager (framework/checkpoint.py). The async number is
    the SUBMISSION cost — snapshot-to-host only, serialization/fsync/
    rename on the background thread — which is what a training step
    actually pays (on this bench host the snapshot is a host memcpy, so
    it dominates submission; on TPU the DMA overlaps). Bar: async
    submission <= 2/3 the sync persist."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.framework.checkpoint import CheckpointManager

    rng = np.random.default_rng(0)
    state = {f"layers.{i}.weight": paddle.to_tensor(
        rng.standard_normal((256, 256)).astype(np.float32))
        for i in range(8)}                      # ~2 MB state_dict
    reps = 5
    roots = [tempfile.mkdtemp(prefix="ckpt_bench_") for _ in range(2)]
    try:
        # best-of per phase: the shared CI hosts are noisy and a mean
        # over a handful of 10-ms saves swings 2x between runs
        m = CheckpointManager(roots[0], keep_n=2)
        m.save(state, step=0)                   # warm (mkdir, caches)
        sync_ms = float("inf")
        for r in range(reps):
            t0 = time.perf_counter()
            m.save(state, step=r + 1)
            sync_ms = min(sync_ms, (time.perf_counter() - t0) * 1e3)

        ma = CheckpointManager(roots[1], keep_n=2, async_save=True)
        ma.save(state, step=0)
        ma.wait()
        submit_ms = float("inf")
        t_all = time.perf_counter()
        for r in range(reps):
            t0 = time.perf_counter()
            ma.save(state, step=r + 1)          # barriers on previous
            submit_ms = min(submit_ms,
                            (time.perf_counter() - t0) * 1e3)
        ma.wait()
        async_total_ms = (time.perf_counter() - t_all) / reps * 1e3

        t0 = time.perf_counter()
        step, restored = m.restore()            # verifies CRC manifest
        restore_ms = (time.perf_counter() - t0) * 1e3
        assert step == reps and len(restored) == len(state)
        nbytes = m.stats()["bytes_written"] // (reps + 1)
    finally:
        for r in roots:
            shutil.rmtree(r, ignore_errors=True)
    speedup = sync_ms / max(submit_ms, 1e-9)
    _emit("checkpoint_roundtrip", sync_ms + restore_ms, "ms",
          speedup / 1.5, {
              "sync_save_ms": round(sync_ms, 2),
              "async_submit_ms": round(submit_ms, 2),
              "async_total_ms": round(async_total_ms, 2),
              "restore_verified_ms": round(restore_ms, 2),
              "async_submit_speedup": round(speedup, 1),
              "checkpoint_bytes": int(nbytes),
              "bar": "async submission <= 2/3 sync persist"})


def _probe_backend(apply_in_process):
    """Probe backend initialization in a throwaway subprocess with a
    capped wait. BENCH_r05 died rc=124: the requested backend (axon)
    hung during init and the driver timeout killed the WHOLE run with an
    empty artifact. A hung/broken backend degrades to CPU lines instead.
    Runs before this process ever imports jax. With
    ``apply_in_process=False`` (the suite parent, which never imports
    jax itself) the fallback is recorded in os.environ only, for the
    per-metric worker subprocesses to inherit."""
    import subprocess
    import sys
    wait = float(os.environ.get("PADDLE_TPU_BENCH_INIT_TIMEOUT", "120"))
    probe = "import jax; jax.devices()"
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True, timeout=wait)
        if r.returncode == 0:
            return True
        err = f"rc={r.returncode}: " + (r.stderr or "")[-240:]
    except subprocess.TimeoutExpired:
        err = f"backend init exceeded the {wait:.0f}s cap"
    except Exception as e:  # noqa: BLE001
        err = f"{type(e).__name__}: {e}"[:300]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PADDLE_TPU_BENCH_FORCE_CPU"] = "1"
    if apply_in_process:
        _force_cpu_in_process()
    _emit("backend_init_fallback", None, "error", 0.0, {
        "error": err,
        "action": "forcing JAX_PLATFORMS=cpu; workloads emit CPU lines",
        "init_wait_cap_s": wait})
    return False


def _force_cpu_in_process():
    try:
        import jax
        # the image's plugin force-prepends the TPU platform regardless
        # of JAX_PLATFORMS; override before any backend resolves
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass


def _ensure_backend_or_cpu():
    return _probe_backend(apply_in_process=True)


# The full suite, in emission order. Micro benches first: they need a
# quiet process for µs fidelity (and with per-metric workers, a fresh
# one). Each row: (error-line label, bench fn name).
_SUITE = [
    ("eager_dispatch_overhead_us", "bench_dispatch_overhead"),
    ("metrics_overhead", "bench_metrics_overhead"),
    ("flight_recorder_overhead", "bench_flight_overhead"),
    ("eager_fusion_speedup", "bench_eager_fusion"),
    ("reduction_fusion_speedup", "bench_reduction_fusion"),
    ("fused_optimizer_step_us", "bench_fused_optimizer_step"),
    ("whole_step_capture_speedup", "bench_whole_step_capture"),
    ("amp_captured_step_us", "bench_amp_captured_step"),
    ("dist_overlap_dryrun", "bench_dist_overlap_dryrun"),
    ("hot_start_time_to_first_step", "bench_hot_start"),
    ("fleet_failover_recovery_seconds", "bench_fleet_failover"),
    ("analysis_selfcheck", "bench_analysis_selfcheck"),
    ("bench_llama", "bench_llama"),
    ("bench_llama7b_geometry", "bench_llama7b_geometry"),
    ("bench_resnet50", "bench_resnet50"),
    ("bench_bert_base", "bench_bert_base"),
    ("bench_gpt13b_geometry", "bench_gpt13b_geometry"),
    ("bench_moe_dispatch", "bench_moe_dispatch"),
    ("bench_llama_decode", "bench_llama_decode"),
    ("llama_decode_paged_tokens_per_sec", "bench_llama_decode_paged"),
    ("prefix_sharing_kv", "bench_prefix_sharing_kv"),
    ("llama_decode_speculative_tokens_per_sec",
     "bench_llama_decode_speculative"),
    ("paged_attention_paths", "bench_paged_attention_paths"),
    ("bench_checkpoint_roundtrip", "bench_checkpoint_roundtrip"),
]


def _run_one(fn_name):
    """Worker mode (``--one <fn>``): run a single metric in this
    process. Handled failures emit an error line and still exit 0 —
    only a hard crash (segfault, OOM kill) surfaces as rc != 0, which
    the parent converts into the error line."""
    label = next((lbl for lbl, fn in _SUITE if fn == fn_name), fn_name)
    if os.environ.get("PADDLE_TPU_BENCH_FORCE_CPU"):
        _force_cpu_in_process()
    elif not os.environ.get("PADDLE_TPU_BENCH_NO_PROBE"):
        _ensure_backend_or_cpu()
    try:
        globals()[fn_name]()
    except Exception as e:  # noqa: BLE001 — record, exit clean
        _emit(label, None, "error", 0.0,
              {"error": f"{type(e).__name__}: {e}"[:300]})


def _run_suite():
    """Suite mode: each metric runs in its OWN capped subprocess, so a
    hung backend/workload yields an error line for that metric and the
    suite still exits 0 — never an rc=124 kill with a truncated
    artifact (BENCH_r05). The parent stays jax-free; workers inherit
    the probe verdict through the environment. An overall budget
    (PADDLE_TPU_BENCH_BUDGET seconds, 0 disables) skips remaining
    metrics with explicit lines once exhausted."""
    import subprocess
    import sys
    _reset_artifact()
    force_cpu = not _probe_backend(apply_in_process=False)
    per_cap = float(os.environ.get(
        "PADDLE_TPU_BENCH_METRIC_TIMEOUT", "420"))
    budget = float(os.environ.get("PADDLE_TPU_BENCH_BUDGET", "1740"))
    deadline = (time.time() + budget) if budget > 0 else None
    env = dict(os.environ, PADDLE_TPU_BENCH_NO_PROBE="1")
    if force_cpu:
        env["PADDLE_TPU_BENCH_FORCE_CPU"] = "1"
    me = os.path.abspath(__file__)
    for label, fn_name in _SUITE:
        cap = per_cap
        if deadline is not None:
            remaining = deadline - time.time()
            if remaining <= 10.0:
                _emit(label, None, "error", 0.0, {
                    "error": "suite budget exhausted; metric skipped",
                    "budget_s": budget})
                continue
            cap = min(cap, remaining)
        try:
            # stdout inherited: the worker's metric lines stream to the
            # driver and append to the shared artifact as they land
            r = subprocess.run([sys.executable, me, "--one", fn_name],
                               env=env, timeout=cap)
            if r.returncode != 0:
                _emit(label, None, "error", 0.0, {
                    "error": f"worker crashed rc={r.returncode}"})
        except subprocess.TimeoutExpired:
            _emit(label, None, "error", 0.0, {
                "error": f"metric exceeded its {cap:.0f}s cap; worker "
                         f"killed, suite continues"})
        except Exception as e:  # noqa: BLE001
            _emit(label, None, "error", 0.0,
                  {"error": f"{type(e).__name__}: {e}"[:300]})


def main(argv=None):
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if "--dist-overlap-worker" in argv:
        # bench_dist_overlap_dryrun's subprocess body: 8 virtual CPU
        # devices were forced through the env before this import chain
        _force_cpu_in_process()
        print(json.dumps(_dist_overlap_impl()), flush=True)
        return
    if "--hot-start-worker" in argv:
        # bench_hot_start's subprocess body: one boot against the
        # shared executable cache dir (cold exports, warm pre-warms)
        _force_cpu_in_process()
        print(json.dumps(_hot_start_impl()), flush=True)
        return
    if "--one" in argv:
        _run_one(argv[argv.index("--one") + 1])
        return
    if "--headline-only" in argv:
        _ensure_backend_or_cpu()
        bench_llama()
        return
    if "--dispatch-only" in argv:
        # quick-iteration smoke path: just the dispatch/fusion/optimizer
        # microbenches, in-process (seconds, not minutes)
        _ensure_backend_or_cpu()
        for fn in (bench_dispatch_overhead, bench_metrics_overhead,
                   bench_flight_overhead,
                   bench_eager_fusion, bench_reduction_fusion,
                   bench_fused_optimizer_step,
                   bench_whole_step_capture, bench_amp_captured_step,
                   bench_hot_start, bench_analysis_selfcheck):
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                _emit(fn.__name__, None, "error", 0.0,
                      {"error": f"{type(e).__name__}: {e}"[:300]})
        return
    # default (the driver run) = the FULL suite, one JSON line per
    # BASELINE workload, each metric in its own capped subprocess
    _run_suite()


if __name__ == "__main__":
    main()
