"""Headline benchmark: Llama causal-LM training tokens/sec/chip.

Runs a ~1.17B-param Llama (Llama-2 geometry scaled to one v5e chip's HBM)
in bf16 with bf16 AdamW state through the compiled whole-train-step path
(paddle_tpu.distributed.dist_train.DistTrainStep: fwd + bwd + optimizer in
one XLA executable, attention on the Pallas flash kernel).

MFU uses the standard 6*N_params FLOPs/token estimate, which EXCLUDES
attention score FLOPs (~12*L*h*s extra per token) — the reported MFU is
therefore conservative by a few percent at seq 2048.

vs_baseline: the reference publishes no numbers (BASELINE.md); the agreed
bar is "A100+NCCL MFU" for Llama-class training, for which well-tuned
public implementations sit at ~0.45 MFU. vs_baseline = our_MFU / 0.45,
with peak = 197 TFLOP/s bf16 for TPU v5e (394 for v5p would be detected).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np


# chip bf16 peak FLOP/s by device_kind substring
_PEAKS = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v6", 918e12), ("v3", 123e12), ("v2", 46e12),
]
_BASELINE_MFU = 0.45  # well-tuned A100 Llama pretraining MFU


def _peak_flops():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 197e12


def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        # ~1.2B-param Llama geometry chosen to saturate one v5e chip's HBM
        # (AdamW fp32 state + bf16 params/grads + flash-attention
        # activations); wide layers keep the MXU fed
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=3584, intermediate_size=9728,
            num_hidden_layers=6, num_attention_heads=28,
            num_key_value_heads=28, max_position_embeddings=2048)
        batch, seq, steps = 4, 2048, 10
    else:  # CI smoke path
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 2, 32, 2

    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    # multi_precision=False stores Adam moments in the param dtype (bf16),
    # the reference's own default for AdamW — halves optimizer-state HBM
    # traffic (+14% step time on v5e). bf16 keeps fp32's exponent range,
    # so the moments lose mantissa only, not range.
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)
    crit = LlamaPretrainingCriterion()
    step = DistTrainStep(model, lambda lg, lb: crit(lg, lb), opt)

    rng = np.random.default_rng(0)
    import jax.numpy as jnp
    # device-resident feed: per-step host->device uploads would serialize
    # on the tunnel RTT and measure the link, not the chip
    ids = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    with jax.default_matmul_precision("bfloat16"):
        # compile + warmup with a full host sync (float(loss): a value
        # transfer is the only trustworthy barrier over the tunnel)
        float(step(ids, ids))
        float(step(ids, ids))
        # timed region: steps chain on-device (donated buffers); ONE final
        # loss fetch closes the timing — per-step fetches would add a
        # ~100 ms tunnel round-trip to every step
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, ids)
        loss = float(loss)
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    flops_per_token = 6 * n_params  # standard fwd+bwd estimate
    mfu = tokens_per_sec * flops_per_token / _peak_flops()
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / _BASELINE_MFU, 4),
        "detail": {
            "params": n_params, "batch": batch, "seq": seq,
            "mfu": round(mfu, 4), "loss": loss,
            "backend": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    main()
