"""Per-op/kernel perf regression gate (VERDICT r3 item 8).

The reference runs an op-benchmark CI that times kernels and diffs the
results against the develop branch, failing on regressions
(ref: tools/ci_op_benchmark.sh + tools/check_op_benchmark_result.py).
This is the TPU-native equivalent: time the ~25 hot ops/kernels the e2e
benches ride on, write ``BENCH_OPS_r{N}.json``, and diff against the
most recent previous round's file for the same backend — a >10%
slowdown on any op exits non-zero and names the op, so a Pallas tile
change can't hide inside e2e noise.

Usage:
    python bench_ops.py              # time, write, gate vs previous
    python bench_ops.py --no-gate    # time + write only
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

import numpy as np

REPEATS = 5          # median-of to de-noise the tunnel
TOLERANCE = 0.10     # >10% slower than previous round fails


def _round_number() -> int:
    """Current round = 1 + highest BENCH_r*.json the driver recorded."""
    rounds = [int(m.group(1)) for f in glob.glob("BENCH_r*.json")
              for m in [re.match(r"BENCH_r(\d+)\.json$",
                                 os.path.basename(f))] if m]
    return (max(rounds) + 1) if rounds else 1


def _previous_file(backend: str):
    """Latest BENCH_OPS_r*.json from an earlier round, same backend."""
    best = None
    for f in glob.glob("BENCH_OPS_r*.json"):
        m = re.match(r"BENCH_OPS_r(\d+)\.json$", os.path.basename(f))
        if not m or int(m.group(1)) >= _round_number():
            continue
        try:
            data = json.load(open(f))
        except Exception:
            continue
        if data.get("backend") != backend:
            continue
        if best is None or int(m.group(1)) > best[0]:
            best = (int(m.group(1)), data)
    return best


def _sync(out):
    """Trustworthy device barrier: fetch ONE element of the result.
    block_until_ready is not a barrier over the axon test tunnel; a
    value transfer is (same methodology as bench.py)."""
    import jax
    leaf = jax.tree.leaves(out)[0]
    return float(leaf.reshape(-1)[0])


def _time_one(fn, args, n: int):
    import jax.numpy as jnp
    out = fn(*args)
    _sync(out)
    # the closing fetch costs one host round-trip; measure it on a
    # fresh trivial value and subtract (a cached buffer would hit the
    # host-side npy cache and under-report)
    rtt = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        float(jnp.zeros(()) + i)
        rtt = min(rtt, time.perf_counter() - t0)
    # median of repeats, discarding windows the tunnel glitched below
    # the measured rtt — a min-of-mins once recorded a physically
    # impossible 0.0 ms for a 256MB reduction and poisoned the gate
    samples = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        _sync(out)
        dt = (time.perf_counter() - t0 - rtt) / n
        if dt > 0:
            samples.append(dt)
    if not samples:
        return 0.0
    samples.sort()
    return samples[len(samples) // 2] * 1e3  # ms


def build_specs(on_tpu: bool):
    """(name, n_iters, make() -> (jitted fn, args)) for each hot op.
    Shapes shrink on CPU so the gate logic itself is testable there."""
    import jax
    import jax.numpy as jnp

    S = 1.0 if on_tpu else 0.0  # scale selector
    rng = np.random.default_rng(0)

    def r(*shape, dtype=jnp.bfloat16):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * 0.05, dtype)

    specs = []

    def add(name, n, make):
        specs.append((name, n if on_tpu else 2, make))

    # -- matmul (the MXU floor everything else is judged against)
    def mk_matmul(train, m):
        a, b = r(m, m), r(m, m)
        if not train:
            return jax.jit(lambda x, y: x @ y), (a, b)

        def step(x, y):
            l, g = jax.value_and_grad(
                lambda yy: ((x @ yy).astype(jnp.float32) ** 2).sum())(y)
            return g
        return jax.jit(step), (a, b)

    m0 = 4096 if on_tpu else 128
    add("matmul_fwd_4k", 30, lambda: mk_matmul(False, m0))
    add("matmul_fwdbwd_4k", 20, lambda: mk_matmul(True, m0))

    # -- flash attention (llama/gpt geometry d=128, bert geometry d=64)
    def mk_flash(train, b, h, s, d, causal=True):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = r(b, h, s, d), r(b, h, s, d), r(b, h, s, d)
        if not train:
            return jax.jit(
                lambda q, k, v: flash_attention(q, k, v, causal=causal)
            ), (q, k, v)

        def step(q, k, v):
            def loss(q, k, v):
                o = flash_attention(q, k, v, causal=causal)
                return (o.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return jax.jit(step), (q, k, v)

    if on_tpu:
        add("flash_fwd_d128_s2048", 80, lambda: mk_flash(
            False, 4, 16, 2048, 128))
        add("flash_fwdbwd_d128_s2048", 10, lambda: mk_flash(
            True, 4, 16, 2048, 128))
        add("flash_fwdbwd_d64_s512_bert", 10, lambda: mk_flash(
            True, 16, 12, 512, 64, causal=False))
    else:
        add("flash_fwd_d128_s2048", 2, lambda: mk_flash(
            False, 1, 2, 128, 64))
        add("flash_fwdbwd_d128_s2048", 2, lambda: mk_flash(
            True, 1, 2, 128, 64))
        add("flash_fwdbwd_d64_s512_bert", 2, lambda: mk_flash(
            True, 1, 2, 128, 64, causal=False))

    # -- segmented (varlen) flash
    def mk_flash_seg():
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_segmented)
        # segmented flash takes [B, L, H, D] + seg [B, L]
        b, s, h, d = (2, 2048, 8, 128) if on_tpu else (1, 128, 2, 64)
        q, k, v = r(b, s, h, d), r(b, s, h, d), r(b, s, h, d)
        seg = jnp.asarray(
            np.repeat(np.arange(4), s // 4)[None, :].repeat(b, 0),
            jnp.int32)
        return jax.jit(lambda q, k, v, seg: flash_attention_segmented(
            q, k, v, seg, causal=True)), (q, k, v, seg)

    add("flash_seg_fwd", 60, mk_flash_seg)

    # -- grouped matmul (MoE expert FFN)
    def mk_gmm(train):
        from paddle_tpu.ops.pallas.grouped_matmul import (
            grouped_matmul, tile_expert_ids)
        e = 16 if on_tpu else 4
        t, k, n = (16384, 1024, 4096) if on_tpu else (256, 32, 64)
        # the tuned configuration (K-tiled kernel, fat token tiles):
        # block_t=512 measured 2x over 128 at this geometry
        block_t = 512 if on_tpu else 64
        lhs = r(t, k)
        rhs = r(e, k, n)
        sizes = jnp.full((e,), t // e, jnp.int32)
        # tile_ids passed explicitly: inside jit group_sizes is a tracer
        # and grouped_matmul would fall back to the dense reference —
        # this spec must time the Pallas kernel, like the MoE layer does
        ids = tile_expert_ids(sizes, block_t, t // block_t)
        if not train:
            return jax.jit(
                lambda l, rh, s, i: grouped_matmul(
                    l, rh, s, block_t=block_t, tile_ids=i)
            ), (lhs, rhs, sizes, ids)

        def step(l, rh, s, i):
            def loss(l, rh):
                o = grouped_matmul(l, rh, s, block_t=block_t, tile_ids=i)
                return (o.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss, argnums=(0, 1))(l, rh)
        return jax.jit(step), (lhs, rhs, sizes, ids)

    add("grouped_matmul_fwd", 20, lambda: mk_gmm(False))
    add("grouped_matmul_fwdbwd", 10, lambda: mk_gmm(True))

    # -- chunked big-vocab cross entropy
    def mk_ce():
        from paddle_tpu.ops.fused_ce import fused_softmax_ce_mean
        # chunked CE takes [B, L, V] + labels [B, L]
        t, v = ((4, 2048), 32000) if on_tpu else ((2, 64), 512)
        logits = r(*t, v, dtype=jnp.float32)
        labels = jnp.asarray(rng.integers(0, v, t), jnp.int32)

        def step(lg, lb):
            def loss(lg):
                return fused_softmax_ce_mean(lg, lb)
            return jax.grad(loss)(lg)
        return jax.jit(step), (logits, labels)

    add("chunked_ce_fwdbwd", 10, mk_ce)

    # -- fused transformer pointwise kernels
    def mk_ln_res_dropout():
        from paddle_tpu.core.tensor import Tensor as _T
        from paddle_tpu.incubate.nn.functional import (
            fused_layernorm_residual_dropout)
        t, h = (8192, 4096) if on_tpu else (128, 64)
        x, res = r(t, h), r(t, h)
        w = jnp.ones((h,), jnp.float32)
        b = jnp.zeros((h,), jnp.float32)

        def f(x, res, w, b):
            out, _ = fused_layernorm_residual_dropout(
                _T(x), _T(res), _T(w), _T(b), p=0.0)
            return out._data
        return jax.jit(f), (x, res, w, b)

    add("fused_ln_residual_dropout", 80, mk_ln_res_dropout)

    def mk_rope():
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding)
        from paddle_tpu.core.tensor import Tensor as _T
        b, s, h, d = (4, 2048, 16, 128) if on_tpu else (1, 64, 2, 16)
        q, k = r(b, s, h, d), r(b, s, h, d)

        def f(q, k):
            oq, ok, _ = fused_rotary_position_embedding(
                _T(q), _T(k), use_neox_rotary_style=True)
            return oq._data, ok._data
        return jax.jit(f), (q, k)

    add("fused_rope", 60, mk_rope)

    def mk_bias_gelu():
        t, h, o = (8192, 4096, 4096) if on_tpu else (64, 32, 32)
        x, w, b = r(t, h), r(h, o), r(o)

        def step(x, w, b):
            def loss(w, b):
                y = jax.nn.gelu((x @ w) + b)
                return (y.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss, argnums=(0, 1))(w, b)
        return jax.jit(step), (x, w, b)

    add("linear_bias_gelu_fwdbwd", 20, mk_bias_gelu)

    # -- conv/bn (ResNet hot block, NHWC)
    def mk_conv_block():
        n, hw, cin, cout = (64, 56, 64, 64) if on_tpu else (2, 8, 4, 4)
        x = r(n, hw, hw, cin)
        w1 = r(3, 3, cin, cout)

        def step(x, w1):
            def loss(w1):
                y = jax.lax.conv_general_dilated(
                    x, w1, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                y = jax.nn.relu(y)
                return (y.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss)(w1)
        return jax.jit(step), (x, w1)

    add("conv3x3_relu_fwdbwd", 80, mk_conv_block)

    def mk_batchnorm():
        from paddle_tpu.nn.functional.norm import batch_norm
        from paddle_tpu.core.tensor import Tensor as _T
        n, hw, ch = (64, 56, 64) if on_tpu else (2, 8, 4)
        x = r(n, hw, hw, ch, dtype=jnp.float32)
        rm = jnp.zeros((ch,), jnp.float32)
        rv = jnp.ones((ch,), jnp.float32)
        w = jnp.ones((ch,), jnp.float32)
        b = jnp.zeros((ch,), jnp.float32)

        def f(x, rm, rv, w, b):
            out = batch_norm(_T(x), _T(rm), _T(rv), _T(w), _T(b),
                             training=True, data_format="NHWC")
            return out._data
        return jax.jit(f), (x, rm, rv, w, b)

    add("batch_norm_train_nhwc", 80, mk_batchnorm)

    # -- big-vocab embedding gradient (MXU dgrad path)
    def mk_embedding_grad():
        from paddle_tpu.nn.functional.common import _embedding_lookup
        v, h, t = (32000, 4096, 8192) if on_tpu else (512, 32, 128)
        w = r(v, h)
        idx = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)

        def step(idx, w):
            def loss(w):
                e = _embedding_lookup(idx, w)
                return (e.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss)(w)
        return jax.jit(step), (idx, w)

    add("embedding_dgrad_32kvocab", 10, mk_embedding_grad)

    # -- cheap-hash dropout (the BERT-step regression of r2)
    def mk_dropout():
        from paddle_tpu.nn.functional.common import dropout
        from paddle_tpu.core.tensor import Tensor as _T
        t, h = (8192, 4096) if on_tpu else (128, 64)
        x = r(t, h)
        key = jax.random.key(0)

        def f(x, key):
            from paddle_tpu.core import random as random_mod
            with random_mod.key_stream(key):
                return dropout(_T(x), p=0.1, training=True)._data
        return jax.jit(f), (x, key)

    add("dropout_cheaphash", 100, mk_dropout)

    # -- reductions / softmax (XLA fusion sanity)
    def mk_softmax():
        b, s = (64, 4096) if on_tpu else (8, 128)
        x = r(b, 16, s, dtype=jnp.float32)
        return jax.jit(lambda x: jax.nn.softmax(x, axis=-1)), (x,)

    add("softmax_fp32", 100, mk_softmax)

    def mk_allreduce_sum():
        n = (64 * 1024 * 1024) if on_tpu else 65536
        x = r(n // 1024, 1024, dtype=jnp.float32)
        return jax.jit(lambda x: x.sum()), (x,)

    add("reduce_sum_64M", 100, mk_allreduce_sum)

    return specs


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    import jax
    on_tpu = jax.default_backend() in ("tpu", "axon")
    backend = "tpu" if on_tpu else jax.default_backend()
    results = {}
    for name, n, make in build_specs(on_tpu):
        try:
            fn, args = make()
            results[name] = round(_time_one(fn, args, n), 4)
            print(f"  {name}: {results[name]:.3f} ms", flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep timing
            results[name] = None
            print(f"  {name}: ERROR {type(e).__name__}: {e}"[:200],
                  flush=True)
    rnd = _round_number()
    out = {"backend": backend, "round": rnd, "tolerance": TOLERANCE,
           "unit": "ms", "ops": results}
    path = f"BENCH_OPS_r{rnd:02d}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")

    if "--no-gate" in argv:
        return 0
    prev = _previous_file(backend)
    if prev is None:
        print("no previous round to diff against — gate passes trivially")
        return 0
    prev_round, prev_data = prev
    regressions, improved = [], []
    for name, ms in results.items():
        was = prev_data.get("ops", {}).get(name)
        if ms is None or was is None or was < 0.02:
            continue  # absent or below timer resolution: can't gate
        delta = (ms - was) / was
        if delta > TOLERANCE:
            regressions.append((name, was, ms, delta))
        elif delta < -TOLERANCE:
            improved.append((name, was, ms, delta))
    for name, was, ms, delta in improved:
        print(f"IMPROVED {name}: {was:.3f} -> {ms:.3f} ms "
              f"({delta * 100:+.1f}%)")
    if regressions:
        for name, was, ms, delta in regressions:
            print(f"REGRESSION {name}: {was:.3f} -> {ms:.3f} ms "
                  f"({delta * 100:+.1f}%) vs r{prev_round:02d}")
        print(f"FAIL: {len(regressions)} op(s) regressed more than "
              f"{TOLERANCE * 100:.0f}%")
        return 1
    print(f"gate OK vs r{prev_round:02d} "
          f"({len(results)} ops, tol {TOLERANCE * 100:.0f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
