"""LM zoo tests: Llama/GPT/BERT forward+backward, sharded train step.

Mirrors the reference's hybrid_strategy llama tests
(ref: test/auto_parallel/hybrid_strategy/semi_auto_llama.py — loss must
decrease and match across parallelism configs).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.dist_train import DistTrainStep
from paddle_tpu.models import (
    BertConfig, BertForMaskedLM, GPTConfig, GPTForCausalLM, LlamaConfig,
    LlamaForCausalLM, LlamaPretrainingCriterion, shard_llama,
)


@pytest.fixture
def ids(rng):
    return paddle.to_tensor(
        rng.integers(0, 128, (2, 16)).astype(np.int32))


class TestLlama:
    def test_forward_backward(self, ids):
        m = LlamaForCausalLM(LlamaConfig.tiny())
        crit = LlamaPretrainingCriterion()
        logits = m(ids)
        assert logits.shape == [2, 16, 128]
        loss = crit(logits, ids)
        loss.backward()
        g = m.llama.layers[0].self_attn.q_proj.weight.grad
        assert g is not None and float(abs(g).sum()) > 0
        # every trainable param gets a grad
        for name, p in m.named_parameters():
            assert p.grad is not None, name

    def test_gqa_matches_mha_shape(self, ids):
        m = LlamaForCausalLM(LlamaConfig.tiny(num_key_value_heads=1))
        assert m(ids).shape == [2, 16, 128]

    def test_recompute_grads_flow(self, ids):
        m = LlamaForCausalLM(LlamaConfig.tiny(recompute=True))
        crit = LlamaPretrainingCriterion()
        crit(m(ids), ids).backward()
        g = m.llama.layers[0].self_attn.q_proj.weight.grad
        assert g is not None and float(abs(g).sum()) > 0

    def test_tied_embeddings(self, ids):
        m = LlamaForCausalLM(LlamaConfig.tiny(tie_word_embeddings=True))
        logits = m(ids)
        assert logits.shape == [2, 16, 128]
        crit = LlamaPretrainingCriterion()
        crit(logits, ids).backward()
        assert m.llama.embed_tokens.weight.grad is not None

    def test_attention_mask_respected(self, rng):
        """An additive mask must change the logits even on the default
        (flash-enabled) config."""
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(rng.integers(0, 128, (1, 8)).astype(np.int32))
        base = m(ids).numpy()
        mask = np.zeros((1, 1, 8, 8), np.float32)
        mask[..., -1] = -1e9  # hide the last key position
        masked = m(ids, attention_mask=paddle.to_tensor(mask)).numpy()
        assert np.abs(base - masked).max() > 1e-6

    def test_generate_kv_cache_consistency(self, rng):
        """Greedy decode with caches == rerunning full forward each step."""
        m = LlamaForCausalLM(LlamaConfig.tiny())
        m.eval()
        ids = paddle.to_tensor(rng.integers(0, 128, (1, 8)).astype(np.int32))
        out = m.generate(ids, max_new_tokens=4)
        assert out.shape == [1, 12]
        # no-cache re-check: argmax of full forward at each position
        cur = ids
        for _ in range(4):
            logits = m(cur)
            nxt = int(np.argmax(logits.numpy()[0, -1]))
            cur = paddle.to_tensor(
                np.concatenate([cur.numpy(), [[nxt]]], axis=1).astype(np.int32))
        np.testing.assert_array_equal(out.numpy(), cur.numpy())

    def test_loss_decreases_train_step(self, ids):
        m = LlamaForCausalLM(LlamaConfig.tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        crit = LlamaPretrainingCriterion()
        step = DistTrainStep(m, lambda lg, lb: crit(lg, lb), opt)
        losses = [float(step(ids, ids)) for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_sharded_train_matches_single(self, rng):
        """dp x fsdp x mp sharded step computes the same losses as the
        unsharded step (the reference's acc-align gate,
        ref: test/auto_parallel/hybrid_strategy/)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        ids_np = rng.integers(0, 64, (4, 16)).astype(np.int32)
        cfg_kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, use_flash_attention=False)

        def run(shard):
            paddle.seed(0)
            m = LlamaForCausalLM(LlamaConfig.tiny(**cfg_kw))
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            crit = LlamaPretrainingCriterion()
            data_sharding = None
            if shard:
                mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                                   dim_names=["dp", "fsdp", "mp"])
                shard_llama(m, mesh, tp_axis="mp", fsdp_axis="fsdp")
                data_sharding = NamedSharding(mesh.to_jax_mesh(),
                                              P("dp", None))
            step = DistTrainStep(m, lambda lg, lb: crit(lg, lb), opt,
                                 data_sharding=data_sharding)
            return [float(step(ids_np, ids_np)) for _ in range(3)]

        single = run(False)
        sharded = run(True)
        np.testing.assert_allclose(single, sharded, rtol=2e-4)


class TestGPT:
    def test_forward_backward(self, ids):
        m = GPTForCausalLM(GPTConfig.tiny())
        logits = m(ids)
        assert logits.shape == [2, 16, 128]
        crit = LlamaPretrainingCriterion()
        loss = crit(logits, ids)
        loss.backward()
        assert m.blocks[0].attn.qkv_proj.weight.grad is not None


class TestBert:
    def test_mlm_forward(self, ids):
        m = BertForMaskedLM(BertConfig.tiny())
        logits = m(ids)
        assert logits.shape == [2, 16, 128]
