"""Inference path tests (ref: the reference's inference API tests drive
AnalysisPredictor over a saved model)."""
import numpy as np

import pytest
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import (Config, Predictor, create_predictor,
                                  load_inference_model,
                                  save_inference_model)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def test_save_load_roundtrip(tmp_path, rng):
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    path = str(tmp_path / "llama")
    save_inference_model(path, m)
    m2 = load_inference_model(path)
    ids = paddle.to_tensor(rng.integers(0, 128, (1, 8)).astype(np.int32))
    np.testing.assert_allclose(m(ids).numpy(), m2(ids).numpy(), atol=1e-6)


def test_predictor_matches_eager(tmp_path, rng):
    paddle.seed(1)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    path = str(tmp_path / "llama")
    save_inference_model(path, m)

    cfg = Config(path)
    pred = create_predictor(cfg)
    ids = rng.integers(0, 128, (2, 8)).astype(np.int32)
    out = pred.run(ids)
    eager = m(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(out[0], eager, atol=1e-5)
    # second call reuses the compiled executable (same shapes)
    out2 = pred.run(ids)
    np.testing.assert_allclose(out2[0], out[0])


def test_load_mismatched_model_raises(tmp_path, rng):
    """A reconstruction whose params don't match the checkpoint must raise
    instead of serving random weights."""
    import pytest
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    path = str(tmp_path / "m")
    save_inference_model(path, m)
    # corrupt the stored config so the rebuilt model has different shapes
    from paddle_tpu.framework.io import load as fload, save as fsave
    payload = fload(path + ".pdmodel", return_numpy=False)
    payload["init_config"] = LlamaConfig.tiny(hidden_size=32)
    fsave(payload, path + ".pdmodel")
    with pytest.raises(Exception):
        load_inference_model(path)


def test_jit_save_load_shares_format(tmp_path, rng):
    paddle.seed(2)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    path = str(tmp_path / "jit_model")
    paddle.jit.save(m, path)
    m2 = paddle.jit.load(path)
    ids = paddle.to_tensor(rng.integers(0, 128, (1, 8)).astype(np.int32))
    np.testing.assert_allclose(m(ids).numpy(), m2(ids).numpy(), atol=1e-6)


def test_input_names_from_signature(rng):
    import paddle_tpu.nn as nn
    m = LlamaForCausalLM(LlamaConfig.tiny())
    assert Predictor(m).get_input_names() == ["input_ids"]


def test_predictor_from_live_model(rng):
    import paddle_tpu.nn as nn
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    pred = Predictor(m)
    x = rng.normal(size=(3, 4)).astype(np.float32)
    out = pred.run(x)
    np.testing.assert_allclose(out[0], m(paddle.to_tensor(x)).numpy(),
                               atol=1e-6)
    # building a predictor must not flip a live model into eval mode
    assert m.training


def test_save_unreconstructable_model_raises_at_save(tmp_path):
    """Models whose __init__ takes args (positional, *args, or required
    keyword-only) without a .config must be refused at SAVE time, not in
    the serving process."""
    import pytest
    import paddle_tpu.nn as nn
    for bad in (nn.Linear(4, 2),
                nn.Sequential(nn.Linear(4, 2))):  # *layers VAR_POSITIONAL
        with pytest.raises(ValueError, match="config"):
            save_inference_model(str(tmp_path / "bad"), bad)


def test_predictor_preserves_mixed_sublayer_modes(rng):
    """Frozen-BN style mixed modes survive a Predictor trace."""
    import paddle_tpu.nn as nn
    m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    m.train()
    m[1].training = False  # deliberately frozen sublayer
    pred = Predictor(m)
    pred.run(rng.normal(size=(2, 4)).astype(np.float32))
    assert m.training and m[0].training and not m[1].training


def test_bf16_dtype_preserved_through_load(tmp_path, rng):
    paddle.seed(4)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.bfloat16()
    path = str(tmp_path / "bf16model")
    save_inference_model(path, m)
    m2 = load_inference_model(path)
    assert str(m2.lm_head.weight.dtype) == "bfloat16"


class TestAOTServing:
    """VERDICT round-1 missing item 10: AOT-serialized executables +
    warm start without the model factory + predictor server loop."""

    def _artifact(self, tmp_path, corrupt_factory=False):
        import numpy as np
        from paddle_tpu.inference import save_inference_model
        from paddle_tpu.jit.api import InputSpec

        paddle.seed(0)

        class Toy(nn.Layer):
            def __init__(self, config=None):
                super().__init__()
                self.config = config
                self.fc = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                        nn.Linear(16, 4))

            def forward(self, x):
                return self.fc(x)

        m = Toy()
        x = np.random.randn(3, 8).astype(np.float32)
        expect = m(paddle.to_tensor(x)).numpy()
        path = str(tmp_path / "toy")
        save_inference_model(path, m,
                             input_spec=[InputSpec([3, 8], "float32")],
                             aot=True)
        if corrupt_factory:
            from paddle_tpu.framework.io import load as _l, save as _s
            payload = _l(path + ".pdmodel", return_numpy=False)
            payload["module"] = "nonexistent_module_xyz"
            _s(payload, path + ".pdmodel")
        return path, x, expect

    def test_aot_serves_without_factory(self, tmp_path):
        import numpy as np
        from paddle_tpu.inference import Config, Predictor
        path, x, expect = self._artifact(tmp_path, corrupt_factory=True)
        p = Predictor(Config(path))
        assert p._aot is not None
        np.testing.assert_allclose(p.run(x)[0], expect, rtol=1e-5,
                                   atol=1e-6)

    def test_server_roundtrip(self, tmp_path):
        import io
        import http.client
        import socket
        import numpy as np
        from paddle_tpu.inference import serve
        path, x, expect = self._artifact(tmp_path)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = serve(path, port=port, block=False)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("GET", "/health")
            assert conn.getresponse().read() == b"ok"
            buf = io.BytesIO()
            np.savez(buf, input_0=x)
            conn.request("POST", "/run", body=buf.getvalue())
            resp = conn.getresponse()
            assert resp.status == 200
            got = np.load(io.BytesIO(resp.read()))["output_0"]
            np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
        finally:
            srv.shutdown()

    def test_aot_requires_input_spec(self, tmp_path):
        from paddle_tpu.inference import save_inference_model

        class NoArg(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 2)

            def forward(self, x):
                return self.fc(x)

        with pytest.raises(ValueError, match="input_spec"):
            save_inference_model(str(tmp_path / "x"), NoArg(), aot=True)


class _BatchToy(nn.Layer):
    """Module-level so the jit-path artifact can re-import the class."""

    def __init__(self, config=None):
        super().__init__()
        self.config = config
        self.fc = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 4))

    def forward(self, x):
        return self.fc(x)


class TestServeMicroBatching:
    """serve() request micro-batching (VERDICT r3 weak item 7; ref: the
    reference predictor's multi-stream batched serving)."""

    def _jit_artifact(self, tmp_path):
        from paddle_tpu.inference import save_inference_model
        paddle.seed(0)
        m = _BatchToy()
        path = str(tmp_path / "toy_jit")
        save_inference_model(path, m)
        return path, m

    def test_concurrent_requests_batch_into_fewer_dispatches(
            self, tmp_path):
        import io
        import http.client
        import socket
        import threading
        import numpy as np
        from paddle_tpu.inference import serve

        path, m = self._jit_artifact(tmp_path)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv = serve(path, port=port, block=False, max_batch=16,
                    batch_window_ms=100.0)
        rng = np.random.default_rng(0)
        xs = [rng.standard_normal((2, 8)).astype(np.float32)
              for _ in range(8)]
        results = [None] * 8
        errors = []

        def post(i):
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                buf = io.BytesIO()
                np.savez(buf, input_0=xs[i])
                conn.request("POST", "/run", body=buf.getvalue())
                resp = conn.getresponse()
                assert resp.status == 200, resp.read()
                results[i] = np.load(io.BytesIO(resp.read()))["output_0"]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            # warm the compile so the batching window isn't distorted
            post(0)
            threads = [threading.Thread(target=post, args=(i,))
                       for i in range(8)]
            before = srv.batcher.batches_run
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            for i in range(8):
                expect = m(paddle.to_tensor(xs[i])).numpy()
                np.testing.assert_allclose(results[i], expect,
                                           rtol=1e-5, atol=1e-6)
            dispatches = srv.batcher.batches_run - before
            assert dispatches < 8, dispatches  # batched, not 1:1
            assert srv.batcher.requests_served >= 9
        finally:
            srv.shutdown()
