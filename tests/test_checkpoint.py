"""Crash-safe checkpointing: atomicity, CRC manifests, retention,
corruption fallback, async persistence, legacy-format compat
(ISSUE 2 tentpole; ref role: the reference's save/load contract in
python/paddle/framework/io.py hardened for preemptible TPU jobs)."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import (CheckpointCorruptError, CheckpointManager,
                                  atomic_save, load_checkpoint,
                                  verify_checkpoint)
from paddle_tpu.framework.io import _pack
from paddle_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


def _state(seed=0, n=64):
    r = np.random.default_rng(seed)
    return {
        "model": {
            "w": paddle.to_tensor(r.standard_normal((n, 8))
                                  .astype(np.float32)),
            "b16": paddle.to_tensor(
                r.standard_normal(n).astype(np.float32)).astype("bfloat16"),
        },
        "opt": [paddle.to_tensor(np.zeros(n, np.float32)), {"lr": 0.1}],
        "step": int(seed),
    }


def _assert_state_equal(got, seed):
    want = _state(seed)
    np.testing.assert_array_equal(got["model"]["w"].numpy(),
                                  want["model"]["w"].numpy())
    np.testing.assert_array_equal(
        got["model"]["b16"].astype("float32").numpy(),
        want["model"]["b16"].astype("float32").numpy())
    assert got["opt"][1]["lr"] == 0.1
    assert got["step"] == seed


class TestAtomicSaveLoad:
    def test_roundtrip_nested_and_bf16(self, tmp_path):
        p = str(tmp_path / "ck")
        atomic_save(_state(3), p)
        ok, why = verify_checkpoint(p)
        assert ok, why
        _assert_state_equal(load_checkpoint(p), 3)

    def test_save_via_paddle_api_is_versioned(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(1), p)
        with open(p, "rb") as f:
            record = pickle.load(f)
        assert record["__paddle_tpu_ckpt__"] == 2
        assert record["manifest"], "manifest must cover the tensors"
        _assert_state_equal(paddle.load(p), 1)

    def test_legacy_bare_pickle_still_loads(self, tmp_path):
        """Files from the pre-manifest paddle.save (a bare pickle of the
        packed tree) load unchanged — the PR-seed checkpoint corpus must
        survive this refactor."""
        p = str(tmp_path / "legacy.pdparams")
        with open(p, "wb") as f:
            pickle.dump(_pack(_state(5)), f, protocol=4)
        _assert_state_equal(paddle.load(p), 5)
        ok, why = verify_checkpoint(p)
        assert ok, why  # legacy = loadable, just without CRCs

    def test_future_version_refused(self, tmp_path):
        p = str(tmp_path / "future")
        with open(p, "wb") as f:
            pickle.dump({"__paddle_tpu_ckpt__": 99, "manifest": {},
                         "payload": {}}, f)
        with pytest.raises(CheckpointCorruptError, match="version"):
            load_checkpoint(p)

    def test_kill_mid_write_preserves_previous_file(self, tmp_path):
        """A preemption mid-write (truncated temp + kill) leaves the
        previous complete checkpoint at the final path untouched."""
        p = str(tmp_path / "ck")
        atomic_save(_state(1), p)
        fi.inject("checkpoint.write", truncate_at=64, kill=True)
        with pytest.raises(fi.KillPoint):
            atomic_save(_state(2), p)
        # tmp litter exists; the real file is the OLD complete state
        assert any(".tmp." in n for n in os.listdir(tmp_path))
        ok, why = verify_checkpoint(p)
        assert ok, why
        _assert_state_equal(load_checkpoint(p), 1)

    def test_injected_io_error_cleans_tmp(self, tmp_path):
        p = str(tmp_path / "ck")
        fi.inject("checkpoint.write", exc=OSError("ENOSPC"))
        with pytest.raises(OSError, match="ENOSPC"):
            atomic_save(_state(0), p)
        assert os.listdir(tmp_path) == []  # survivable error: tmp removed

    def test_corrupted_tensor_bytes_detected(self, tmp_path):
        """Flip one byte inside a tensor's payload: the pickle still
        decodes, but the CRC manifest refuses to hand the data back."""
        p = str(tmp_path / "ck")
        atomic_save({"w": paddle.to_tensor(
            np.full((32,), 2.0, np.float32))}, p)
        blob = bytearray(open(p, "rb").read())
        idx = blob.rfind(np.float32(2.0).tobytes())
        assert idx > 0
        blob[idx] ^= 0x55
        with open(p, "wb") as f:
            f.write(bytes(blob))
        ok, why = verify_checkpoint(p)
        assert not ok and "crc32" in why
        with pytest.raises(CheckpointCorruptError, match="corrupt"):
            load_checkpoint(p)

    def test_truncated_file_detected(self, tmp_path):
        p = str(tmp_path / "ck")
        atomic_save(_state(0), p)
        blob = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(blob[:len(blob) // 2])
        ok, why = verify_checkpoint(p)
        assert not ok and "unreadable" in why


class TestCheckpointManager:
    def test_retention_keeps_newest_n(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_n=2)
        for s in range(5):
            m.save(_state(s), step=s)
        assert m.steps() == [3, 4]
        assert m.stats()["retired"] == 3

    def test_auto_step_resumes_numbering(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_n=5)
        m.save(_state(0))
        m.save(_state(1))
        m2 = CheckpointManager(str(tmp_path), keep_n=5)  # fresh process
        m2.save(_state(2))
        assert m2.steps() == [0, 1, 2]

    def test_latest_falls_back_past_killed_save(self, tmp_path):
        """THE acceptance scenario: a save killed mid-write leaves
        latest() resolving to the previous good checkpoint."""
        m = CheckpointManager(str(tmp_path), keep_n=3)
        m.save(_state(0), step=0)
        fi.inject("checkpoint.write", truncate_at=100, kill=True)
        with pytest.raises(fi.KillPoint):
            m.save(_state(1), step=1)
        fi.clear()
        assert m.latest_step() == 0
        step, got = m.restore()
        assert step == 0
        _assert_state_equal(got, 0)

    def test_latest_skips_corrupt_newest(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_n=3)
        m.save(_state(0), step=0)
        m.save(_state(1), step=1)
        newest = m.latest()
        blob = bytearray(open(newest, "rb").read())
        blob[-40] ^= 0xFF  # damage tensor bytes near the end
        with open(newest, "wb") as f:
            f.write(bytes(blob))
        assert m.latest_step() == 0
        assert m.stats()["corrupt_skipped"] >= 1
        step, got = m.restore()
        assert step == 0
        _assert_state_equal(got, 0)

    def test_restore_none_when_empty(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        assert m.latest() is None
        assert m.restore() is None

    def test_async_save_persists_and_barriers(self, tmp_path):
        """Async mode: save() returns after the host snapshot; the
        persist completes on the background thread; wait()/close()
        barrier and the result verifies + restores."""
        m = CheckpointManager(str(tmp_path), keep_n=3, async_save=True)
        m.save(_state(0), step=0)
        m.wait()
        assert m.stats()["saves"] == 1
        assert m.stats()["async_saves"] == 1
        step, got = m.restore()
        assert step == 0
        _assert_state_equal(got, 0)
        m.close()

    def test_async_error_surfaces_on_next_save(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_n=3, async_save=True)
        fi.inject("checkpoint.write", exc=OSError("disk gone"))
        m.save(_state(0), step=0)
        with pytest.raises(OSError, match="disk gone"):
            # the barrier at the head of the next save joins the
            # background persist and re-raises its failure instead of
            # silently dropping the checkpoint
            m.save(_state(1), step=1)
        fi.clear()

    def test_async_kill_then_latest_falls_back(self, tmp_path):
        """Preemption during the BACKGROUND persist: the reader-side
        latest() must not raise — it drains and falls back."""
        m = CheckpointManager(str(tmp_path), keep_n=3, async_save=True)
        m.save(_state(0), step=0)
        m.wait()
        fi.inject("checkpoint.write", truncate_at=80, kill=True)
        m.save(_state(1), step=1)
        assert m.latest_step() == 0  # drains quietly, falls back
        fi.clear()
        with pytest.raises(fi.KillPoint):
            m.wait()  # the writer-side barrier still reports the kill

    def test_stats_shape(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        m.save(_state(0))
        s = m.stats()
        for key in ("saves", "async_saves", "bytes_written",
                    "corrupt_skipped", "retired", "async_queue_depth"):
            assert key in s
        assert s["saves"] == 1 and s["bytes_written"] > 0
