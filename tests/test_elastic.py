"""Elastic membership + watchdog collective attribution
(VERDICT round-1 item 9; ref: fleet/elastic/manager.py:125,
phi/core/distributed/comm_task_manager.h:37-57)."""
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import TCPStore
from paddle_tpu.distributed.elastic import ElasticManager


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestElasticManager:
    def test_scale_in_fires_rank_rewrite(self):
        port = _free_port()
        store = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
        try:
            events = []
            m0 = ElasticManager(store, "0", ttl=1.2, interval=0.3,
                                stability_ticks=2,
                                on_membership_change=lambda a, i:
                                events.append((list(a), i)))
            m1 = ElasticManager(store, "1", ttl=1.2, interval=0.3)
            m0.start()
            m1.start()
            time.sleep(1.0)
            assert m0.alive_nodes() == ["0", "1"]
            # node 1 dies (heartbeat stops)
            m1.leave()
            deadline = time.time() + 10
            while (not events or events[-1][0] != ["0"]) and \
                    time.time() < deadline:
                time.sleep(0.2)
            assert events, "membership change never fired"
            alive, idx = events[-1]
            assert alive == ["0"] and idx == 0
            m0.stop()
        finally:
            store.shutdown()

    def test_join_detected(self):
        port = _free_port()
        store = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
        try:
            events = []
            m0 = ElasticManager(store, "a", ttl=1.2, interval=0.3,
                                stability_ticks=2,
                                on_membership_change=lambda a, i:
                                events.append((list(a), i)))
            m0.start()
            time.sleep(0.8)
            m1 = ElasticManager(store, "b", ttl=1.2, interval=0.3)
            m1.start()
            deadline = time.time() + 8
            while not events and time.time() < deadline:
                time.sleep(0.2)
            assert events and events[-1][0] == ["a", "b"]
            assert events[-1][1] == 0
            m1.stop()
            m0.stop()
        finally:
            store.shutdown()


class TestWatchdogSpans:
    def test_timeout_names_the_operation(self):
        wd = dist.install_watchdog(timeout=0.5)
        try:
            release = threading.Event()

            def blocked():
                with wd.span("all_reduce(group=0)"):
                    release.wait(5)

            t = threading.Thread(target=blocked, daemon=True)
            t.start()
            deadline = time.time() + 6
            while not wd.timed_out_spans and time.time() < deadline:
                time.sleep(0.1)
            release.set()
            t.join()
            assert wd.timed_out_spans
            name, age, _ = wd.timed_out_spans[0]
            assert name == "all_reduce(group=0)"
            assert age >= 0.5
        finally:
            dist.uninstall_watchdog()

    def test_collectives_emit_spans(self):
        import paddle_tpu as paddle
        wd = dist.install_watchdog(timeout=60.0)
        try:
            t = paddle.to_tensor(np.ones(3, np.float32))
            dist.all_reduce(t)
            out = []
            dist.all_gather(out, t)
            report = wd.open_span_report()
            assert "all_reduce(group=0)" in report or \
                "all_gather(group=0)" in report, report
        finally:
            dist.uninstall_watchdog()

    def test_report_shows_open_span(self):
        wd = dist.install_watchdog(timeout=60.0)
        try:
            with wd.span("recv(group=3)"):
                assert "recv(group=3)" in wd.open_span_report()
        finally:
            dist.uninstall_watchdog()


class TestLauncherElastic:
    def test_scale_out_and_in_rewrites_world(self, tmp_path):
        """Launcher under --elastic: a peer node joining (simulated via
        direct store heartbeats) restarts workers with the doubled world
        size; the peer vanishing scales back. ref: manager.py watchers +
        rank rewrite."""
        port = _free_port()
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import os, time
            print("WORLD", os.environ["PADDLE_TRAINERS_NUM"],
                  "RANK", os.environ["PADDLE_TRAINER_ID"], flush=True)
            time.sleep(30)
        """))
        env = dict(os.environ, PYTHONPATH="/root/repo",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--log_dir", str(tmp_path / "log"),
             "--master", f"127.0.0.1:{port}",
             "--elastic", "--elastic_ttl", "1.5",
             str(script)],
            env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            # wait for the elastic store to come up, then fake node "1"
            store = TCPStore("127.0.0.1", port + 2, is_master=False,
                             world_size=1, timeout=30.0)
            time.sleep(1.0)
            peer = ElasticManager(store, "1", ttl=1.5, interval=0.4)
            peer.start()

            def wait_log(pred, timeout=30.0):
                # poll with a deadline: fixed sleeps flaked under loaded
                # CI (parallel suites starve the watcher loop)
                path = tmp_path / "log" / "workerlog.0"
                deadline = time.time() + timeout
                log = ""
                while time.time() < deadline:
                    if path.exists():
                        log = path.read_text()
                        if pred(log):
                            return log
                    time.sleep(0.3)
                return log

            log = wait_log(lambda l: "WORLD 2 RANK 0" in l)
            assert "WORLD 2 RANK 0" in log, log
            peer.leave()      # scale-in -> restart with world=1
            log = wait_log(
                lambda l: "WORLD 2" in l and "WORLD 1" in l
                and l.rindex("WORLD 1") > l.index("WORLD 2"))
            assert "WORLD 1 RANK 0" in log, log
            # after scale-in the world returns to 1 (appears again)
            assert log.rindex("WORLD 1") > log.index("WORLD 2"), log
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


class TestReviewRegressions:
    def test_atomic_roster_unique_slots(self):
        port = _free_port()
        store = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
        try:
            ms = [ElasticManager(store, str(i), ttl=2.0, interval=0.3)
                  for i in range(6)]
            threads = [threading.Thread(target=m._register) for m in ms]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            roster = ms[0].roster()
            assert sorted(roster, key=int) == [str(i) for i in range(6)], \
                roster
        finally:
            store.shutdown()

    def test_numeric_sort_past_ten_nodes(self):
        port = _free_port()
        store = TCPStore("127.0.0.1", port, is_master=True, world_size=1)
        try:
            ms = [ElasticManager(store, str(i), ttl=5.0, interval=0.5)
                  for i in (0, 2, 10, 11, 1)]
            for m in ms:
                m._register()
                m._heartbeat_once()
            assert ms[0].alive_nodes() == ["0", "1", "2", "10", "11"]
        finally:
            store.shutdown()

    def test_timed_out_span_stays_visible(self):
        wd = dist.install_watchdog(timeout=0.4)
        try:
            release = threading.Event()

            def blocked():
                with wd.span("recv(group=7)"):
                    release.wait(5)

            t = threading.Thread(target=blocked, daemon=True)
            t.start()
            deadline = time.time() + 5
            while not wd.timed_out_spans and time.time() < deadline:
                time.sleep(0.1)
            # span still OPEN and flagged while the thread hangs
            rep = wd.open_span_report()
            assert "recv(group=7)" in rep and "TIMED OUT" in rep, rep
            release.set()
            t.join()
            rep2 = wd.open_span_report()
            assert "[timed out]" in rep2, rep2
        finally:
            dist.uninstall_watchdog()

    def test_span_group_attribution_positional(self):
        import paddle_tpu as paddle
        wd = dist.install_watchdog(timeout=60.0)
        try:
            g = dist.new_group([0])
            t = paddle.to_tensor(np.ones(2, np.float32))
            dist.all_reduce(t, dist.ReduceOp.SUM, g)  # positional group
            assert f"all_reduce(group={g.id})" in wd.open_span_report()
        finally:
            dist.uninstall_watchdog()


class TestElasticRobustness:
    """ISSUE 2 satellite: flapping debounce, graceful leave, membership
    under a fault-injected (flaky) store."""

    def _store(self):
        port = _free_port()
        return TCPStore("127.0.0.1", port, is_master=True, world_size=1,
                        backoff=0.01, backoff_max=0.05)

    def test_flapping_heartbeat_is_debounced(self):
        """A node blinking in and out of the alive set (slow beat, GC
        pause) must NOT fire the rank-rewrite callback: the watch tick
        requires the changed set to repeat for stability_ticks
        consecutive scans. Driven through _watch_tick directly so no
        sleep tuning is involved."""
        store = self._store()
        try:
            events = []
            m = ElasticManager(store, "0", ttl=5.0, interval=0.1,
                               stability_ticks=3,
                               on_membership_change=lambda a, i:
                               events.append((list(a), i)))
            m._register()
            m._heartbeat_once()
            m._known = ["0", "1"]
            # node 1 flaps: absent for one scan, back, absent, back...
            for flap in (["0"], ["0", "1"], ["0"], ["0", "1"]):
                assert m._watch_tick(alive=flap) is None
            assert events == []
            # a REAL departure (stable for stability_ticks scans) fires
            for _ in range(3):
                m._watch_tick(alive=["0"])
            assert events == [(["0"], 0)]
        finally:
            store.shutdown()

    def test_graceful_leave_immediate(self):
        """leave() deletes the heartbeat key: peers drop the node on the
        very next scan instead of waiting out the TTL."""
        store = self._store()
        try:
            m0 = ElasticManager(store, "0", ttl=30.0, interval=0.2)
            m1 = ElasticManager(store, "1", ttl=30.0, interval=0.2)
            for m in (m0, m1):
                m._register()
                m._heartbeat_once()
            assert m0.alive_nodes() == ["0", "1"]
            m1.leave()
            # no TTL wait: the beat key is gone, exclusion is immediate
            assert m0.alive_nodes() == ["0"]
            # the roster slot survives (a rejoining node keeps its slot)
            assert m1.node_id in m0.roster()
        finally:
            store.shutdown()

    def test_membership_survives_flaky_store(self):
        """Transient store failures during heartbeats/scans are absorbed
        by the store's retry layer + the threads' consecutive-failure
        tolerance; membership still converges."""
        from paddle_tpu.utils import fault_injection as fi
        store = self._store()
        try:
            events = []
            m0 = ElasticManager(store, "0", ttl=2.0, interval=0.2,
                                stability_ticks=2,
                                on_membership_change=lambda a, i:
                                events.append((list(a), i)))
            m0.start()
            # every op type flakes a couple of times while the threads run
            fi.inject("store.add", exc=ConnectionResetError("flake"),
                      times=3)
            fi.inject("store.get_nowait",
                      exc=ConnectionResetError("flake"), times=3)
            m1 = ElasticManager(store, "1", ttl=2.0, interval=0.2)
            m1.start()
            deadline = time.time() + 15
            while (not events or events[-1][0] != ["0", "1"]) and \
                    time.time() < deadline:
                time.sleep(0.2)
            assert events and events[-1][0] == ["0", "1"], events
            assert store.op_retries >= 1  # the flakes really happened
            m1.stop()
            m0.stop()
        finally:
            fi.clear()
            store.shutdown()

    def test_watch_thread_survives_transient_scan_failures(self):
        """A run of scan failures below MAX_CONSECUTIVE_FAILURES must
        not kill the watcher: a later real change still fires."""
        store = self._store()
        try:
            events = []
            m0 = ElasticManager(store, "0", ttl=2.0, interval=0.15,
                                stability_ticks=2,
                                on_membership_change=lambda a, i:
                                events.append((list(a), i)))
            m0.start()
            from paddle_tpu.utils import fault_injection as fi
            # three consecutive scan-side failures (tolerance is 5)
            fi.inject("store.get_nowait",
                      exc=ConnectionResetError("flake"), times=3)
            m1 = ElasticManager(store, "1", ttl=2.0, interval=0.15)
            m1.start()
            deadline = time.time() + 15
            while (not events or events[-1][0] != ["0", "1"]) and \
                    time.time() < deadline:
                time.sleep(0.2)
            assert events and events[-1][0] == ["0", "1"], events
            m1.stop()
            m0.stop()
        finally:
            store.shutdown()
