"""Test harness config.

Runs the whole suite on the XLA CPU backend with 8 virtual devices so that
mesh/sharding/collective logic is exercised without TPU hardware — the
strategy SURVEY.md §4 calls for (the reference's closest analog is the
fake_cpu_device CustomDevice plugin, ref: paddle/phi/backends/custom/
fake_cpu_device.h + test/custom_runtime/).

Env vars must be set before the first jax import, hence this file's top.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env selects the TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The image ships a plugin that force-prepends the "axon" TPU platform to
# jax_platforms regardless of JAX_PLATFORMS; override after import so
# jax.devices() resolves to the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return np.random.default_rng(0)
