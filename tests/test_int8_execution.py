"""Int8 EXECUTION path (VERDICT r3 item 9): PTQ/QAT-calibrated Linears
lower to actual s8 x s8 -> s32 matmuls with a scale epilogue — not
fake-quant simulation (ref: the reference's inference quant passes +
phi int8 kernels; on TPU int8 is a native MXU fast path).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (Int8Linear, PTQ, QuantConfig,
                                     convert_to_int8)


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                         nn.Linear(64, 16))


def _calibrated_int8(model, calib):
    ptq = PTQ(QuantConfig())
    observed = ptq.quantize(model)
    for batch in calib:
        observed(paddle.to_tensor(batch))
    converted = ptq.convert(observed)
    return convert_to_int8(converted)


class TestInt8Execution:
    def test_convert_swaps_to_int8_layers(self, rng):
        model = _mlp()
        calib = [rng.normal(size=(16, 32)).astype(np.float32)
                 for _ in range(4)]
        q = _calibrated_int8(model, calib)
        int8_layers = [l for l in q.sublayers()
                       if isinstance(l, Int8Linear)]
        assert len(int8_layers) == 2
        for l in int8_layers:
            assert str(np.dtype(l.w_int8.dtype)) == "int8"

    def test_hlo_contains_int8_dot(self, rng):
        """The compiled program must really run s8 operands into an s32
        dot — the int8-execution contract, asserted on the HLO."""
        import jax

        model = _mlp()
        calib = [rng.normal(size=(16, 32)).astype(np.float32)
                 for _ in range(4)]
        q = _calibrated_int8(model, calib)
        from paddle_tpu.jit.api import functionalize
        apply, params0, _ = functionalize(q)

        def fwd(x):
            out, _ = apply(params0, {}, x)
            return out

        import jax.numpy as jnp
        x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
        hlo = jax.jit(fwd).lower(x).compile().as_text()
        assert "s8[" in hlo, "no int8 operand in the compiled program"
        assert "s32[" in hlo, "no int32 accumulation in the program"

    def test_accuracy_within_1pct_of_fp32(self, rng):
        """Top-1 agreement vs the fp32 model >= 99% on a trained
        classifier fixture (the reference's int8-deployment accuracy
        contract; an untrained model's near-tied random logits would
        test tie-flipping, not quantization quality)."""
        model = _mlp()
        # 16-class gaussian blobs; a short training run separates the
        # logits so top-1 is confident
        centers = rng.normal(size=(16, 32)).astype(np.float32) * 2.0
        labels = rng.integers(0, 16, 1024)
        data = (centers[labels]
                + rng.normal(size=(1024, 32)).astype(np.float32) * 0.3)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        crit = paddle.nn.CrossEntropyLoss()
        for i in range(60):
            sl = slice((i % 8) * 128, (i % 8) * 128 + 128)
            loss = crit(model(paddle.to_tensor(data[sl])),
                        paddle.to_tensor(labels[sl].astype(np.int64)))
            loss.backward()
            opt.step()
            opt.clear_grad()
        calib = [data[j * 128:(j + 1) * 128] for j in range(8)]
        q = _calibrated_int8(model, calib)
        x = (centers[labels]
             + rng.normal(size=(1024, 32)).astype(np.float32) * 0.3)
        ref = model(paddle.to_tensor(x)).numpy()
        got = q(paddle.to_tensor(x)).numpy()
        agree = (ref.argmax(-1) == got.argmax(-1)).mean()
        assert agree >= 0.99, agree
        # and the raw outputs stay close in an absolute sense
        scale = np.abs(ref).max()
        assert np.abs(got - ref).max() / scale < 0.1

    def test_int8_matches_fakequant_closely(self, rng):
        """Int8 execution approximates the fake-quant simulation it
        replaces (per-channel weight steps make it slightly MORE
        accurate, so compare both to fp32 rather than to each other)."""
        model = _mlp()
        calib = [rng.normal(size=(64, 32)).astype(np.float32)
                 for _ in range(8)]
        ptq = PTQ(QuantConfig())
        observed = ptq.quantize(model)
        for b in calib:
            observed(paddle.to_tensor(b))
        fake = ptq.convert(observed)
        int8 = convert_to_int8(fake)
        x = rng.normal(size=(128, 32)).astype(np.float32)
        ref = model(paddle.to_tensor(x)).numpy()
        e_fake = np.abs(fake(paddle.to_tensor(x)).numpy() - ref).mean()
        e_int8 = np.abs(int8(paddle.to_tensor(x)).numpy() - ref).mean()
        assert e_int8 <= e_fake * 1.5, (e_int8, e_fake)

    def test_predictor_serves_int8(self, rng, tmp_path):
        """The Predictor path (save -> load -> compiled serve) runs the
        int8 program end-to-end."""
        model = _mlp()
        calib = [rng.normal(size=(16, 32)).astype(np.float32)
                 for _ in range(4)]
        q = _calibrated_int8(model, calib)
        from paddle_tpu.inference import Predictor
        pred = Predictor(q)
        x = rng.normal(size=(4, 32)).astype(np.float32)
        out = pred.run(x)[0]
        ref = q(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                                   atol=1e-5)
