"""Multi-process DataLoader tests (ref: io/dataloader/worker.py,
dataloader_iter.py _DataLoaderIterMultiProcess): worker processes,
shared-memory transport, get_worker_info, per-worker seeding,
SubsetRandomSampler."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (DataLoader, Dataset, IterableDataset,
                           SubsetRandomSampler, get_worker_info)


class PidDataset(Dataset):
    """Returns the producing process pid with each sample."""

    def __init__(self, n=32, dim=8):
        self.n = n
        self.dim = dim

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        x = np.full((self.dim,), float(i), np.float32)
        return x, np.asarray([os.getpid(), i], np.int64)


class BigDataset(Dataset):
    """Samples big enough to take the /dev/shm path (>16KB)."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.full((64, 64), float(i), np.float32)  # 16KB each


class WorkerInfoDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None, "get_worker_info None inside worker"
        return np.asarray([info.id, info.num_workers, i], np.int64)


class ShardedIterable(IterableDataset):
    def __init__(self, n=24):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        if info is None:
            lo, hi, step = 0, self.n, 1
        else:
            lo, hi, step = info.id, self.n, info.num_workers
        for i in range(lo, hi, step):
            yield np.asarray([i], np.int64)


class TestMultiprocessDataLoader:
    def test_transforms_run_off_main_process(self):
        dl = DataLoader(PidDataset(), batch_size=4, num_workers=2)
        pids = set()
        seen = []
        for x, meta in dl:
            pids.update(np.asarray(meta)[:, 0].tolist())
            seen.extend(np.asarray(meta)[:, 1].tolist())
        assert os.getpid() not in pids, "samples produced in main process"
        assert len(pids) == 2, f"expected 2 worker pids, got {pids}"
        # sampler order preserved across round-robin workers
        assert seen == list(range(32))

    def test_batch_content_correct(self):
        dl = DataLoader(PidDataset(), batch_size=4, num_workers=2)
        for bi, (x, meta) in enumerate(dl):
            exp = np.stack([np.full((8,), float(4 * bi + j), np.float32)
                            for j in range(4)])
            np.testing.assert_array_equal(np.asarray(x), exp)

    def test_shared_memory_path(self):
        dl = DataLoader(BigDataset(), batch_size=2, num_workers=2,
                        use_shared_memory=True)
        out = [np.asarray(b) for b in dl]
        assert len(out) == 4
        for bi, b in enumerate(out):
            np.testing.assert_array_equal(
                b, np.stack([np.full((64, 64), 2. * bi, np.float32),
                             np.full((64, 64), 2. * bi + 1, np.float32)]))
        # no leaked segments
        leaks = [f for f in os.listdir("/dev/shm")
                 if f.startswith("ptpu_dl_")]
        assert not leaks, leaks

    def test_get_worker_info_inside_worker(self):
        dl = DataLoader(WorkerInfoDataset(), batch_size=2, num_workers=2)
        rows = np.concatenate([np.asarray(b) for b in dl])
        assert set(rows[:, 0].tolist()) == {0, 1}
        assert (rows[:, 1] == 2).all()
        assert get_worker_info() is None  # main process

    def test_iterable_dataset_sharded(self):
        dl = DataLoader(ShardedIterable(24), batch_size=3, num_workers=2)
        got = sorted(int(v) for b in dl for v in np.asarray(b).ravel())
        assert got == list(range(24))

    def test_worker_exception_propagates(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                if i == 2:
                    raise RuntimeError("boom-42")
                return np.zeros(4, np.float32)

        dl = DataLoader(Bad(), batch_size=2, num_workers=2)
        with pytest.raises(RuntimeError, match="boom-42"):
            list(dl)

    def test_worker_init_fn_and_seeding(self):
        calls = []

        class SeedDataset(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                # per-worker numpy seeding: same worker -> same stream
                return np.asarray([np.random.randint(0, 2 ** 30)],
                                  np.int64)

        # distinct workers must not produce identical random streams
        dl = DataLoader(SeedDataset(), batch_size=1, num_workers=2)
        vals = [int(np.asarray(b)[0, 0]) for b in dl]
        assert len(set(vals)) > 1

    def test_thread_fallback_flag(self, monkeypatch):
        monkeypatch.setenv("FLAGS_dataloader_use_threads", "1")
        dl = DataLoader(PidDataset(8), batch_size=2, num_workers=2)
        pids = {int(np.asarray(m)[j, 0]) for _, m in dl for j in range(2)}
        assert pids == {os.getpid()}


class TestSubsetRandomSampler:
    def test_permutes_subset_only(self):
        idx = [3, 5, 7, 11]
        s = SubsetRandomSampler(idx)
        got = list(s)
        assert sorted(got) == sorted(idx)
        assert len(s) == 4

    def test_with_dataloader(self):
        from paddle_tpu.io import BatchSampler
        ds = PidDataset(16)
        bs = BatchSampler(sampler=SubsetRandomSampler([0, 1, 2, 3]),
                          batch_size=2)
        dl = DataLoader(ds, batch_sampler=bs, num_workers=0)
        seen = sorted(int(np.asarray(m)[j, 1]) for _, m in dl
                      for j in range(2))
        assert seen == [0, 1, 2, 3]


class TestRobustness:
    def test_early_exit_cleans_shm(self):
        dl = DataLoader(BigDataset(), batch_size=2, num_workers=2,
                        use_shared_memory=True)
        it = iter(dl)
        next(it)  # consume one batch, abandon the rest
        it.close()
        import time
        time.sleep(0.3)
        leaks = [f for f in os.listdir("/dev/shm")
                 if f.startswith("ptpu_dl_")]
        assert not leaks, leaks

    def test_sigkilled_worker_detected_not_hang(self):
        import signal

        class KillSelf(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 3:
                    os.kill(os.getpid(), signal.SIGKILL)
                return np.zeros(4, np.float32)

        dl = DataLoader(KillSelf(), batch_size=2, num_workers=2)
        with pytest.raises(RuntimeError, match="died without reporting"):
            list(dl)

    def test_bounded_prefetch_window(self):
        """No more than prefetch_factor*num_workers batches may be
        produced ahead of the consumer (unbounded prefetch exhausts
        host RAM on big datasets)."""
        import multiprocessing as mp
        counter = mp.get_context("fork").Value("i", 0)

        class Counting(Dataset):
            def __init__(self, c):
                self.c = c

            def __len__(self):
                return 64

            def __getitem__(self, i):
                with self.c.get_lock():
                    self.c.value += 1
                return np.zeros(8, np.float32)

        dl = DataLoader(Counting(counter), batch_size=1, num_workers=2,
                        prefetch_factor=2)
        it = iter(dl)
        next(it)
        import time
        time.sleep(0.5)  # give workers time to run ahead if unbounded
        produced = counter.value
        it.close()
        # window = 2*2 batches in flight + the consumed one + refill
        assert produced <= 8, f"prefetch ran ahead: {produced} samples"
