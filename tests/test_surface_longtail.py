"""Long-tail API surface: utils helpers, amp/autograd extras, fft
hermitian n-d, linalg tail, incubate extras, geometric sampling,
distribution trio, device module, quantization bases, text re-exports
(ref: the per-module __all__ lists in python/paddle/*)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestUtils:
    def test_deprecated_levels(self):
        from paddle_tpu.utils import deprecated

        @deprecated(since="0.1", update_to="new_api", level=1)
        def old(x):
            return x + 1

        with pytest.warns(DeprecationWarning):
            assert old(1) == 2
        assert "Deprecated" in old.__doc__

        @deprecated(level=2)
        def gone():
            pass

        with pytest.raises(RuntimeError):
            gone()

    def test_run_check_and_versions(self, capsys):
        from paddle_tpu.utils import require_version, run_check, try_import
        run_check()
        assert "successfully" in capsys.readouterr().out
        require_version("0.0.1")
        with pytest.raises(Exception):
            require_version("999.0")
        assert try_import("math") is not None
        with pytest.raises(ImportError):
            try_import("definitely_not_a_module_xyz")


class TestAmpAutograd:
    def test_bf16_supported(self):
        assert paddle.amp.is_bfloat16_supported() is True
        assert isinstance(paddle.amp.is_float16_supported(), bool)

    def test_saved_tensors_hooks_pylayer(self):
        from paddle_tpu.autograd import PyLayer, saved_tensors_hooks
        packed, unpacked = [], []

        class Sq(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, g):
                (x,) = ctx.saved_tensor()
                return g * 2 * x

        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        with saved_tensors_hooks(
                lambda t: (packed.append(1), t.numpy())[-1],
                lambda p: (unpacked.append(1),
                           paddle.to_tensor(p))[-1]):
            y = Sq.apply(x)
        y.sum().backward()
        assert packed and unpacked
        np.testing.assert_allclose(x.grad.numpy(), [6.0])


class TestFFTHermitian:
    def test_hfft2_matches_composed_numpy(self, rng):
        x = (rng.normal(size=(4, 5)) + 1j * rng.normal(size=(4, 5)))
        x = x.astype(np.complex64)
        out = paddle.fft.hfft2(paddle.to_tensor(x)).numpy()
        want = np.fft.hfft(np.fft.fft(x, axis=-2), axis=-1)
        np.testing.assert_allclose(out, want, atol=1e-3)

    def test_ihfftn_roundtrips_hfftn(self, rng):
        real = rng.normal(size=(6, 8)).astype(np.float32)
        spec = paddle.fft.ihfftn(paddle.to_tensor(real))
        back = paddle.fft.hfftn(spec).numpy()
        np.testing.assert_allclose(back, real, atol=1e-3)


class TestLinalgTail:
    def test_inv_cond_norms_lu(self, rng):
        import paddle_tpu.linalg as L
        a_np = rng.normal(size=(5, 5)).astype(np.float32)
        a = paddle.to_tensor(a_np)
        np.testing.assert_allclose(L.inv(a).numpy(), np.linalg.inv(a_np),
                                   atol=1e-4)
        assert abs(float(L.cond(a).numpy())
                   - np.linalg.cond(a_np)) < 1e-2
        np.testing.assert_allclose(
            float(L.vector_norm(a).numpy()),
            np.linalg.norm(a_np.ravel()), rtol=1e-5)
        # keepdim with axis=None keeps every reduced dim as size-1
        kd = L.vector_norm(a, keepdim=True)
        assert kd.shape == [1, 1]
        np.testing.assert_allclose(float(kd.numpy()[0, 0]),
                                   np.linalg.norm(a_np.ravel()),
                                   rtol=1e-5)
        lu_m, piv = L.lu(a)
        P, Lo, U = L.lu_unpack(lu_m, piv)
        np.testing.assert_allclose(
            P.numpy() @ Lo.numpy() @ U.numpy(), a_np, atol=1e-4)

    def test_cholesky_inverse_and_matrix_exp(self, rng):
        import paddle_tpu.linalg as L
        a_np = rng.normal(size=(4, 4)).astype(np.float32)
        spd = a_np @ a_np.T + 4 * np.eye(4, dtype=np.float32)
        Lc = np.linalg.cholesky(spd)
        np.testing.assert_allclose(
            L.cholesky_inverse(paddle.to_tensor(Lc)).numpy(),
            np.linalg.inv(spd), atol=1e-3)
        np.testing.assert_allclose(
            L.matrix_exp(paddle.to_tensor(
                np.zeros((3, 3), np.float32))).numpy(),
            np.eye(3), atol=1e-6)

    def test_lowrank_factorizations(self, rng):
        import paddle_tpu.linalg as L
        paddle.seed(0)
        lowr = (rng.normal(size=(8, 2))
                @ rng.normal(size=(2, 6))).astype(np.float32)
        U, S, V = L.svd_lowrank(paddle.to_tensor(lowr), q=4)
        rec = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
        np.testing.assert_allclose(rec, lowr, atol=1e-3)
        U2, _, _ = L.pca_lowrank(paddle.to_tensor(lowr), q=3)
        assert U2.shape[1] == 3

    def test_fp8_gemm_contract(self, rng):
        import paddle_tpu.linalg as L
        a = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
        out = L.fp8_fp8_half_gemm_fused(a, a, act="relu")
        assert "bfloat16" in str(out.dtype)
        assert float(out.numpy().astype(np.float32).min()) >= 0


class TestIncubateExtras:
    def test_masked_softmax_and_identity_loss(self, rng):
        import paddle_tpu.incubate as inc
        x = paddle.to_tensor(rng.normal(size=(2, 4, 4)).astype(np.float32))
        m = paddle.to_tensor(np.zeros((2, 4, 4), np.float32))
        a = inc.softmax_mask_fuse(x, m).numpy()
        b = inc.softmax_mask_fuse_upper_triangle(x).numpy()
        assert np.allclose(a.sum(-1), 1, atol=1e-5)
        assert np.allclose(np.triu(b[0], 1), 0, atol=1e-6)
        assert abs(float(inc.identity_loss(x, "mean").numpy())
                   - x.numpy().mean()) < 1e-6

    def test_lookahead_trains(self, rng):
        import paddle_tpu.incubate as inc
        import paddle_tpu.nn as nn
        paddle.seed(0)
        mdl = nn.Linear(4, 4)
        opt = inc.LookAhead(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=mdl.parameters()),
            alpha=0.5, k=2)
        X = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))
        l0 = None
        for _ in range(6):
            loss = (mdl(X) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            l0 = l0 if l0 is not None else float(loss)
        assert float(loss) < l0

    def test_model_average_window_mean(self, rng):
        import paddle_tpu.incubate as inc
        import paddle_tpu.nn as nn
        paddle.seed(0)
        mdl = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=mdl.parameters())
        X = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))
        ma = inc.ModelAverage(0.5, parameters=mdl.parameters(),
                              min_average_window=10,
                              max_average_window=100)
        vals = []
        for _ in range(3):
            loss = (mdl(X) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.step()
            vals.append(mdl.weight.numpy().copy())
        trained = mdl.weight.numpy().copy()
        with ma.apply():
            applied = mdl.weight.numpy().copy()
        np.testing.assert_allclose(mdl.weight.numpy(), trained)
        np.testing.assert_allclose(applied, np.mean(vals, axis=0),
                                   atol=1e-5)


class TestGeometricSampling:
    ROW = np.array([1, 2, 0, 2, 0, 1], np.int64)
    COLPTR = np.array([0, 2, 4, 6], np.int64)

    def test_sample_neighbors(self):
        import paddle_tpu.geometric as G
        n, c = G.sample_neighbors(
            paddle.to_tensor(self.ROW), paddle.to_tensor(self.COLPTR),
            paddle.to_tensor(np.array([0, 2], np.int64)))
        assert c.numpy().tolist() == [2, 2]
        assert sorted(n.numpy()[:2].tolist()) == [1, 2]

    def test_weighted_sample_respects_support(self):
        import paddle_tpu.geometric as G
        w = np.array([1.0, 0.0, 1.0, 1.0, 1.0, 1.0], np.float32)
        n, c = G.weighted_sample_neighbors(
            paddle.to_tensor(self.ROW), paddle.to_tensor(self.COLPTR),
            paddle.to_tensor(w),
            paddle.to_tensor(np.array([0], np.int64)), sample_size=1)
        assert n.numpy().tolist() == [1]  # the zero-weight edge never

    def test_send_uv_and_heter_reindex(self, rng):
        import paddle_tpu.geometric as G
        x = paddle.to_tensor(rng.normal(size=(3, 4)).astype(np.float32))
        uv = G.send_uv(x, x, paddle.to_tensor(np.array([0], np.int64)),
                       paddle.to_tensor(np.array([2], np.int64)), "sub")
        np.testing.assert_allclose(
            uv.numpy()[0], x.numpy()[0] - x.numpy()[2], atol=1e-6)
        src, dst, nodes = G.reindex_heter_graph(
            paddle.to_tensor(np.array([0, 1], np.int64)),
            [paddle.to_tensor(np.array([5, 6, 5], np.int64))],
            [paddle.to_tensor(np.array([2, 1], np.int64))])
        assert nodes.numpy().tolist() == [0, 1, 5, 6]
        assert src.numpy().tolist() == [2, 3, 2]
        assert dst.numpy().tolist() == [0, 0, 1]


class TestDistributionTrio:
    def test_continuous_bernoulli_moments_and_cdf(self):
        from paddle_tpu.distribution import ContinuousBernoulli
        paddle.seed(0)
        for p in (0.25, 0.7):
            cb = ContinuousBernoulli(p)
            xs = np.linspace(1e-4, 1 - 1e-4, 10001).astype(np.float32)
            pdf = cb.prob(paddle.to_tensor(xs)).numpy().astype(np.float64)
            Z = np.trapezoid(pdf, xs)
            m = np.trapezoid(pdf * xs, xs)
            v = np.trapezoid(pdf * (xs - m) ** 2, xs)
            assert abs(Z - 1) < 1e-3
            assert abs(float(cb.mean.numpy()) - m) < 1e-3
            assert abs(float(cb.variance.numpy()) - v) < 1e-3
            u = np.array([0.1, 0.5, 0.9], np.float32)
            x = cb.icdf(paddle.to_tensor(u))
            np.testing.assert_allclose(cb.cdf(x).numpy(), u, atol=1e-4)
        # Taylor patch at p=0.5 stays finite
        cb5 = ContinuousBernoulli(0.5)
        assert abs(float(cb5.mean.numpy()) - 0.5) < 1e-4

    def test_lkj_known_densities(self):
        from paddle_tpu.distribution import LKJCholesky
        paddle.seed(0)
        # dim=2: p(rho) = C (1-rho^2)^(eta-1); eta=1 -> uniform (1/2),
        # eta=2 -> 3/4 (1-rho^2)
        for eta, want_fn in ((1.0, lambda r: 0.5),
                             (2.0, lambda r: 0.75 * (1 - r * r))):
            lkj = LKJCholesky(2, eta)
            for rho in (-0.6, 0.0, 0.5):
                L = np.array([[1, 0], [rho, np.sqrt(1 - rho ** 2)]],
                             np.float32)
                lp = float(lkj.log_prob(paddle.to_tensor(L)).numpy())
                assert abs(lp - np.log(want_fn(rho))) < 5e-4

    def test_lkj_samples_are_correlation_cholesky(self):
        from paddle_tpu.distribution import LKJCholesky
        paddle.seed(0)
        Ls = LKJCholesky(3, 2.0).sample((200,)).numpy()
        corr = Ls @ np.swapaxes(Ls, -1, -2)
        np.testing.assert_allclose(
            np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-5)
        assert abs(corr[:, 1, 0].mean()) < 0.1


class TestDeviceModule:
    def test_streams_events_and_queries(self):
        import paddle_tpu.device as D
        assert "cpu" in D.get_all_device_type() or D.get_all_device_type()
        s = D.Stream()
        e = s.record_event()
        assert e.query() is True
        e.synchronize()
        with D.stream_guard(D.Stream()):
            pass
        D.synchronize()
        assert D.get_cudnn_version() is None
        assert D.is_compiled_with_rocm() is False
        with pytest.raises(RuntimeError):
            D.XPUPlace(0)


class TestQuantBase:
    def test_quanter_factory(self):
        from paddle_tpu.quantization import BaseQuanter, quanter

        @quanter("MyQuanterFactory")
        class MyQuanter(BaseQuanter):
            def __init__(self, bits=8):
                super().__init__()
                self.bits = bits

            def forward(self, x):
                return x

            def bit_length(self):
                return self.bits

        import sys
        factory_cls = getattr(sys.modules[MyQuanter.__module__],
                              "MyQuanterFactory")
        inst = factory_cls(bits=4)._instance()
        assert isinstance(inst, MyQuanter) and inst.bit_length() == 4


class TestTextSurface:
    def test_dataset_names_reexported(self):
        import paddle_tpu.text as t
        for n in ("Conll05st", "Imdb", "Imikolov", "Movielens",
                  "UCIHousing", "WMT14", "WMT16"):
            assert hasattr(t, n), n


class TestReviewRegressions:
    def test_khop_revisited_frontier_dst_ids(self):
        """Hop-2 edges from a revisited node must use its EXISTING id
        (reindex-by-position corrupted them)."""
        import paddle_tpu.incubate as inc
        row = np.array([1, 0, 0], np.int64)
        colptr = np.array([0, 2, 3], np.int64)
        src, dst, nodes, cnt = inc.graph_khop_sampler(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.array([0], np.int64)), [-1, -1])
        n = len(nodes.numpy())
        assert dst.numpy().max() < n and src.numpy().max() < n
        # hop 1: node 0 -> {1, 0}; hop 2 dst ids must be the ids of 1
        # and 0 themselves (1 and 0), never a fresh id
        assert set(dst.numpy().tolist()) <= {0, 1}

    def test_ormqr_nonsquare_full_q(self, rng):
        import scipy.linalg as sl
        import paddle_tpu.linalg as L
        a_np = rng.normal(size=(4, 2)).astype(np.float32)
        (h, tau), _ = sl.qr(a_np, mode="raw")
        y = rng.normal(size=(4, 3)).astype(np.float32)
        out = L.ormqr(paddle.to_tensor(h.astype(np.float32)),
                      paddle.to_tensor(tau.astype(np.float32)),
                      paddle.to_tensor(y))
        q_full, _ = sl.qr(a_np, mode="full")
        # sign conventions match because both use the same reflectors
        np.testing.assert_allclose(out.numpy(), q_full @ y, atol=1e-4)

    def test_fp8_gemm_bias_before_act(self):
        import paddle_tpu.linalg as L
        eye = paddle.to_tensor(np.eye(3, dtype=np.float32))
        out = L.fp8_fp8_half_gemm_fused(
            eye, eye, bias=paddle.to_tensor(
                np.full((3,), -5.0, np.float32)), act="relu")
        # relu(I @ I - 5) == 0 everywhere; act-then-bias would give -4/-5
        assert float(out.numpy().astype(np.float32).min()) == 0.0

    def test_incubate_graph_signature_order(self):
        """Reference positional order: (row, colptr, nodes, eids,
        perm_buffer, sample_size)."""
        import paddle_tpu.incubate as inc
        row = np.array([1, 2, 0, 2, 0, 1], np.int64)
        colptr = np.array([0, 2, 4, 6], np.int64)
        n, c = inc.graph_sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.array([0], np.int64)), None, None, 1)
        assert c.numpy().tolist() == [1]
        out = inc.graph_send_recv(
            paddle.to_tensor(np.eye(3, dtype=np.float32)),
            paddle.to_tensor(np.array([0, 1], np.int64)),
            paddle.to_tensor(np.array([1, 2], np.int64)), "sum")
        assert out.shape == [3, 3]
