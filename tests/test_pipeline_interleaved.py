"""Compiled interleaved (VPP) pipeline schedule tests: numerics must match
the serial layer stack and the non-interleaved compiled pipeline
(ref: fleet/meta_parallel/pipeline_parallel.py:1174 VPP semantics)."""
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401


def _mesh(pp=4):
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:pp]).reshape(pp)
    return Mesh(devs, ("pp",))


def _stage_fn(p, x):
    import jax.numpy as jnp
    return jnp.tanh(x @ p["w"] + p["b"])


def _stack(rng, L, d):
    import jax.numpy as jnp
    per = [{"w": jnp.asarray(rng.normal(size=(d, d)) * 0.5, jnp.float32),
            "b": jnp.asarray(rng.normal(size=(d,)) * 0.1, jnp.float32)}
           for _ in range(L)]
    from paddle_tpu.parallel import stack_layer_params
    return per, stack_layer_params(per)


def _serial(per, x):
    import jax.numpy as jnp
    for p in per:
        x = jnp.tanh(x @ p["w"] + p["b"])
    return x


@pytest.mark.parametrize("M,V,L", [(4, 2, 8), (8, 2, 8), (3, 3, 12)])
def test_interleaved_matches_serial(rng, M, V, L):
    import jax.numpy as jnp
    from paddle_tpu.parallel import spmd_pipeline_interleaved

    d = 8
    per, stacked = _stack(rng, L, d)
    mesh = _mesh(4)
    mb = jnp.asarray(rng.normal(size=(M, 2, d)), jnp.float32)
    out = spmd_pipeline_interleaved(_stage_fn, stacked, mb, mesh, "pp",
                                    num_chunks=V)
    want = np.stack([_serial(per, mb[m]) for m in range(M)])
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


def test_interleaved_matches_noninterleaved(rng):
    import jax.numpy as jnp
    from paddle_tpu.parallel import spmd_pipeline, spmd_pipeline_interleaved

    d, L, M = 8, 8, 4
    per, stacked = _stack(rng, L, d)
    mesh = _mesh(4)
    mb = jnp.asarray(rng.normal(size=(M, 2, d)), jnp.float32)
    a = spmd_pipeline(_stage_fn, stacked, mb, mesh, "pp")
    b = spmd_pipeline_interleaved(_stage_fn, stacked, mb, mesh, "pp",
                                  num_chunks=2)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_interleaved_grad_matches_serial(rng):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import spmd_pipeline_interleaved

    d, L, M, V = 4, 8, 4, 2
    per, stacked = _stack(rng, L, d)
    mesh = _mesh(4)
    mb = jnp.asarray(rng.normal(size=(M, 2, d)), jnp.float32)

    def loss_pipe(params):
        out = spmd_pipeline_interleaved(_stage_fn, params, mb, mesh, "pp",
                                        num_chunks=V)
        return (out ** 2).mean()

    def loss_serial(params):
        outs = []
        for m in range(M):
            x = mb[m]
            for i in range(L):
                p = jax.tree.map(lambda a: a[i], params)
                x = jnp.tanh(x @ p["w"] + p["b"])
            outs.append(x)
        return (jnp.stack(outs) ** 2).mean()

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_serial = jax.grad(loss_serial)(stacked)
    for k in g_pipe:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_serial[k]), atol=1e-5)


def test_layer_count_validation(rng):
    import jax.numpy as jnp
    from paddle_tpu.parallel import spmd_pipeline_interleaved

    _, stacked = _stack(rng, 6, 4)
    mesh = _mesh(4)
    mb = jnp.zeros((2, 2, 4), jnp.float32)
    with pytest.raises(ValueError, match="multiple of num_chunks"):
        spmd_pipeline_interleaved(_stage_fn, stacked, mb, mesh, "pp",
                                  num_chunks=2)
