"""Paged KV cache serving (ISSUE 11): block-pool allocator invariants,
paged-vs-dense decode equivalence across bucketed prompt lengths,
chunked prefill/decode interleave under GenerationServer, exhaustion
and eviction accounting, and the captured paged decode step's
0-host-sync steady state.

Oracle strategy: the dense LlamaDecodeEngine (itself pinned against
LlamaForCausalLM.generate in test_serving_generation.py) is the token
reference — the paged engine must reproduce its greedy streams
exactly, with HBM proportional to active tokens instead of
slots x max_seq. Reference streams are computed once per prompt on a
module-scoped dense engine (the hapi-generate oracle costs seconds
per request; the compiled dense engine costs milliseconds and is
transitively oracle-pinned).
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (GenerationServer, LlamaDecodeEngine,
                                PagedLlamaDecodeEngine)
from paddle_tpu.serving_cache import PagedKVCache

CFG = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
           num_hidden_layers=2, num_attention_heads=4,
           num_key_value_heads=2, use_flash_attention=False)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny(**CFG))


@pytest.fixture(scope="module")
def dense_ref(model):
    """Module-scoped dense reference engine + memoized greedy streams
    (max_seq 256 so no reference stream ever truncates early)."""
    eng = LlamaDecodeEngine(model, max_slots=1, max_seq=256)
    cache = {}

    def ref(prompt, n_new):
        key = (tuple(int(t) for t in prompt), int(n_new))
        if key not in cache:
            cache[key] = eng.generate(list(key[0]), max_new_tokens=n_new)
        return cache[key]

    return ref


@pytest.fixture(scope="module")
def paged64(model):
    """Shared paged engine (2 slots, max_seq 64, 8-token blocks and
    prefill chunks); tests release every slot they touch."""
    return PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                  block_size=8, prefill_chunk=8)


def _wait_steps(srv, n, tries=400):
    for _ in range(tries):
        if srv.steps_run >= n:
            return True
        time.sleep(0.02)
    return False


class TestPagedVsDense:
    def test_bit_equivalence_across_bucketed_prompt_lengths(
            self, model, dense_ref, paged64):
        """Paged greedy streams match the dense engine token-for-token
        for prompts spanning the prefill buckets (3 -> one sub-chunk
        bucket, 30 -> four 8-token chunks crossing block boundaries)."""
        for prompt in ([5, 9, 11, 3], [2], [1, 2, 3, 4, 5, 6, 7, 8],
                       list(range(1, 14)), list(range(3, 33))):
            want = dense_ref(prompt, 12)
            got = paged64.generate(prompt, max_new_tokens=12)
            assert got == want, (len(prompt), got, want)
        # every request released its blocks + reservation
        st = paged64._kv.stats()
        assert st["blocks_used"] == 0 and st["blocks_reserved"] == 0

    def test_slots_are_independent(self, dense_ref, paged64):
        """Interleaved slots over a SHARED block pool produce exactly
        their single-request sequences (no cross-slot block leaks)."""
        p0, p1 = [1, 2, 3], [40, 41, 42, 43, 44]
        o0 = [paged64.prefill(0, p0, budget=8)]
        o1 = [paged64.prefill(1, p1, budget=8)]
        for _ in range(5):
            nxt = paged64.step()
            o0.append(int(nxt[0]))
            o1.append(int(nxt[1]))
        paged64.release(0)
        paged64.release(1)
        assert o0 == dense_ref(p0, 6)
        assert o1 == dense_ref(p1, 6)

    def test_decode_window_matches_dense(self, dense_ref, paged64):
        """decode_steps (device-resident token feedback, one fetch per
        window) over the block pool continues each slot's reference
        stream, with the window's blocks pre-mapped so the device
        table stays valid."""
        p0, p1 = [1, 2, 3], [4, 5]
        paged64.prefill(0, p0, budget=20)
        paged64.prefill(1, p1, budget=20)
        toks = paged64.decode_steps(6)
        paged64.release(0)
        paged64.release(1)
        assert list(toks[0]) == dense_ref(p0, 7)[1:]
        assert list(toks[1]) == dense_ref(p1, 7)[1:]

    def test_slot_reuse_after_release(self, paged64):
        a = paged64.generate([7, 8], max_new_tokens=4)
        b = paged64.generate([7, 8], max_new_tokens=4)
        assert a == b  # recycled blocks must not leak stale K/V

    def test_recycled_block_garbage_is_inert(self, dense_ref, paged64):
        """Blocks recycled from a pathological request (activations
        driven to NaN/inf write non-finite K/V) must be invisible to
        the next request sharing the pool: masked columns contribute
        exactly zero. Pins the 0*NaN=NaN leak in the PV contraction —
        the pool poisons NOTHING even when every stale cell is NaN."""
        import jax.numpy as jnp

        paged64.kvs["k"] = [jnp.full_like(a, jnp.nan)
                            for a in paged64.kvs["k"]]
        paged64.kvs["v"] = [jnp.full_like(a, jnp.nan)
                            for a in paged64.kvs["v"]]
        prompt = [5, 9, 11, 3]
        assert paged64.generate(prompt, max_new_tokens=12) == \
            dense_ref(prompt, 12)

    def test_quantized_kv_blocks(self, model, dense_ref):
        """bf16 pools on an f32 model and int8 absmax pools both
        decode deterministically; int8 stays close to the exact
        stream early on (same-first-token sanity)."""
        want = dense_ref([5, 9, 11], 6)
        for quant in ("bfloat16", "int8"):
            eng = PagedLlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                         block_size=16, kv_quant=quant)
            out = eng.generate([5, 9, 11], max_new_tokens=6)
            assert len(out) == 6
            assert all(0 <= t < CFG["vocab_size"] for t in out)
            assert out == eng.generate([5, 9, 11], max_new_tokens=6)
            assert out[0] == want[0], (quant, out, want)

    def test_export_decode_roundtrip(self, model):
        """The paged decode step AOT-exports with its block-pool
        signature and the artifact matches the live step."""
        import jax
        import jax.numpy as jnp

        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=32,
                                     block_size=8)
        eng.prefill(0, [3, 4, 5], budget=8)
        blob = eng.export_decode()
        assert isinstance(blob, (bytes, bytearray)) and len(blob) > 0
        rebuilt = jax.export.deserialize(bytearray(blob))
        args = (eng.params, eng.kvs, jnp.asarray(eng.last_ids),
                jnp.asarray(eng.pos),
                jnp.asarray(eng._kv.block_tables),
                jnp.asarray(eng.active))
        nxt_aot, _ = rebuilt.call(*args)
        nxt_live, _ = jax.jit(eng._decode_impl)(*args)
        assert int(nxt_aot[0]) == int(nxt_live[0])

    def test_no_dense_view_in_paged_attention(self, model):
        """Acceptance: the paged decode step never materializes a
        dense [., max_seq] score or cache view — no intermediate in
        its jaxpr (loop bodies included) carries a max_seq-sized
        dimension. max_seq=48 is chosen to collide with no other
        dimension of this geometry."""
        import jax
        import jax.numpy as jnp

        max_seq = 48
        eng = PagedLlamaDecodeEngine(model, max_slots=3,
                                     max_seq=max_seq, block_size=16)
        args = (eng.params, eng.kvs, jnp.asarray(eng.last_ids),
                jnp.asarray(eng.pos),
                jnp.asarray(eng._kv.block_tables),
                jnp.asarray(eng.active))
        jaxpr = jax.make_jaxpr(eng._decode_impl)(*args)

        offenders = []

        def walk(jx):
            for eqn in jx.eqns:
                for v in eqn.outvars:
                    shape = getattr(v.aval, "shape", ())
                    if max_seq in tuple(shape):
                        offenders.append((eqn.primitive.name,
                                          tuple(shape)))
                for p in eqn.params.values():
                    for sub in (p if isinstance(p, (list, tuple))
                                else [p]):
                        if isinstance(sub, jax.core.Jaxpr):
                            walk(sub)
                        elif isinstance(sub, jax.core.ClosedJaxpr):
                            walk(sub.jaxpr)

        walk(jaxpr.jaxpr)
        assert offenders == [], offenders


class TestBlockAllocator:
    def test_admit_extend_release_churn_no_leaks(self):
        """Randomized admit/extend/release churn: blocks are never
        double-owned, free + owned == pool, reservations balance, and
        a full drain returns the pool to its initial state."""
        rng = np.random.default_rng(0)
        kv = PagedKVCache(max_slots=8, max_seq=64, block_size=8,
                          num_blocks=20)
        held = {}  # slot -> next unmapped position
        for _ in range(300):
            op = rng.integers(0, 3)
            if op == 0:  # admit
                free = [s for s in range(8) if s not in held]
                if free:
                    s = int(rng.choice(free))
                    tokens = int(rng.integers(1, 40))
                    if kv.admit(s, min(tokens, 8), tokens):
                        held[s] = min(tokens, 8)
            elif op == 1 and held:  # extend within reservation
                s = int(rng.choice(list(held)))
                pos = held[s]
                bidx = pos // kv.block_size
                if bidx < kv.max_blocks_per_slot and \
                        kv.block_tables[s, bidx] < 0:
                    try:
                        kv.ensure_token(s, pos)
                        held[s] = pos + kv.block_size
                    except RuntimeError:
                        pass  # budget spent: legal terminal state
                else:
                    held[s] = pos + 1
            elif held:  # release
                s = int(rng.choice(list(held)))
                kv.release(s, evicted=bool(rng.integers(0, 2)))
                del held[s]
            st = kv.stats()
            owned = sum(len(b) for b in kv._owned.values())
            assert st["blocks_free"] + owned == 20
            assert st["blocks_reserved"] == sum(kv._reserved.values())
            assert st["blocks_free"] >= st["blocks_reserved"]
            mapped = int((kv.block_tables >= 0).sum())
            assert mapped == owned
            phys = kv.block_tables[kv.block_tables >= 0]
            assert len(set(phys.tolist())) == len(phys)  # no aliasing
        for s in list(held):
            kv.release(s)
        st = kv.stats()
        assert st["blocks_free"] == 20 and st["blocks_used"] == 0
        assert st["blocks_reserved"] == 0
        assert (kv.block_tables == -1).all()

    def test_exhaustion_defers_and_recovers(self):
        kv = PagedKVCache(max_slots=4, max_seq=64, block_size=8,
                          num_blocks=4)
        assert kv.admit(0, 8, 16)          # 2 now, 0 reserved... 2 total
        assert kv.admit(1, 8, 16)
        assert not kv.admit(2, 8, 16)      # pool covered: defer
        assert kv.stats()["blocks_available"] == 0
        kv.release(0)
        assert kv.admit(2, 8, 16)          # recovered

    def test_impossible_request_raises(self):
        kv = PagedKVCache(max_slots=2, max_seq=256, block_size=8,
                          num_blocks=4)
        with pytest.raises(ValueError, match="pool holds only"):
            kv.admit(0, 8, 200)            # needs 25 blocks of 4

    def test_reservation_guarantees_extension(self):
        """The admission invariant: a second admit cannot eat blocks
        an earlier request reserved for its decode tail."""
        kv = PagedKVCache(max_slots=2, max_seq=64, block_size=8,
                          num_blocks=3)
        assert kv.admit(0, 4, 24)          # 1 mapped + 2 reserved
        assert not kv.admit(1, 4, 8)       # nothing left to reserve
        kv.ensure_token(0, 8)
        kv.ensure_token(0, 16)             # reservation fully drawn
        assert kv.stats()["blocks_used"] == 3

    def test_eviction_counter_counts_reclaims_only(self):
        kv = PagedKVCache(max_slots=2, max_seq=32, block_size=8,
                          num_blocks=4)
        kv.admit(0, 8, 8)
        kv.release(0)                      # normal completion
        assert kv.evictions == 0
        kv.admit(1, 16, 16)
        kv.release(1, evicted=True)        # deadline/failure reclaim
        assert kv.evictions == 2


class TestServerInterleave:
    def test_concurrent_requests_share_pool(self, model, dense_ref):
        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8, prefill_chunk=8)
        srv = GenerationServer(eng)
        jobs = [([1, 2, 3], 8), ([40, 41], 5), (list(range(1, 25)), 6)]
        results = {}

        def run(i, prompt, n):
            results[i] = srv.generate(prompt, n, timeout=120)

        ts = [threading.Thread(target=run, args=(i, p, n))
              for i, (p, n) in enumerate(jobs)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        for i, (p, n) in enumerate(jobs):
            assert results[i] == dense_ref(p, n), i
        assert srv.admitted == 3
        assert srv.shutdown(drain=True, timeout=120)
        assert srv.stats()["kv_pool"]["blocks_used"] == 0

    def test_pool_exhaustion_queues_not_crashes(self, model, dense_ref):
        """More requests than the pool covers: the overflow WAITS for
        blocks (never a loop crash), is admitted as earlier requests
        release, and every stream still matches its oracle."""
        eng = PagedLlamaDecodeEngine(model, max_slots=4, max_seq=64,
                                     block_size=8, num_blocks=4,
                                     prefill_chunk=8)
        srv = GenerationServer(eng)
        reqs = [srv.submit([1, 2, 3, 4, 5, 6, 7], 8) for _ in range(5)]
        for r in reqs:
            assert r["done"].wait(120), srv.stats()
            assert r["error"] is None, r["error"]
            assert list(r["out"]) == dense_ref([1, 2, 3, 4, 5, 6, 7], 8)
        st = srv.stats()
        assert st["kv_pool"]["blocks_used"] == 0
        assert srv.shutdown(drain=True, timeout=60)

    def test_deferred_request_is_not_starved(self, model, dense_ref):
        """Head-of-line fairness: while a large request waits for
        blocks, newer small requests must NOT be admitted past it and
        re-consume every freed block — the deferred request admits
        first once capacity frees."""
        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8, num_blocks=4,
                                     prefill_chunk=8)
        orig_step = eng.step

        def slow_step():
            time.sleep(0.03)
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        small_a = srv.submit([1, 2, 3], 12)       # 2 blocks, runs long
        assert _wait_steps(srv, 2)
        big = srv.submit(list(range(1, 17)), 15)  # needs all 4 blocks
        small_c = srv.submit([4, 5], 6)           # 1 block, arrives last
        for r in (small_a, big, small_c):
            assert r["done"].wait(120) and r["error"] is None, r["error"]
        # the big request was admitted BEFORE the later small one
        assert big["t_admit"] < small_c["t_admit"], (
            big["t_admit"], small_c["t_admit"])
        assert list(big["out"]) == dense_ref(list(range(1, 17)), 15)
        srv.shutdown()

    def test_drain_shutdown_with_prefill_in_flight(self, model,
                                                   dense_ref):
        """Drain during a chunked prefill: the half-prefilled long
        prompt AND everything queued complete with full oracle
        streams before the loop exits."""
        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=256,
                                     block_size=16, prefill_chunk=8)
        srv = GenerationServer(eng)
        short = srv.submit([1, 2, 3], 10)
        assert _wait_steps(srv, 2)
        long_p = list(range(2, 60))        # 58 tokens -> 8 chunks
        long = srv.submit(long_p, 6)
        queued = srv.submit([7, 9, 2], 5)
        assert srv.shutdown(drain=True, timeout=180)
        for req, (p, n) in ((short, ([1, 2, 3], 10)),
                            (long, (long_p, 6)),
                            (queued, ([7, 9, 2], 5))):
            assert req["done"].is_set()
            assert req["error"] is None, req["error"]
            assert list(req["out"]) == dense_ref(p, n)
        assert srv.stats()["kv_pool"]["blocks_used"] == 0

    def test_expired_requests_return_blocks_as_evictions(self, model,
                                                         dense_ref):
        """Deadline expiry — waiting-for-blocks OR active — frees the
        blocks and counts them into block_evictions_total."""
        eng = PagedLlamaDecodeEngine(model, max_slots=1, max_seq=64,
                                     block_size=8, num_blocks=4,
                                     prefill_chunk=8)
        orig_step = eng.step

        def slow_step():
            time.sleep(0.05)
            return orig_step()

        eng.step = slow_step
        srv = GenerationServer(eng)
        blocker = srv.submit([1, 2, 3], 25)        # hogs slot + blocks
        starved = srv.submit([9, 8], 8, deadline=0.3)
        assert starved["done"].wait(60)
        assert isinstance(starved["error"], TimeoutError)
        active = srv.submit(list(range(1, 6)), 24, deadline=1.2)
        assert blocker["done"].wait(120) and blocker["error"] is None
        assert active["done"].wait(120)
        assert isinstance(active["error"], TimeoutError)
        assert len(active["out"]) >= 1             # partials retained
        assert eng._kv.evictions >= 1              # reclaim counted
        assert eng._kv.stats()["blocks_used"] == 0
        # pool recovered: a fresh request still serves
        assert srv.generate([1, 2, 3], 2, timeout=60) == \
            dense_ref([1, 2, 3], 2)
        srv.shutdown()

    @pytest.mark.slow
    def test_long_prompt_does_not_stall_decode(self, model):
        """Acceptance regression: per-step decode latency for an
        already-admitted stream while a long prompt chunk-prefills
        stays within 2x its no-prefill baseline (+ scheduling slack).
        Gaps come from the flight recorder's per-step decode events,
        so the measurement sees exactly what the loop does."""
        from paddle_tpu.observability import flight

        def median_decode_gap(with_long_prompt):
            eng = PagedLlamaDecodeEngine(model, max_slots=2,
                                         max_seq=512, block_size=16,
                                         prefill_chunk=16)
            srv = GenerationServer(eng)
            a = srv.submit([1, 2, 3], 60)
            assert _wait_steps(srv, 4)
            if with_long_prompt:
                srv.submit(list(range(2, 300)), 4)   # ~19 chunks
            assert a["done"].wait(180)
            assert srv.shutdown(drain=True, timeout=180)
            ev = [e for e in flight.events(trace_id=a["trace_id"])
                  if e["name"] == "decode"]
            gaps = np.diff([e["ts_us"] for e in ev]) / 1e6
            assert len(gaps) >= 20
            return float(np.median(gaps))

        base = median_decode_gap(False)
        overlapped = median_decode_gap(True)
        assert overlapped <= 2.0 * base + 0.05, (overlapped, base)


class TestPagedCapture:
    def test_paged_decode_step_audits_zero_syncs(self, model):
        """The captured paged decode step runs 0 host syncs in steady
        state and counts into sot.captured_steps_total (capture_jit
        accounting), like the dense step it replaces."""
        import jax.numpy as jnp
        from paddle_tpu import analysis
        from paddle_tpu.observability import metrics as om

        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8)
        eng.prefill(0, [1, 2, 3], budget=30)
        eng.prefill(1, [4, 5], budget=30)
        for _ in range(3):                 # warm + steady state
            eng.step()

        def one_captured_step():
            eng._extend_tables()
            nxt, eng.kvs = eng._decode(
                eng.params, eng.kvs, jnp.asarray(eng.last_ids),
                jnp.asarray(eng.pos), jnp.asarray(eng._kv.block_tables),
                jnp.asarray(eng.active))
            return nxt

        before = dict(om.snapshot().get("sot", {}))
        rep = analysis.audit(one_captured_step)
        after = dict(om.snapshot().get("sot", {}))
        assert rep.syncs == [], rep.syncs
        assert not [d for d in rep.diagnostics
                    if d.rule in ("PTA001", "PTA002", "PTA003")], \
            [d.to_dict() for d in rep.diagnostics]
        got = after.get("captured_steps_total", 0) - \
            before.get("captured_steps_total", 0)
        assert got >= 1, (before, after)

    def test_block_pool_gauges_and_flight_events(self, model):
        """serving.blocks_free/blocks_used track the pool and the
        flight journal carries block_alloc/block_free (and
        block_exhausted on a deferred admission)."""
        from paddle_tpu.observability import flight
        from paddle_tpu.observability import metrics as om

        eng = PagedLlamaDecodeEngine(model, max_slots=2, max_seq=64,
                                     block_size=8, num_blocks=4)
        assert eng.begin_request(0, [1, 2, 3, 4, 5, 6, 7, 8, 9], 14)
        snap = om.snapshot()["serving"]
        assert snap["blocks_used"] == 2          # 9 tokens -> 2 blocks
        assert snap["blocks_free"] == 4 - 3      # +1 block reserved
        assert not eng.begin_request(1, [1] * 9, 14)  # exhausted
        eng.release(0, evicted=True)
        snap = om.snapshot()["serving"]
        assert snap["blocks_used"] == 0 and snap["blocks_free"] == 4
        names = [e["name"] for e in flight.events(category="serving")]
        for expected in ("block_alloc", "block_exhausted",
                         "block_free"):
            assert expected in names, names
