"""Live device-memory observability (VERDICT r3 item 4).

Covers paddle_tpu.device memory_stats/max_memory_allocated over the
op-boundary tracker + native MemStats counters (ref:
python/paddle/device/cuda/__init__.py:233 over
paddle/phi/core/memory/stats.h), program_memory_analysis over XLA's
per-executable breakdown, and the ZeRO-3 memory-scaling contract
(SURVEY §7 "memory parity" hard-part).
"""
import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.device as D

MB = 1024 * 1024


class TestLiveCounters:
    def test_alloc_free_peak_cycle(self):
        gc.collect()
        base = D.memory_allocated()
        t = paddle.to_tensor(np.zeros((512, 512), np.float32))
        a1 = D.memory_allocated()
        assert MB <= a1 - base < 1.5 * MB
        # eager op output goes through the apply_op funnel; the host
        # read flushes the lazy-eager fusion chain so its buffer exists
        u = t * 2.0
        u.numpy()
        a2 = D.memory_allocated()
        assert MB <= a2 - a1 < 1.5 * MB
        assert D.max_memory_allocated() >= a2
        del t, u
        gc.collect()
        a3 = D.memory_allocated()
        assert a3 <= base + 64 * 1024
        # peak survives the free
        assert D.max_memory_allocated() >= a2

    def test_reset_max(self):
        t = paddle.to_tensor(np.zeros((256, 256), np.float32))
        del t
        gc.collect()
        D.reset_max_memory_allocated()
        assert abs(D.max_memory_allocated() - D.memory_allocated()) \
            <= 64 * 1024
        D.reset_peak_memory_stats()  # alias

    def test_stats_dict_shape(self):
        st = D.memory_stats()
        for k in ("allocated.current", "allocated.peak",
                  "reserved.current", "reserved.peak"):
            assert k in st and st[k] >= 0
        # per-device query forms
        assert D.memory_allocated(0) >= 0
        assert D.memory_allocated("cpu:0") >= 0

    def test_raw_jnp_arrays_visible(self):
        """Arrays created outside the op funnel appear via the exact
        live scan fold-in."""
        gc.collect()
        base = D.memory_allocated()
        x = jnp.zeros((512, 512), jnp.float32)
        assert D.memory_allocated() - base >= MB
        del x

    def test_sharded_array_counts_per_device(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        gc.collect()
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        d3 = jax.devices()[3]
        base3 = D.memory_allocated(d3)
        big = jax.device_put(jnp.zeros((8 * 256, 1024)), sh)  # 8MB global
        got = D.memory_allocated(d3) - base3
        assert 0.9 * MB <= got <= 1.5 * MB  # 1/8th shard per device
        del big

    def test_cuda_shim(self):
        import paddle_tpu.device.cuda as C
        assert C.memory_allocated() >= 0
        assert C.max_memory_allocated() >= C.memory_allocated() - 64 * 1024
        assert C.device_count() == 0
        with pytest.raises(ValueError):
            C.get_device_properties()


class TestProgramMemory:
    def test_program_memory_analysis(self):
        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((256, 256))
        out = D.program_memory_analysis(f, x)
        assert out["argument_bytes"] == 256 * 256 * 4
        assert out["temp_bytes"] > 0
        assert out["peak_hbm"] >= out["argument_bytes"]

    def test_accepts_precompiled(self):
        f = jax.jit(lambda x: x * 2)
        c = f.lower(jnp.ones((16,))).compile()
        out = D.program_memory_analysis(c)
        assert out["argument_bytes"] == 64


class TestZeRO3MemoryScaling:
    """ZeRO-3's point is memory: per-device param+opt-state bytes must
    scale ~1/n_shard (ref: GroupShardedStage3 param slicing,
    fleet/meta_parallel/sharding/group_sharded_stage3.py:493)."""

    def _arg_bytes(self, n_shard):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = jax.devices()[:n_shard]
        mesh = Mesh(np.array(devs).reshape(n_shard), ("fsdp",))
        wsh = NamedSharding(mesh, P("fsdp", None))
        rep = NamedSharding(mesh, P())
        dsh = NamedSharding(mesh, P("fsdp", None))
        W = jax.device_put(jnp.zeros((1024, 256)), wsh)
        m = jax.device_put(jnp.zeros((1024, 256)), wsh)
        v = jax.device_put(jnp.zeros((1024, 256)), wsh)
        x = jax.device_put(jnp.zeros((n_shard * 4, 1024)), dsh)

        def step(W, m, v, x):
            def loss(W):
                return ((x @ W) ** 2).mean()
            g = jax.grad(loss)(W)
            m2 = 0.9 * m + 0.1 * g
            v2 = 0.99 * v + 0.01 * g * g
            return W - 1e-3 * m2 / (jnp.sqrt(v2) + 1e-8), m2, v2

        c = jax.jit(step, out_shardings=(wsh, wsh, wsh)).lower(
            W, m, v, x).compile()
        del rep
        return D.program_memory_analysis(c)["argument_bytes"]

    def test_opt_state_scales_inverse_nshard(self):
        b1 = self._arg_bytes(1)
        b8 = self._arg_bytes(8)
        # 3 big tensors (param + 2 moments) shard 8x; batch stays 1/8
        # per device too => close to exactly 1/8
        assert b8 * 6 < b1


class TestMemEstimator:
    """Static jaxpr-liveness peak estimator (the decision metric for
    memory-aware recompute; ref: auto_parallel_recompute.py's memory
    model over the static IR)."""

    def test_simple_chain_liveness(self):
        from paddle_tpu.distributed.auto_parallel.mem_estimator import (
            estimate_peak_bytes)

        def f(x):
            a = x * 2          # 4MB born
            b = a + 1          # 4MB born, a dies after
            return b.sum()

        x = jnp.zeros((1024, 1024), jnp.float32)  # 4MB
        peak = estimate_peak_bytes(jax.make_jaxpr(f)(x))
        # input (4MB) + at most two 4MB temporaries live at once
        assert 8 * MB <= peak <= 14 * MB, peak

    def test_remat_ranks_below_plain(self):
        from paddle_tpu.distributed.auto_parallel.mem_estimator import (
            estimate_peak_bytes)

        Ws = [jnp.zeros((256, 256), jnp.float32) for _ in range(8)]
        x = jnp.ones((4096, 256))

        def block(w, h):
            return jnp.tanh(h @ w)

        def loss_plain(ws):
            h = x
            for w in ws:
                h = block(w, h)
            return (h ** 2).mean()

        def loss_remat(ws):
            h = x
            for w in ws:
                h = jax.checkpoint(block)(w, h)
            return (h ** 2).mean()

        p = estimate_peak_bytes(jax.make_jaxpr(jax.grad(loss_plain))(Ws))
        r = estimate_peak_bytes(jax.make_jaxpr(jax.grad(loss_remat))(Ws))
        assert r < 0.8 * p, (r, p)
