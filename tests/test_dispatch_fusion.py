"""Lazy-eager elementwise fusion: equivalence, flush triggers, caching.

The fusion runtime (core/fusion.py) defers ops flagged ``fusable`` in
ops/ops.yaml into per-chain jitted executables. These tests pin the
contract:

* numerical equivalence fused vs. eager across every fusable op,
  forward AND gradient (via ``backward()``), under BOTH
  ``FLAGS_eager_fusion`` settings (the kill switch must restore the
  exact pre-fusion path);
* flush-trigger correctness — host read, non-fusable op boundary,
  in-place mutation, ``backward()``, chain-length cap;
* steady-state caching — a 12-op chain compiles at most once after
  warmup (≤1 new compile, the rest cache hits).
"""
import numpy as np
import pytest
import yaml

import paddle_tpu as paddle
from paddle_tpu.core import fusion
from paddle_tpu.core.flags import get_flags, set_flags

RNG = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _restore_fusion_flags():
    prev = get_flags(["FLAGS_eager_fusion", "FLAGS_eager_fusion_max_chain"])
    yield
    set_flags(prev)


def _fusable_names():
    d = yaml.safe_load(open("paddle_tpu/ops/ops.yaml"))["ops"]
    return sorted({o["name"] for o in d if o.get("fusable")})


FUSABLE = _fusable_names()

# input domains: (generator per positional tensor arg)
_POS = {"log", "log10", "log1p", "log2", "sqrt", "rsqrt", "lgamma",
        "digamma", "reciprocal"}
_UNIT = {"asin", "acos", "atanh", "erfinv"}
_GE1 = {"acosh"}
_BINARY = {"add", "subtract", "multiply", "divide", "maximum", "minimum",
           "fmax", "fmin", "atan2", "hypot", "logaddexp", "pow", "mod",
           "copysign"}


def _make_inputs(name):
    if name in _POS:
        arrs = [(RNG.random((3, 4)) + 0.5).astype(np.float32)]
    elif name in _UNIT:
        arrs = [(RNG.random((3, 4)) * 1.6 - 0.8).astype(np.float32)]
    elif name in _GE1:
        arrs = [(RNG.random((3, 4)) + 1.5).astype(np.float32)]
    elif name in _BINARY:
        arrs = [RNG.standard_normal((3, 4)).astype(np.float32),
                (RNG.random((3, 4)) + 0.5).astype(np.float32)]
    else:
        arrs = [RNG.standard_normal((3, 4)).astype(np.float32)]
    return arrs


def _run_chain(name, arrs, fused):
    """op under test embedded in a small fusable chain; returns
    (output ndarray, [input grad ndarrays])."""
    set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
    fn = getattr(paddle, name)
    ts = [paddle.to_tensor(a, stop_gradient=False) for a in arrs]
    z = fn(*ts)
    w = paddle.add(paddle.multiply(z, 0.5), 0.25)  # extend the chain
    if fused:
        assert w._lazy is not None, f"{name}: chain did not defer"
    else:
        assert w._lazy is None, f"{name}: kill switch did not disable"
    s = paddle.sum(w)  # non-fusable boundary + backward root
    s.backward()
    grads = [None if t.grad is None else t.grad.numpy() for t in ts]
    return w.numpy(), grads


@pytest.mark.parametrize("name", FUSABLE)
def test_fused_matches_eager(name):
    arrs = _make_inputs(name)
    out_f, g_f = _run_chain(name, [a.copy() for a in arrs], fused=True)
    out_e, g_e = _run_chain(name, [a.copy() for a in arrs], fused=False)
    np.testing.assert_allclose(out_f, out_e, rtol=1e-5, atol=1e-6,
                               err_msg=f"{name}: fused forward mismatch")
    assert len(g_f) == len(g_e)
    for i, (gf, ge) in enumerate(zip(g_f, g_e)):
        assert (gf is None) == (ge is None), (name, i)
        if gf is not None:
            np.testing.assert_allclose(
                gf, ge, rtol=1e-5, atol=1e-6,
                err_msg=f"{name}: fused grad mismatch (input {i})")


class TestFlushTriggers:
    def _chain(self, x, b):
        t = x
        for _ in range(3):
            t = paddle.multiply(t, b)
            t = paddle.add(t, 0.5)
        return t

    def test_host_read_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("host_read", 0)
        z.numpy()
        assert z._lazy is None
        assert fusion.stats()["flush_reasons"]["host_read"] == before + 1

    def test_non_fusable_boundary_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("op_boundary", 0)
        s = paddle.sum(z)  # reduction: not fusable
        assert z._lazy is None
        assert fusion.stats()["flush_reasons"]["op_boundary"] == before + 1
        assert s.numpy() == pytest.approx(float(np.sum(z.numpy())))

    def test_inplace_mutation_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        z[0, 0] = 99.0  # __setitem__ routes through the _data property
        assert z._lazy is None
        expect = np.array(self._chain(paddle.to_tensor(
            np.full((2, 3), 2.0, np.float32)),
            paddle.to_tensor(np.full((2, 3), 2.0, np.float32))).numpy())
        expect[0, 0] = 99.0
        np.testing.assert_allclose(z.numpy(), expect)

    def test_leaf_mutation_after_defer_uses_dispatch_value(self):
        """Mutating a LEAF after a dependent chain deferred must not
        change the chain's result: the flush computes from the
        dispatch-time buffer, exactly as the eager op would have."""
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.exp(x)      # deferred, reads x@dispatch
        x.zero_()              # rebinds x's buffer
        np.testing.assert_allclose(y.numpy(), np.e, rtol=1e-6)
        z = paddle.add(y, x)   # new chain sees the MUTATED x
        np.testing.assert_allclose(z.numpy(), np.e, rtol=1e-6)

    def test_detach_alias_keeps_grad_identity(self):
        """x and x.detach() share one buffer but are distinct grad
        leaves: the fused program must not merge their slots."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32([2.0]), stop_gradient=False)
            d = x.detach()
            y = paddle.multiply(d, x)  # detached alias FIRST
            paddle.sum(y).backward()
            return None if x.grad is None else float(x.grad.numpy())
        gf, ge = run(True), run(False)
        assert gf == ge == pytest.approx(2.0)

    def test_signed_zero_scalar_not_conflated(self):
        set_flags({"FLAGS_eager_fusion": 1})
        t = paddle.to_tensor(np.float32([3.0]))
        pos = paddle.copysign(t, 0.0)
        neg = paddle.copysign(t, -0.0)
        np.testing.assert_allclose(pos.numpy(), [3.0])
        np.testing.assert_allclose(neg.numpy(), [-3.0])

    def test_set_value_discards_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        z = paddle.add(x, 1.0)
        z.set_value(np.zeros((2, 2), np.float32))
        assert z._lazy is None
        np.testing.assert_allclose(z.numpy(), 0.0)

    def test_rebind_with_pending_consumer_not_reverted(self):
        """A direct _data rebind discards y's chain; a later flush of a
        consumer that captured y's expr must not resurrect the stale
        fused value into y, while the consumer itself still sees y's
        dispatch-time value (eager semantics)."""
        import jax.numpy as jnp
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.exp(x)                 # lazy
        z = paddle.add(y, 1.0)            # pending consumer of y's expr
        y._data = jnp.zeros((2, 2), jnp.float32)  # no-read rebind
        np.testing.assert_allclose(z.numpy(), np.e + 1.0, rtol=1e-6)
        np.testing.assert_allclose(y.numpy(), 0.0)  # user value kept

    def test_backward_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.7), stop_gradient=False)
        z = paddle.multiply(paddle.sin(x), paddle.cos(x))
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("backward", 0)
        z.backward()
        assert fusion.stats()["flush_reasons"]["backward"] == before + 1
        expect = float(np.cos(0.7) ** 2 - np.sin(0.7) ** 2)
        assert float(x.grad.numpy()) == pytest.approx(expect, rel=1e-5)

    def test_chain_cap_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_max_chain": 6})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        before = fusion.stats()["flush_reasons"].get("cap", 0)
        t = x
        for _ in range(10):
            t = paddle.add(t, 1.0)
        assert fusion.stats()["flush_reasons"].get("cap", 0) > before
        np.testing.assert_allclose(t.numpy(), 11.0)

    def test_lazy_shape_introspection_does_not_flush(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = paddle.add(x, 1.0)
        assert z._lazy is not None
        assert z.shape == [2, 3]
        assert z.ndim == 2 and z.size == 6
        assert z.dtype == np.float32
        assert len(z) == 2
        assert z._lazy is not None  # aval answered without materializing


class TestCaching:
    def test_12op_chain_steady_state_compiles_at_most_once(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32))

        def chain(t):
            for _ in range(4):
                t = paddle.multiply(t, b)
                t = paddle.add(t, b)
                t = paddle.subtract(t, 0.125)
            return t

        for _ in range(3):  # warmup
            chain(x).numpy()
        s0 = fusion.stats()
        for _ in range(10):
            chain(x).numpy()
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 10
        assert s1["cache_misses"] - s0["cache_misses"] <= 1, \
            "steady-state 12-op chain must hit the fusion cache"
        assert s1["cache_hits"] - s0["cache_hits"] >= 9
        # ops-per-chain histogram sees the 12-op chains
        assert s1["chain_length_hist"].get(12, 0) >= \
            s0["chain_length_hist"].get(12, 0) + 9

    def test_kill_switch_restores_eager_path(self):
        set_flags({"FLAGS_eager_fusion": 0})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        s0 = fusion.stats()["ops_deferred"]
        z = paddle.add(paddle.multiply(x, 2.0), 1.0)
        assert z._lazy is None  # executed immediately, pre-PR path
        assert fusion.stats()["ops_deferred"] == s0
        np.testing.assert_allclose(z.numpy(), 3.0)


class TestGradSemantics:
    def test_shared_subexpression_grads(self):
        """Diamond DAG: u feeds two consumers; grads accumulate once per
        path, exactly as the per-op tape would."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
            u = paddle.multiply(x, 2.0)
            a = paddle.add(u, 1.0)
            c = paddle.multiply(u, a)  # u used twice
            paddle.sum(c).backward()
            return float(c.numpy()), float(x.grad.numpy())
        cf, gf = run(True)
        ce, ge = run(False)
        assert cf == pytest.approx(ce, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)

    def test_partial_flush_then_continue(self):
        """Reading an intermediate mid-chain materializes it; the rest of
        the chain keeps building and grads still match eager."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.3), stop_gradient=False)
            u = paddle.sin(x)
            _ = u.numpy()  # mid-chain host read
            z = paddle.multiply(u, u)
            paddle.sum(z).backward()
            return float(z.numpy()), float(x.grad.numpy())
        zf, gf = run(True)
        ze, ge = run(False)
        assert zf == pytest.approx(ze, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)

    def test_no_grad_segment_blocks_gradient(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.4), stop_gradient=False)
            with paddle.no_grad():
                frozen = paddle.multiply(x, 3.0)
            z = paddle.add(paddle.multiply(x, 2.0), frozen)
            paddle.sum(z).backward()
            return float(z.numpy()), float(x.grad.numpy())
        zf, gf = run(True)
        ze, ge = run(False)
        assert zf == pytest.approx(ze, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)  # 2.0: no_grad leg cut

    def test_functional_grad_through_fused_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.9), stop_gradient=False)
        y = paddle.multiply(paddle.exp(x), 2.0)
        (g,) = paddle.grad(y, [x])
        assert float(g.numpy()) == pytest.approx(
            2.0 * float(np.exp(0.9)), rel=1e-5)

    def test_double_grad_through_fused_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.6), stop_gradient=False)
        y = paddle.multiply(paddle.sin(x), paddle.sin(x))
        (g,) = paddle.grad(y, [x], create_graph=True)
        (gg,) = paddle.grad(g, [x])
        # d2/dx2 sin^2 = 2 cos(2x)
        assert float(gg.numpy()) == pytest.approx(
            2.0 * float(np.cos(1.2)), rel=1e-4)

    def test_hook_on_lazy_intermediate(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
            u = paddle.multiply(x, 2.0)
            seen = []
            u.register_hook(lambda g: seen.append(float(g.numpy())))
            z = paddle.multiply(u, 4.0)
            paddle.sum(z).backward()
            return seen, float(x.grad.numpy())
        sf, gf = run(True)
        se, ge = run(False)
        assert sf == se == [4.0]
        assert gf == ge == pytest.approx(8.0)

    def test_live_intermediate_is_a_tape_edge(self):
        """A HELD requires-grad intermediate must stay inspectable after
        the chain flushes: functional grad, post-hoc retain_grads, and
        post-hoc hooks all behave exactly as eager."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.ones(3, np.float32),
                                 stop_gradient=False)
            y = paddle.multiply(x, 2.0)   # held intermediate
            z = paddle.multiply(y, 3.0)
            loss = paddle.sum(z)          # flush boundary
            (gy,) = paddle.grad(loss, [y], retain_graph=True)
            return None if gy is None else gy.numpy().tolist()
        assert run(True) == run(False) == [3.0, 3.0, 3.0]

    def test_posthoc_retain_grads_and_hook(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.ones(2, np.float32),
                                 stop_gradient=False)
            y = paddle.multiply(x, 2.0)
            z = paddle.multiply(y, 3.0)
            loss = paddle.sum(z)          # chain flushed here
            seen = []
            y.retain_grads()              # AFTER the flush
            y.register_hook(lambda g: seen.append(g.numpy().tolist()))
            loss.backward()
            yg = None if y.grad is None else y.grad.numpy().tolist()
            return yg, seen
        assert run(True) == run(False) == ([3.0, 3.0], [[3.0, 3.0]])

    def test_fused_node_appears_on_tape(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        z = paddle.add(paddle.multiply(x, 3.0), 1.0)
        z.numpy()
        assert z._node is not None and z._node.name == "fused_chain"
        assert not z.stop_gradient


def test_stats_surface_shape():
    s = fusion.stats()
    for key in ("ops_deferred", "chains_flushed", "ops_fused",
                "cache_hits", "cache_misses", "flush_reasons",
                "chain_length_hist", "cache_size", "avg_ops_per_chain"):
        assert key in s
