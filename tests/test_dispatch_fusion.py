"""Lazy-eager fusion: equivalence, flush triggers, caching, reduction
terminators, matmul epilogues.

The fusion runtime (core/fusion.py) defers ops flagged ``fusable`` in
ops/ops.yaml into per-chain jitted executables. These tests pin the
contract:

* numerical equivalence fused vs. eager across every fusable op,
  forward AND gradient (via ``backward()``), under BOTH
  ``FLAGS_eager_fusion`` settings (the kill switch must restore the
  exact pre-fusion path);
* flush-trigger correctness — host read, non-fusable op boundary,
  in-place mutation, ``backward()``, chain-length cap;
* steady-state caching — a 12-op chain compiles at most once after
  warmup (≤1 new compile, the rest cache hits);
* reduction terminators (``fusable: reduce``) — fwd+grad equivalence
  for every marked op across f32/bf16 and axis/keepdim variants, flush
  reason taxonomy (``reduce_boundary``), steady-state ≤1-compile for a
  reduction-terminated chain;
* matmul/linear epilogues (``fusable: epilogue``) — the contraction is
  re-captured into the chain's program; held requires-grad handles stay
  real tape edges.
"""
import numpy as np
import pytest
import yaml

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import fusion
from paddle_tpu.core.flags import get_flags, set_flags

RNG = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _restore_fusion_flags():
    prev = get_flags(["FLAGS_eager_fusion", "FLAGS_eager_fusion_max_chain",
                      "FLAGS_eager_fusion_reduce",
                      "FLAGS_eager_fusion_epilogue"])
    yield
    set_flags(prev)


def _names_by_class(cls):
    d = yaml.safe_load(open("paddle_tpu/ops/ops.yaml"))["ops"]
    return sorted({o["name"] for o in d if o.get("fusable") == cls})


FUSABLE = [n for n in _names_by_class(True)  # elementwise chain members
           if n != "cast"]                   # (cast: dedicated test below)
REDUCE_OPS = _names_by_class("reduce")       # terminator ops
EPILOGUE_OPS = _names_by_class("epilogue")

# input domains: (generator per positional tensor arg)
_POS = {"log", "log10", "log1p", "log2", "sqrt", "rsqrt", "lgamma",
        "digamma", "reciprocal"}
_UNIT = {"asin", "acos", "atanh", "erfinv"}
_GE1 = {"acosh"}
_BINARY = {"add", "subtract", "multiply", "divide", "maximum", "minimum",
           "fmax", "fmin", "atan2", "hypot", "logaddexp", "pow", "mod",
           "copysign"}


def _make_inputs(name):
    if name in _POS:
        arrs = [(RNG.random((3, 4)) + 0.5).astype(np.float32)]
    elif name in _UNIT:
        arrs = [(RNG.random((3, 4)) * 1.6 - 0.8).astype(np.float32)]
    elif name in _GE1:
        arrs = [(RNG.random((3, 4)) + 1.5).astype(np.float32)]
    elif name in _BINARY:
        arrs = [RNG.standard_normal((3, 4)).astype(np.float32),
                (RNG.random((3, 4)) + 0.5).astype(np.float32)]
    else:
        arrs = [RNG.standard_normal((3, 4)).astype(np.float32)]
    return arrs


def _run_chain(name, arrs, fused):
    """op under test embedded in a small fusable chain; returns
    (output ndarray, [input grad ndarrays])."""
    set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
    fn = getattr(paddle, name, None) or getattr(F, name)
    ts = [paddle.to_tensor(a, stop_gradient=False) for a in arrs]
    z = fn(*ts)
    w = paddle.add(paddle.multiply(z, 0.5), 0.25)  # extend the chain
    if fused:
        assert w._lazy is not None, f"{name}: chain did not defer"
    else:
        assert w._lazy is None, f"{name}: kill switch did not disable"
    s = paddle.sum(w)  # non-fusable boundary + backward root
    s.backward()
    grads = [None if t.grad is None else t.grad.numpy() for t in ts]
    return w.numpy(), grads


@pytest.mark.parametrize("name", FUSABLE)
def test_fused_matches_eager(name):
    arrs = _make_inputs(name)
    out_f, g_f = _run_chain(name, [a.copy() for a in arrs], fused=True)
    out_e, g_e = _run_chain(name, [a.copy() for a in arrs], fused=False)
    np.testing.assert_allclose(out_f, out_e, rtol=1e-5, atol=1e-6,
                               err_msg=f"{name}: fused forward mismatch")
    assert len(g_f) == len(g_e)
    for i, (gf, ge) in enumerate(zip(g_f, g_e)):
        assert (gf is None) == (ge is None), (name, i)
        if gf is not None:
            np.testing.assert_allclose(
                gf, ge, rtol=1e-5, atol=1e-6,
                err_msg=f"{name}: fused grad mismatch (input {i})")


class TestFlushTriggers:
    def _chain(self, x, b):
        t = x
        for _ in range(3):
            t = paddle.multiply(t, b)
            t = paddle.add(t, 0.5)
        return t

    def test_host_read_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("host_read", 0)
        z.numpy()
        assert z._lazy is None
        assert fusion.stats()["flush_reasons"]["host_read"] == before + 1

    def test_non_fusable_boundary_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("op_boundary", 0)
        s = paddle.cumsum(z)  # scan: not fusable in any class
        assert z._lazy is None
        assert fusion.stats()["flush_reasons"]["op_boundary"] == before + 1
        np.testing.assert_allclose(
            s.numpy(), np.cumsum(z.numpy().reshape(-1)), rtol=1e-6)

    def test_reduction_is_not_a_boundary(self):
        """Since Fusion II a `fusable: reduce` op joins the DAG as a
        terminator instead of flushing its input chain at dispatch."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        s = paddle.sum(z)
        assert z._lazy is not None  # chain still pending
        assert s._lazy is not None  # terminator joined it
        assert s.numpy() == pytest.approx(float(np.sum(z.numpy())))

    def test_inplace_mutation_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        z = self._chain(x, x)
        assert z._lazy is not None
        z[0, 0] = 99.0  # __setitem__ routes through the _data property
        assert z._lazy is None
        expect = np.array(self._chain(paddle.to_tensor(
            np.full((2, 3), 2.0, np.float32)),
            paddle.to_tensor(np.full((2, 3), 2.0, np.float32))).numpy())
        expect[0, 0] = 99.0
        np.testing.assert_allclose(z.numpy(), expect)

    def test_leaf_mutation_after_defer_uses_dispatch_value(self):
        """Mutating a LEAF after a dependent chain deferred must not
        change the chain's result: the flush computes from the
        dispatch-time buffer, exactly as the eager op would have."""
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.exp(x)      # deferred, reads x@dispatch
        x.zero_()              # rebinds x's buffer
        np.testing.assert_allclose(y.numpy(), np.e, rtol=1e-6)
        z = paddle.add(y, x)   # new chain sees the MUTATED x
        np.testing.assert_allclose(z.numpy(), np.e, rtol=1e-6)

    def test_detach_alias_keeps_grad_identity(self):
        """x and x.detach() share one buffer but are distinct grad
        leaves: the fused program must not merge their slots."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32([2.0]), stop_gradient=False)
            d = x.detach()
            y = paddle.multiply(d, x)  # detached alias FIRST
            paddle.sum(y).backward()
            return None if x.grad is None else float(x.grad.numpy())
        gf, ge = run(True), run(False)
        assert gf == ge == pytest.approx(2.0)

    def test_signed_zero_scalar_not_conflated(self):
        set_flags({"FLAGS_eager_fusion": 1})
        t = paddle.to_tensor(np.float32([3.0]))
        pos = paddle.copysign(t, 0.0)
        neg = paddle.copysign(t, -0.0)
        np.testing.assert_allclose(pos.numpy(), [3.0])
        np.testing.assert_allclose(neg.numpy(), [-3.0])

    def test_set_value_discards_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        z = paddle.add(x, 1.0)
        z.set_value(np.zeros((2, 2), np.float32))
        assert z._lazy is None
        np.testing.assert_allclose(z.numpy(), 0.0)

    def test_rebind_with_pending_consumer_not_reverted(self):
        """A direct _data rebind discards y's chain; a later flush of a
        consumer that captured y's expr must not resurrect the stale
        fused value into y, while the consumer itself still sees y's
        dispatch-time value (eager semantics)."""
        import jax.numpy as jnp
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        y = paddle.exp(x)                 # lazy
        z = paddle.add(y, 1.0)            # pending consumer of y's expr
        y._data = jnp.zeros((2, 2), jnp.float32)  # no-read rebind
        np.testing.assert_allclose(z.numpy(), np.e + 1.0, rtol=1e-6)
        np.testing.assert_allclose(y.numpy(), 0.0)  # user value kept

    def test_backward_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.7), stop_gradient=False)
        z = paddle.multiply(paddle.sin(x), paddle.cos(x))
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("backward", 0)
        z.backward()
        assert fusion.stats()["flush_reasons"]["backward"] == before + 1
        expect = float(np.cos(0.7) ** 2 - np.sin(0.7) ** 2)
        assert float(x.grad.numpy()) == pytest.approx(expect, rel=1e-5)

    def test_chain_cap_flushes(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_max_chain": 6})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        before = fusion.stats()["flush_reasons"].get("cap", 0)
        t = x
        for _ in range(10):
            t = paddle.add(t, 1.0)
        assert fusion.stats()["flush_reasons"].get("cap", 0) > before
        np.testing.assert_allclose(t.numpy(), 11.0)

    def test_lazy_shape_introspection_does_not_flush(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = paddle.add(x, 1.0)
        assert z._lazy is not None
        assert z.shape == [2, 3]
        assert z.ndim == 2 and z.size == 6
        assert z.dtype == np.float32
        assert len(z) == 2
        assert z._lazy is not None  # aval answered without materializing


class TestCaching:
    def test_12op_chain_steady_state_compiles_at_most_once(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32))

        def chain(t):
            for _ in range(4):
                t = paddle.multiply(t, b)
                t = paddle.add(t, b)
                t = paddle.subtract(t, 0.125)
            return t

        for _ in range(3):  # warmup
            chain(x).numpy()
        s0 = fusion.stats()
        for _ in range(10):
            chain(x).numpy()
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 10
        assert s1["cache_misses"] - s0["cache_misses"] <= 1, \
            "steady-state 12-op chain must hit the fusion cache"
        assert s1["cache_hits"] - s0["cache_hits"] >= 9
        # ops-per-chain histogram sees the 12-op chains
        assert s1["chain_length_hist"].get(12, 0) >= \
            s0["chain_length_hist"].get(12, 0) + 9

    def test_kill_switch_restores_eager_path(self):
        set_flags({"FLAGS_eager_fusion": 0})
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        s0 = fusion.stats()["ops_deferred"]
        z = paddle.add(paddle.multiply(x, 2.0), 1.0)
        assert z._lazy is None  # executed immediately, pre-PR path
        assert fusion.stats()["ops_deferred"] == s0
        np.testing.assert_allclose(z.numpy(), 3.0)


class TestGradSemantics:
    def test_shared_subexpression_grads(self):
        """Diamond DAG: u feeds two consumers; grads accumulate once per
        path, exactly as the per-op tape would."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
            u = paddle.multiply(x, 2.0)
            a = paddle.add(u, 1.0)
            c = paddle.multiply(u, a)  # u used twice
            paddle.sum(c).backward()
            return float(c.numpy()), float(x.grad.numpy())
        cf, gf = run(True)
        ce, ge = run(False)
        assert cf == pytest.approx(ce, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)

    def test_partial_flush_then_continue(self):
        """Reading an intermediate mid-chain materializes it; the rest of
        the chain keeps building and grads still match eager."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.3), stop_gradient=False)
            u = paddle.sin(x)
            _ = u.numpy()  # mid-chain host read
            z = paddle.multiply(u, u)
            paddle.sum(z).backward()
            return float(z.numpy()), float(x.grad.numpy())
        zf, gf = run(True)
        ze, ge = run(False)
        assert zf == pytest.approx(ze, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)

    def test_no_grad_segment_blocks_gradient(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(0.4), stop_gradient=False)
            with paddle.no_grad():
                frozen = paddle.multiply(x, 3.0)
            z = paddle.add(paddle.multiply(x, 2.0), frozen)
            paddle.sum(z).backward()
            return float(z.numpy()), float(x.grad.numpy())
        zf, gf = run(True)
        ze, ge = run(False)
        assert zf == pytest.approx(ze, rel=1e-6)
        assert gf == pytest.approx(ge, rel=1e-6)  # 2.0: no_grad leg cut

    def test_functional_grad_through_fused_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.9), stop_gradient=False)
        y = paddle.multiply(paddle.exp(x), 2.0)
        (g,) = paddle.grad(y, [x])
        assert float(g.numpy()) == pytest.approx(
            2.0 * float(np.exp(0.9)), rel=1e-5)

    def test_double_grad_through_fused_chain(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(0.6), stop_gradient=False)
        y = paddle.multiply(paddle.sin(x), paddle.sin(x))
        (g,) = paddle.grad(y, [x], create_graph=True)
        (gg,) = paddle.grad(g, [x])
        # d2/dx2 sin^2 = 2 cos(2x)
        assert float(gg.numpy()) == pytest.approx(
            2.0 * float(np.cos(1.2)), rel=1e-4)

    def test_hook_on_lazy_intermediate(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
            u = paddle.multiply(x, 2.0)
            seen = []
            u.register_hook(lambda g: seen.append(float(g.numpy())))
            z = paddle.multiply(u, 4.0)
            paddle.sum(z).backward()
            return seen, float(x.grad.numpy())
        sf, gf = run(True)
        se, ge = run(False)
        assert sf == se == [4.0]
        assert gf == ge == pytest.approx(8.0)

    def test_live_intermediate_is_a_tape_edge(self):
        """A HELD requires-grad intermediate must stay inspectable after
        the chain flushes: functional grad, post-hoc retain_grads, and
        post-hoc hooks all behave exactly as eager."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.ones(3, np.float32),
                                 stop_gradient=False)
            y = paddle.multiply(x, 2.0)   # held intermediate
            z = paddle.multiply(y, 3.0)
            loss = paddle.sum(z)          # flush boundary
            (gy,) = paddle.grad(loss, [y], retain_graph=True)
            return None if gy is None else gy.numpy().tolist()
        assert run(True) == run(False) == [3.0, 3.0, 3.0]

    def test_posthoc_retain_grads_and_hook(self):
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
            x = paddle.to_tensor(np.ones(2, np.float32),
                                 stop_gradient=False)
            y = paddle.multiply(x, 2.0)
            z = paddle.multiply(y, 3.0)
            loss = paddle.sum(z)          # chain flushed here
            seen = []
            y.retain_grads()              # AFTER the flush
            y.register_hook(lambda g: seen.append(g.numpy().tolist()))
            loss.backward()
            yg = None if y.grad is None else y.grad.numpy().tolist()
            return yg, seen
        assert run(True) == run(False) == ([3.0, 3.0], [[3.0, 3.0]])

    def test_fused_node_appears_on_tape(self):
        set_flags({"FLAGS_eager_fusion": 1})
        x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        z = paddle.add(paddle.multiply(x, 3.0), 1.0)
        z.numpy()
        assert z._node is not None and z._node.name == "fused_chain"
        assert not z.stop_gradient


# ---------------------------------------------------------------------------
# reduction terminators (fusable: reduce)
# ---------------------------------------------------------------------------

# axis/keepdim variants per reduce op; squared_l2_norm is a fixed full
# reduction (no axis surface by contract)
_REDUCE_VARIANTS = [
    {}, {"axis": 0}, {"axis": 1}, {"axis": 1, "keepdim": True},
    {"axis": [0, 1]},
]


def _reduce_cases():
    for name in REDUCE_OPS:
        variants = [{}] if name == "squared_l2_norm" else _REDUCE_VARIANTS
        for v in variants:
            for dt in ("float32", "bfloat16"):
                yield name, v, dt


def _run_reduce_chain(name, kw, dtype, arr, fused):
    """op under test terminating a fusable chain; returns
    (output ndarray f32, input grad ndarray f32)."""
    set_flags({"FLAGS_eager_fusion": 1 if fused else 0,
               "FLAGS_eager_fusion_reduce": 1})
    # leaf constructed IN dtype (a cast op would make x a non-leaf and
    # backward() would not populate x.grad)
    x = paddle.to_tensor(arr, dtype=dtype, stop_gradient=False)
    z = paddle.add(paddle.multiply(x, 0.5), 0.25)  # producer chain
    r = getattr(paddle, name)(z, **kw)
    if fused:
        assert r._lazy is not None, f"{name}{kw}: did not defer"
    else:
        assert r._lazy is None, f"{name}{kw}: kill switch did not disable"
    loss = paddle.sum(r)
    loss.backward()
    return (r.astype("float32").numpy(),
            x.grad.astype("float32").numpy())


@pytest.mark.parametrize("name,kw,dtype",
                         list(_reduce_cases()),
                         ids=lambda v: str(v).replace(" ", ""))
def test_reduce_terminator_matches_eager(name, kw, dtype):
    # spread > 0.3 avoids ties (max/min subgradient routing) and keeps
    # prod away from 0; bf16 compares at its ~2^-8 resolution
    arr = (RNG.random((3, 4)) * 1.5 + 0.3).astype(np.float32)
    out_f, g_f = _run_reduce_chain(name, kw, dtype, arr.copy(), fused=True)
    out_e, g_e = _run_reduce_chain(name, kw, dtype, arr.copy(), fused=False)
    tol = dict(rtol=1e-5, atol=1e-6) if dtype == "float32" else \
        dict(rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(out_f, out_e, err_msg=f"{name}{kw} fwd",
                               **tol)
    np.testing.assert_allclose(g_f, g_e, err_msg=f"{name}{kw} grad", **tol)


class TestReductionTerminators:
    def test_one_program_no_intermediate(self):
        """mean((x*y+z)**2) runs as ONE fused executable: a single chain
        flush covering all 4 ops, counted as a fused reduction."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        x = paddle.to_tensor(RNG.standard_normal((4, 5)).astype(np.float32))
        y = paddle.to_tensor(RNG.standard_normal((4, 5)).astype(np.float32))
        z = paddle.to_tensor(RNG.standard_normal((4, 5)).astype(np.float32))
        s0 = fusion.stats()
        r = paddle.mean(paddle.square(
            paddle.add(paddle.multiply(x, y), z)))
        assert r._lazy is not None
        got = float(r.numpy())
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 1
        assert s1["ops_fused"] - s0["ops_fused"] == 4
        assert s1["reductions_fused"] - s0["reductions_fused"] == 1
        ref = float(np.mean((x.numpy() * y.numpy() + z.numpy()) ** 2))
        assert got == pytest.approx(ref, rel=1e-5)

    def test_chain_continues_past_terminator(self):
        """Fusable consumers keep chaining past a reduce node — the
        softmax pattern fuses into one program (the held non-rg
        intermediate `e` just becomes a second output of it)."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        xn = RNG.standard_normal((4, 6)).astype(np.float32)
        x = paddle.to_tensor(xn)
        e = paddle.exp(paddle.subtract(
            x, paddle.max(x, axis=1, keepdim=True)))
        sm = paddle.divide(e, paddle.sum(e, axis=1, keepdim=True))
        assert sm._lazy is not None
        s0 = fusion.stats()
        out = sm.numpy()
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 1
        ref = np.exp(xn - xn.max(1, keepdims=True))
        ref /= ref.sum(1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_softmax_grad_matches_eager(self):
        """Grad-mode softmax: a HELD requires-grad intermediate cuts the
        chain into tape-edge programs, and grads still match eager."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0,
                       "FLAGS_eager_fusion_reduce": 1})
            xn = np.float32([[0.3, -1.2, 0.8], [2.0, 0.1, -0.4]])
            x = paddle.to_tensor(xn, stop_gradient=False)
            e = paddle.exp(paddle.subtract(
                x, paddle.max(x, axis=1, keepdim=True)))
            sm = paddle.divide(e, paddle.sum(e, axis=1, keepdim=True))
            paddle.sum(paddle.multiply(sm, sm)).backward()
            return sm.numpy(), x.grad.numpy()
        of, gf = run(True)
        oe, ge = run(False)
        np.testing.assert_allclose(of, oe, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(gf, ge, rtol=1e-5, atol=1e-7)

    def test_axis_and_keepdim_key_the_cache(self):
        """Two flushes differing only in reduce attrs must be distinct
        programs (the attrs are folded into the structural key)."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        arr = RNG.standard_normal((3, 5)).astype(np.float32)
        x = paddle.to_tensor(arr)
        a = paddle.sum(paddle.multiply(x, 2.0), axis=0)
        b = paddle.sum(paddle.multiply(x, 2.0), axis=1)
        np.testing.assert_allclose(a.numpy(), (arr * 2).sum(0), rtol=1e-5)
        np.testing.assert_allclose(b.numpy(), (arr * 2).sum(1), rtol=1e-5)
        k = paddle.sum(paddle.multiply(x, 2.0), axis=1, keepdim=True)
        assert k.numpy().shape == (3, 1)

    def test_reduction_terminated_chain_single_compile(self):
        """Steady state: an 8-op chain + mean terminator is ONE cached
        executable — ≤1 compile after warmup, 100% hits."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        x = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(
            RNG.standard_normal((8, 8)).astype(np.float32))

        def loss(t):
            for _ in range(4):
                t = paddle.multiply(t, b)
                t = paddle.add(t, 0.125)
            return paddle.mean(paddle.square(t))

        for _ in range(3):  # warmup (sighting + compile)
            float(loss(x).numpy())
        s0 = fusion.stats()
        for _ in range(10):
            float(loss(x).numpy())
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 10
        assert s1["cache_misses"] - s0["cache_misses"] <= 1, \
            "steady-state reduction-terminated chain must hit the cache"
        assert s1["cache_hits"] - s0["cache_hits"] >= 9
        assert s1["reductions_fused"] - s0["reductions_fused"] == 10
        assert s1["chain_length_hist"].get(10, 0) >= \
            s0["chain_length_hist"].get(10, 0) + 9

    def test_reduce_flag_off_restores_boundary(self):
        """FLAGS_eager_fusion_reduce=0: the reduction flushes its input
        chain at dispatch again, labeled reduce_boundary."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 0})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        z = paddle.add(paddle.multiply(x, 2.0), 1.0)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("reduce_boundary", 0)
        s = paddle.sum(z)
        assert z._lazy is None and s._lazy is None
        assert fusion.stats()["flush_reasons"]["reduce_boundary"] == \
            before + 1
        assert float(s.numpy()) == pytest.approx(18.0)

    def test_functional_grad_through_terminator(self):
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        x = paddle.to_tensor(np.float32([1.0, 2.0, 3.0]),
                             stop_gradient=False)
        loss = paddle.mean(paddle.square(x))
        (g,) = paddle.grad(loss, [x])
        np.testing.assert_allclose(g.numpy(), 2 * x.numpy() / 3, rtol=1e-6)

    def test_sum_dtype_attr(self):
        """The dtype attr participates in the program key and output."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        r = paddle.sum(paddle.multiply(x, 1.0), dtype="float32")
        assert r._lazy is not None
        assert r.dtype == np.float32
        assert float(r.numpy()) == pytest.approx(6.0)

    def test_squared_l2_norm(self):
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
        arr = RNG.standard_normal((4, 4)).astype(np.float32)
        x = paddle.to_tensor(arr, stop_gradient=False)
        n = paddle.squared_l2_norm(paddle.multiply(x, 0.5))
        assert n._lazy is not None
        n.backward()
        assert float(n.numpy()) == pytest.approx(
            float(np.sum((arr * 0.5) ** 2)), rel=1e-5)
        # d/dx sum((0.5 x)^2) = 2 * 0.5x * 0.5 = 0.5 x
        np.testing.assert_allclose(x.grad.numpy(), arr * 0.5, rtol=1e-5)


# ---------------------------------------------------------------------------
# matmul / linear epilogues (fusable: epilogue)
# ---------------------------------------------------------------------------

def _run_epilogue(build, fused, *arrs):
    set_flags({"FLAGS_eager_fusion": 1 if fused else 0,
               "FLAGS_eager_fusion_epilogue": 1})
    ts = [paddle.to_tensor(a, stop_gradient=False) for a in arrs]
    out = build(*ts)
    if fused:
        assert out._lazy is not None, "epilogue chain did not defer"
    paddle.sum(out).backward()
    return (out.astype("float32").numpy(),
            [t.grad.astype("float32").numpy() for t in ts])


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_matmul_bias_act_epilogue_matches_eager(dtype):
    x = RNG.standard_normal((4, 6)).astype(np.float32)
    w = RNG.standard_normal((6, 3)).astype(np.float32)
    b = RNG.standard_normal((3,)).astype(np.float32)

    def build(xt, wt, bt):
        mm = paddle.matmul(xt.astype(dtype), wt.astype(dtype))
        return paddle.tanh(paddle.add(mm, bt.astype(dtype)))

    out_f, g_f = _run_epilogue(build, True, x, w, b)
    out_e, g_e = _run_epilogue(build, False, x, w, b)
    tol = dict(rtol=1e-5, atol=1e-6) if dtype == "float32" else \
        dict(rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(out_f, out_e, **tol)
    for gf, ge in zip(g_f, g_e):
        np.testing.assert_allclose(gf, ge, **tol)


@pytest.mark.parametrize("tx,ty", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_matmul_transpose_attrs(tx, ty):
    """Transpose flags ride as node attrs in the fused program key."""
    a = RNG.standard_normal((5, 4)).astype(np.float32)
    b = RNG.standard_normal((4, 3)).astype(np.float32)
    a_in = np.ascontiguousarray(a.T) if tx else a
    b_in = np.ascontiguousarray(b.T) if ty else b

    def build(at, bt):
        return paddle.add(
            paddle.matmul(at, bt, transpose_x=tx, transpose_y=ty), 0.5)

    out_f, g_f = _run_epilogue(build, True, a_in, b_in)
    out_e, g_e = _run_epilogue(build, False, a_in, b_in)
    np.testing.assert_allclose(out_f, a @ b + 0.5, rtol=1e-5)
    np.testing.assert_allclose(out_f, out_e, rtol=1e-5, atol=1e-6)
    for gf, ge in zip(g_f, g_e):
        np.testing.assert_allclose(gf, ge, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("with_bias", [True, False])
def test_linear_epilogue_matches_eager(with_bias):
    x = RNG.standard_normal((2, 6)).astype(np.float32)
    w = RNG.standard_normal((6, 4)).astype(np.float32)
    b = RNG.standard_normal((4,)).astype(np.float32)

    if with_bias:
        def build(xt, wt, bt):
            return F.relu(F.linear(xt, wt, bt))
        args = (x, w, b)
    else:
        def build(xt, wt):
            return F.relu(F.linear(xt, wt))
        args = (x, w)

    out_f, g_f = _run_epilogue(build, True, *args)
    out_e, g_e = _run_epilogue(build, False, *args)
    np.testing.assert_allclose(out_f, out_e, rtol=1e-5, atol=1e-6)
    for gf, ge in zip(g_f, g_e):
        np.testing.assert_allclose(gf, ge, rtol=1e-5, atol=1e-6)


def test_cast_fuses_into_epilogue_chain():
    """cast (parametric elementwise: target dtype in the program key)
    rides the chain — act(x@w+b).astype(bf16) is still ONE program."""
    set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_epilogue": 1})
    x = paddle.to_tensor(RNG.standard_normal((4, 6)).astype(np.float32))
    w = paddle.to_tensor(RNG.standard_normal((6, 3)).astype(np.float32))
    b = paddle.to_tensor(RNG.standard_normal((3,)).astype(np.float32))
    s0 = fusion.stats()
    out = paddle.tanh(paddle.add(paddle.matmul(x, w), b)).astype("bfloat16")
    assert out._lazy is not None
    assert out.dtype == np.dtype("bfloat16")  # aval answers lazily
    got = out.astype("float32").numpy()
    s1 = fusion.stats()
    # matmul + add + tanh + cast + the read-back cast: one flush
    assert s1["chains_flushed"] - s0["chains_flushed"] == 1
    assert s1["ops_fused"] - s0["ops_fused"] == 5
    ref = np.tanh(x.numpy() @ w.numpy() + b.numpy())
    np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)


def test_cast_grad_matches_eager():
    def run(fused):
        set_flags({"FLAGS_eager_fusion": 1 if fused else 0})
        x = paddle.to_tensor(np.float32([1.5, -2.0]), stop_gradient=False)
        y = paddle.multiply(x, 2.0).astype("float16")
        assert (y._lazy is not None) == bool(fused)
        paddle.sum(y).backward()
        return y.astype("float32").numpy(), x.grad.numpy()
    of, gf = run(True)
    oe, ge = run(False)
    np.testing.assert_allclose(of, oe, rtol=1e-6)
    np.testing.assert_allclose(gf, ge, rtol=1e-6)


@pytest.mark.parametrize("approximate", [False, True])
def test_gelu_epilogue(approximate):
    """gelu's `approximate` flag is a node attr — both variants fuse as
    distinct programs and match their eager results."""
    x = RNG.standard_normal((3, 5)).astype(np.float32)
    w = RNG.standard_normal((5, 2)).astype(np.float32)

    def build(xt, wt):
        return F.gelu(paddle.matmul(xt, wt), approximate=approximate)

    out_f, g_f = _run_epilogue(build, True, x, w)
    out_e, g_e = _run_epilogue(build, False, x, w)
    np.testing.assert_allclose(out_f, out_e, rtol=1e-5, atol=1e-6)
    for gf, ge in zip(g_f, g_e):
        np.testing.assert_allclose(gf, ge, rtol=1e-5, atol=1e-6)


class TestMatmulEpilogue:
    def test_epilogue_counter_and_single_program(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_epilogue": 1})
        x = paddle.to_tensor(RNG.standard_normal((4, 6)).astype(np.float32))
        w = paddle.to_tensor(RNG.standard_normal((6, 3)).astype(np.float32))
        b = paddle.to_tensor(RNG.standard_normal((3,)).astype(np.float32))
        s0 = fusion.stats()
        out = paddle.tanh(paddle.add(paddle.matmul(x, w), b))
        out.numpy()
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 1
        assert s1["epilogues_fused"] - s0["epilogues_fused"] == 1
        assert s1["ops_fused"] - s0["ops_fused"] == 3

    def test_lone_matmul_not_counted_as_epilogue(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_epilogue": 1})
        x = paddle.to_tensor(RNG.standard_normal((4, 6)).astype(np.float32))
        w = paddle.to_tensor(RNG.standard_normal((6, 3)).astype(np.float32))
        s0 = fusion.stats()
        r = paddle.matmul(x, w)
        assert r._lazy is not None
        got = r.numpy()
        s1 = fusion.stats()
        assert s1["epilogues_fused"] - s0["epilogues_fused"] == 0
        np.testing.assert_allclose(got, x.numpy() @ w.numpy(), rtol=1e-5)

    def test_epilogue_flag_off_keeps_matmul_eager(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_epilogue": 0})
        x = paddle.to_tensor(RNG.standard_normal((4, 6)).astype(np.float32))
        w = paddle.to_tensor(RNG.standard_normal((6, 3)).astype(np.float32))
        r = paddle.matmul(x, w)
        assert r._lazy is None  # dispatched eagerly, pre-Fusion-II path

    def test_matmul_boundary_reason(self):
        """With the epilogue flag off, a contraction consuming a pending
        chain flushes it labeled matmul_boundary."""
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_epilogue": 0})
        x = paddle.to_tensor(np.ones((4, 6), np.float32))
        w = paddle.to_tensor(np.ones((6, 3), np.float32))
        z = paddle.add(paddle.multiply(x, 2.0), 1.0)
        assert z._lazy is not None
        before = fusion.stats()["flush_reasons"].get("matmul_boundary", 0)
        r = paddle.matmul(z, w)
        assert z._lazy is None
        assert fusion.stats()["flush_reasons"]["matmul_boundary"] == \
            before + 1
        np.testing.assert_allclose(r.numpy(), np.full((4, 3), 18.0),
                                   rtol=1e-6)

    def test_held_requires_grad_matmul_stays_tape_edge(self):
        """A live requires-grad matmul handle cuts the chain (its own
        GradNode) — the epilogue never swallows a contraction another
        consumer may inspect. Matches eager exactly."""
        def run(fused):
            set_flags({"FLAGS_eager_fusion": 1 if fused else 0,
                       "FLAGS_eager_fusion_epilogue": 1})
            x = paddle.to_tensor(np.float32([[1.0, 2.0]]),
                                 stop_gradient=False)
            w = paddle.to_tensor(np.float32([[3.0], [4.0]]),
                                 stop_gradient=False)
            t = paddle.matmul(x, w)        # held handle
            y = paddle.multiply(t, 2.0)
            loss = paddle.sum(y)
            (gt,) = paddle.grad(loss, [t], retain_graph=True)
            return t.item(), gt.item()
        assert run(True) == run(False) == (11.0, 2.0)

    def test_epilogue_steady_state_single_compile(self):
        set_flags({"FLAGS_eager_fusion": 1,
                   "FLAGS_eager_fusion_epilogue": 1})
        x = paddle.to_tensor(RNG.standard_normal((16, 16))
                             .astype(np.float32), stop_gradient=False)
        w = paddle.to_tensor(RNG.standard_normal((16, 16))
                             .astype(np.float32))
        b = paddle.to_tensor(RNG.standard_normal((16,)).astype(np.float32))

        def step():
            return paddle.tanh(paddle.add(paddle.matmul(x, w), b)).numpy()

        for _ in range(3):
            step()
        s0 = fusion.stats()
        for _ in range(10):
            step()
        s1 = fusion.stats()
        assert s1["cache_misses"] - s0["cache_misses"] <= 1
        assert s1["cache_hits"] - s0["cache_hits"] >= 9
        assert s1["epilogues_fused"] - s0["epilogues_fused"] == 10

    def test_matmul_reduction_whole_loss_fuses(self):
        """act(x @ w + b) -> mean loss: contraction, epilogue AND
        terminator in ONE program."""
        set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1,
                   "FLAGS_eager_fusion_epilogue": 1})
        xn = RNG.standard_normal((4, 6)).astype(np.float32)
        wn = RNG.standard_normal((6, 3)).astype(np.float32)
        bn = RNG.standard_normal((3,)).astype(np.float32)
        x = paddle.to_tensor(xn, stop_gradient=False)
        w = paddle.to_tensor(wn, stop_gradient=False)
        b = paddle.to_tensor(bn, stop_gradient=False)
        s0 = fusion.stats()
        loss = paddle.mean(paddle.square(
            paddle.tanh(paddle.add(paddle.matmul(x, w), b))))
        loss.backward()
        s1 = fusion.stats()
        assert s1["chains_flushed"] - s0["chains_flushed"] == 1
        assert s1["epilogues_fused"] - s0["epilogues_fused"] == 1
        assert s1["reductions_fused"] - s0["reductions_fused"] == 1
        ref = np.mean(np.tanh(xn @ wn + bn) ** 2)
        assert float(loss.numpy()) == pytest.approx(ref, rel=1e-5)
        # grads flow to all three leaves through the one fused VJP
        assert all(t.grad is not None for t in (x, w, b))


def test_compile_seconds_labeled_by_program_kind():
    """Reduce/epilogue programs land their first-call compile time in
    fusion.compile_seconds under a kind label (the chrome-trace /
    snapshot attribution the profiler satellite wires through)."""
    set_flags({"FLAGS_eager_fusion": 1, "FLAGS_eager_fusion_reduce": 1})
    x = paddle.to_tensor(RNG.standard_normal((3, 7)).astype(np.float32))
    for _ in range(3):  # sighting -> compile -> steady
        float(paddle.mean(paddle.sinh(paddle.multiply(x, 0.5))).numpy())
    kinds = {dict(k).get("kind")
             for k in fusion._M_compile_s.series()}
    assert "reduce" in kinds


def test_stats_surface_shape():
    s = fusion.stats()
    for key in ("ops_deferred", "chains_flushed", "ops_fused",
                "cache_hits", "cache_misses", "flush_reasons",
                "chain_length_hist", "cache_size", "avg_ops_per_chain",
                "reductions_fused", "epilogues_fused"):
        assert key in s
