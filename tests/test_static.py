"""paddle.static facade tests: record/replay programs, Executor, minimize,
save/load_inference_model (ref: SURVEY layer 14, test/legacy_test static
coverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _reset_static():
    from paddle_tpu.static.program import (_reset_default_programs,
                                           _set_static_mode)
    yield
    _set_static_mode(False)
    _reset_default_programs()


def test_program_guard_records_and_replays():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        w = paddle.to_tensor(np.ones((4, 2), np.float32))
        y = paddle.matmul(x, w)
        z = y + 1.0
    assert len(prog.ops) >= 2

    exe = static.Executor()
    feed = np.arange(8, dtype=np.float32).reshape(2, 4)
    out, = exe.run(prog, feed={"x": feed}, fetch_list=[z])
    np.testing.assert_allclose(out, feed @ np.ones((4, 2)) + 1.0)


def test_replay_retraces_new_batch_size():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 3], "float32")
        y = (x * 2.0).sum(axis=-1)
    exe = static.Executor()
    for b in (2, 5):
        arr = np.random.default_rng(b).normal(size=(b, 3)).astype(np.float32)
        out, = exe.run(prog, feed={"x": arr}, fetch_list=[y])
        np.testing.assert_allclose(out, (arr * 2).sum(-1), rtol=1e-6)


def test_enable_static_default_program():
    paddle.enable_static()
    assert not paddle.in_dynamic_mode()
    x = static.data("x", [None, 2], "float32")
    y = x * 3.0
    exe = static.Executor()
    exe.run(static.default_startup_program())
    out, = exe.run(feed={"x": np.ones((4, 2), np.float32)},
                   fetch_list=[y])
    np.testing.assert_allclose(out, 3.0 * np.ones((4, 2)))
    paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_static_layer_and_minimize_trains():
    """Full static training loop: Layer fwd + loss + SGD minimize; the
    Executor compiles fwd+bwd+update into one program and the parameters
    actually move."""
    import paddle_tpu.nn as nn

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 4)).astype(np.float32)
    W_true = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    Y = X @ W_true

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        label = static.data("y", [None, 1], "float32")
        model = nn.Linear(4, 1)
        pred = model(x)
        loss = ((pred - label) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    losses = []
    for _ in range(60):
        lv, = exe.run(prog, feed={"x": X, "y": Y}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.01, (losses[0], losses[-1])
    np.testing.assert_allclose(model.weight.numpy(), W_true, atol=0.15)


def test_static_nn_fc():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 6], "float32")
        h = static.nn.fc(x, 3, activation="relu")
    exe = static.Executor()
    out, = exe.run(prog, feed={"x": np.ones((2, 6), np.float32)},
                   fetch_list=[h])
    assert out.shape == (2, 3)
    assert (out >= 0).all()


def test_static_matches_dygraph_numerics():
    """Same Layer, same weights: static replay == eager forward."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(5, 8), nn.ReLU(), nn.Linear(8, 2))
    arr = np.random.default_rng(1).normal(size=(3, 5)).astype(np.float32)

    eager_out = model(paddle.to_tensor(arr)).numpy()

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 5], "float32")
        y = model(x)
    exe = static.Executor()
    static_out, = exe.run(prog, feed={"x": arr}, fetch_list=[y])
    np.testing.assert_allclose(static_out, eager_out, rtol=1e-5, atol=1e-6)


def test_save_load_inference_model(tmp_path):
    import paddle_tpu.nn as nn

    paddle.seed(0)
    model = nn.Linear(4, 2)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        y = model(x)
    exe = static.Executor()
    arr = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    want, = exe.run(prog, feed={"x": arr}, fetch_list=[y])

    prefix = str(tmp_path / "linear")
    static.save_inference_model(prefix, [x], [y], exe, program=prog)

    loaded, feed_names, fetch_targets = static.load_inference_model(
        prefix, exe)
    assert feed_names == ["x"]
    got, = exe.run(loaded, feed={"x": arr})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # saved artifact survives weight mutation (params baked at save time)
    model.weight.set_value(np.zeros((4, 2), np.float32))
    got2, = exe.run(loaded, feed={"x": arr})
    np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)


def test_fetch_param_is_fresh_across_runs():
    """Fetching a parameter must show the optimizer-updated value, not the
    compile-time constant."""
    import paddle_tpu.nn as nn

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 2], "float32")
        model = nn.Linear(2, 1)
        loss = (model(x) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=model.parameters())
        opt.minimize(loss)
    exe = static.Executor()
    arr = np.ones((4, 2), np.float32)
    _, w1 = exe.run(prog, feed={"x": arr}, fetch_list=[loss, model.weight])
    after_run1 = model.weight.numpy().copy()
    _, w2 = exe.run(prog, feed={"x": arr}, fetch_list=[loss, model.weight])
    assert not np.allclose(w1, w2), "fetched param value is stale"
    # fetch shows the value used during that run (pre-update), so run2's
    # fetch equals the post-run1 live weight
    np.testing.assert_allclose(w2, after_run1, rtol=1e-6)


def test_static_bn_running_stats_update():
    """BN running stats must advance across Executor.run calls (buffer
    updates are replayed, not baked at record time)."""
    import paddle_tpu.nn as nn

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 3, 4, 4], "float32")
        bn = nn.BatchNorm2D(3)
        bn.train()
        y = bn(x)
    exe = static.Executor()
    rng = np.random.default_rng(0)
    arr = (5.0 + rng.normal(size=(8, 3, 4, 4))).astype(np.float32)
    mean0 = bn._mean.numpy().copy()
    exe.run(prog, feed={"x": arr}, fetch_list=[y])
    mean1 = bn._mean.numpy().copy()
    exe.run(prog, feed={"x": arr}, fetch_list=[y])
    mean2 = bn._mean.numpy().copy()
    assert not np.allclose(mean0, mean1), "running mean did not move"
    assert not np.allclose(mean1, mean2), "running mean stuck after run 1"
    # converging toward the true batch mean (~5)
    assert np.all(mean2 > mean1) and np.all(mean1 > mean0)


def test_static_dropout_fresh_mask_per_run():
    """Dropout masks must differ across Executor.run calls (PRNG slots are
    refreshed per run, not baked at record time)."""
    import paddle_tpu.nn.functional as F

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 64], "float32")
        y = F.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    arr = np.ones((2, 64), np.float32)
    out1, = exe.run(prog, feed={"x": arr}, fetch_list=[y])
    out2, = exe.run(prog, feed={"x": arr}, fetch_list=[y])
    assert not np.array_equal(out1, out2), "dropout mask is frozen"
    assert ((out1 == 0) | (np.isclose(out1, 2.0))).all()


def test_static_bn_bias_correction_uses_fed_batch():
    """Running-var update must use the fed batch's n/(n-1), not the
    placeholder's."""
    import paddle_tpu.nn as nn

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 2], "float32")
        bn = nn.BatchNorm1D(2)
        bn.train()
        y = bn(x)
    exe = static.Executor()
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(64, 2)).astype(np.float32)
    exe.run(prog, feed={"x": arr}, fetch_list=[y])
    # paddle momentum 0.9: new_var = 0.9*1 + 0.1*unbiased_var
    want = 0.9 + 0.1 * arr.var(0, ddof=1)
    np.testing.assert_allclose(bn._variance.numpy(), want, rtol=1e-4)


def test_static_fc_flattens_batch_polymorphic():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 2, 3], "float32")
        h = static.nn.fc(x, 4)
    exe = static.Executor()
    for b in (1, 5):
        out, = exe.run(prog, feed={"x": np.ones((b, 2, 3), np.float32)},
                       fetch_list=[h])
        assert out.shape == (b, 4)


def test_fetch_feed_passthrough():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 2], "float32")
        y = x + 0.0
    exe = static.Executor()
    arr = np.ones((2, 2), np.float32)
    # fetching the feed placeholder itself returns the fed value
    out_x, out_y = exe.run(prog, feed={"x": arr}, fetch_list=[x, y])
    np.testing.assert_allclose(out_x, arr)
    np.testing.assert_allclose(out_y, arr)
