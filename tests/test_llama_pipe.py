"""Pipeline-parallel Llama tests: the full pp training-step path
(ref parity gate: test/collective/fleet hybrid pp llama — pipeline loss
must match the serial model)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLMPipe


def _mesh(shape=(2, 4), names=("dp", "pp")):
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()).reshape(*shape), names)


@pytest.fixture
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=4, use_flash_attention=False)


class TestLlamaPipe:
    def test_forward_matches_serial(self, cfg, rng):
        paddle.seed(0)
        pipe = LlamaForCausalLMPipe(cfg, _mesh(), pp_axis="pp",
                                    batch_axes=("dp",),
                                    num_microbatches=4)
        ids_np = rng.integers(0, 128, (8, 16)).astype(np.int32)
        logits_pipe = np.asarray(pipe.forward_logits(ids_np))
        # the owned serial model shares the same parameters
        serial = pipe.model(paddle.to_tensor(ids_np)).numpy()
        np.testing.assert_allclose(logits_pipe, serial, atol=2e-4)

    def test_train_step_loss_decreases(self, cfg, rng):
        paddle.seed(1)
        pipe = LlamaForCausalLMPipe(cfg, _mesh(), pp_axis="pp",
                                    batch_axes=("dp",),
                                    num_microbatches=4)
        step = pipe.train_step(learning_rate=1e-2)
        ids = rng.integers(0, 128, (8, 16)).astype(np.int32)
        losses = [float(step(ids, ids)) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_layer_count_must_divide(self, rng):
        bad = LlamaConfig.tiny(num_hidden_layers=3)
        with pytest.raises(ValueError, match="divide"):
            LlamaForCausalLMPipe(bad, _mesh(), pp_axis="pp")

    def test_tied_embeddings_pipe(self, rng):
        cfg = LlamaConfig.tiny(num_hidden_layers=4,
                               use_flash_attention=False,
                               tie_word_embeddings=True)
        paddle.seed(2)
        pipe = LlamaForCausalLMPipe(cfg, _mesh(), pp_axis="pp",
                                    batch_axes=("dp",),
                                    num_microbatches=2)
        ids = rng.integers(0, 128, (4, 16)).astype(np.int32)
        step = pipe.train_step(1e-2)
        assert np.isfinite(float(step(ids, ids)))
