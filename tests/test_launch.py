"""Launcher CLI tests (ref: the reference tests its launcher by shelling
out, test/collective/test_communication_api_base.py:58-79)."""
import os
import subprocess
import sys
import textwrap

import pytest


def _run_launch(tmp_path, script_body, extra=(), env=None):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log"), *extra, str(script)]
    e = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env=e, cwd="/root/repo"), tmp_path / "log"


def test_rank_env_injection(tmp_path):
    proc, log = _run_launch(tmp_path, """
        import os
        print("rank", os.environ["PADDLE_TRAINER_ID"],
              "of", os.environ["PADDLE_TRAINERS_NUM"])
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr
    logs = sorted(os.listdir(log))
    assert logs == ["workerlog.0", "workerlog.1"]
    body0 = (log / "workerlog.0").read_text()
    body1 = (log / "workerlog.1").read_text()
    assert "rank 0 of 2" in body0
    assert "rank 1 of 2" in body1


def test_failure_propagates(tmp_path):
    proc, _ = _run_launch(tmp_path, """
        import sys
        sys.exit(3)
    """)
    assert proc.returncode != 0
    assert "failed with exit code 3" in proc.stderr


def test_elastic_restart(tmp_path):
    """Worker exits 101 once, then succeeds after restart
    (ref: elastic/manager.py restart protocol)."""
    proc, log = _run_launch(tmp_path, """
        import os, sys
        marker = os.environ["MARKER"]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(101)
        print("resumed ok")
    """, extra=["--elastic_retries", "1"],
        env={"MARKER": str(tmp_path / "marker")})
    assert proc.returncode == 0, proc.stderr
    assert "resumed ok" in (log / "workerlog.0").read_text()


def test_cross_process_collectives(tmp_path):
    """2-process eager collectives over the TCPStore channel transport
    (ref: process_group_nccl.cc Send/Recv + store bootstrap)."""
    proc, log = _run_launch(tmp_path, """
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        dist.init_parallel_env()
        r, n = dist.get_rank(), dist.get_world_size()
        assert n == 2, n

        # all_reduce
        t = paddle.to_tensor(np.full((3,), float(r + 1), np.float32))
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.full((3,), 3.0))

        # broadcast from rank 1
        b = paddle.to_tensor(np.full((2,), float(10 * (r + 1)), np.float32))
        dist.broadcast(b, src=1)
        np.testing.assert_allclose(b.numpy(), np.full((2,), 20.0))

        # reduce to dst=1 only
        d = paddle.to_tensor(np.full((2,), float(r + 1), np.float32))
        dist.reduce(d, dst=1)
        expect = 3.0 if r == 1 else float(r + 1)
        np.testing.assert_allclose(d.numpy(), np.full((2,), expect))

        # p2p: 0 -> 1 twice (FIFO), 1 -> 0 once; interleaved channels
        if r == 0:
            dist.send(paddle.to_tensor(np.array([1.0], np.float32)), dst=1)
            dist.send(paddle.to_tensor(np.array([2.0], np.float32)), dst=1)
            got = paddle.to_tensor(np.zeros(1, np.float32))
            dist.recv(got, src=1)
            assert got.numpy()[0] == 7.0
        else:
            dist.send(paddle.to_tensor(np.array([7.0], np.float32)), dst=0)
            a = paddle.to_tensor(np.zeros(1, np.float32))
            b2 = paddle.to_tensor(np.zeros(1, np.float32))
            dist.recv(a, src=0); dist.recv(b2, src=0)
            assert (a.numpy()[0], b2.numpy()[0]) == (1.0, 2.0)

        # scatter from 0
        s = paddle.to_tensor(np.zeros((2,), np.float32))
        if r == 0:
            dist.scatter(s, [paddle.to_tensor(np.full((2,), 5.0, np.float32)),
                             paddle.to_tensor(np.full((2,), 9.0, np.float32))],
                         src=0)
        else:
            dist.scatter(s, src=0)
        np.testing.assert_allclose(s.numpy(),
                                   np.full((2,), 5.0 if r == 0 else 9.0))

        # alltoall_single: rank r sends [r*10+j] to rank j
        inp = paddle.to_tensor(
            np.array([r * 10, r * 10 + 1], np.float32))
        out = paddle.to_tensor(np.zeros((2,), np.float32))
        dist.alltoall_single(out, inp)
        np.testing.assert_allclose(out.numpy(), np.array([r, 10 + r]))

        # object collectives
        objs = []
        dist.all_gather_object(objs, {"rank": r})
        assert objs == [{"rank": 0}, {"rank": 1}]
        ol = [None]
        if r == 0:
            ol = [{"cfg": 42}]
        dist.broadcast_object_list(ol, src=0)
        assert ol == [{"cfg": 42}]
        so = [None]
        dist.scatter_object_list(so, [["a"], ["b"]] if r == 0 else None,
                                 src=0)
        assert so == [["a"] if r == 0 else ["b"]]

        dist.barrier()
        print("CROSS_PROC_OK rank", r)
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr + (
        (log / "workerlog.0").read_text() if log.exists() else "")
    for i in (0, 1):
        assert "CROSS_PROC_OK" in (log / f"workerlog.{i}").read_text()


def test_two_process_1f1b_pipeline(tmp_path):
    """2-process fleet 1F1B pipeline matches the single-process oracle
    loss and stage-local weight updates (VERDICT round-1 item 3; ref:
    pipeline_parallel.py:575-720 + p2p_communication.py:576)."""
    proc, log = _run_launch(tmp_path, """
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer

        D, M = 8, 4   # width, micro-batches

        class Block(nn.Layer):
            def __init__(self, idx):
                super().__init__()
                self.fc = nn.Linear(D, D)
                rng = np.random.default_rng(100 + idx)
                self.fc.weight.set_value(
                    (rng.standard_normal((D, D)) * 0.3).astype(np.float32))
                self.fc.bias.set_value(np.zeros(D, np.float32))
            def forward(self, x):
                return paddle.tanh(self.fc(x))

        def loss_fn(out, label):
            return ((out - label) ** 2).mean()

        rng = np.random.default_rng(0)
        xs = rng.standard_normal((8, D)).astype(np.float32)
        ys = rng.standard_normal((8, D)).astype(np.float32)

        # --- single-process oracle: grad-accumulated fwd/bwd + SGD step
        oracle = [Block(i) for i in range(4)]
        for mi in range(M):
            x = paddle.to_tensor(xs[mi * 2:(mi + 1) * 2])
            for b in oracle:
                x = b(x)
            l = loss_fn(x, paddle.to_tensor(ys[mi * 2:(mi + 1) * 2]))
            (l / M).backward()
        oracle_losses = []
        x = paddle.to_tensor(xs)
        for b in oracle:
            x = b(x)
        # per-micro mean loss (what the pipeline reports)
        tot = 0.0
        for mi in range(M):
            xm = paddle.to_tensor(xs[mi * 2:(mi + 1) * 2])
            for b in oracle:
                xm = b(xm)
            tot += float(loss_fn(xm, paddle.to_tensor(
                ys[mi * 2:(mi + 1) * 2])))
        oracle_loss = tot / M

        # --- 2-process pipeline
        dist.init_parallel_env()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": M,
                                     "micro_batch_size": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pl = PipelineLayer([LayerDesc(Block, i) for i in range(4)],
                           loss_fn=loss_fn)
        model = fleet.distributed_model(pl)
        loss = model.forward_backward_pipeline(
            (paddle.to_tensor(xs), paddle.to_tensor(ys)))
        r = dist.get_rank()
        assert abs(float(loss) - oracle_loss) < 1e-5, \\
            (float(loss), oracle_loss)

        # stage-local grads must match the oracle's corresponding layers
        own = oracle[:2] if r == 0 else oracle[2:]
        for got, exp in zip(model._layers._stage_layers, own):
            np.testing.assert_allclose(got.fc.weight.grad.numpy(),
                                       exp.fc.weight.grad.numpy(),
                                       rtol=1e-4, atol=1e-5)
        print("PP_1F1B_OK rank", r)
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr + "".join(
        (log / f"workerlog.{i}").read_text() for i in (0, 1)
        if (log / f"workerlog.{i}").exists())
    for i in (0, 1):
        assert "PP_1F1B_OK" in (log / f"workerlog.{i}").read_text()


def test_bucketed_dp_gradients(tmp_path):
    """DataParallel fuses grads into size buckets for the allreduce
    (ref: reducer.cc EagerReducer) — results match per-param math."""
    proc, log = _run_launch(tmp_path, """
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.distributed as dist

        dist.init_parallel_env()
        r = dist.get_rank()
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        dp = paddle.DataParallel(net, comm_buffer_size=1)
        x = paddle.to_tensor(
            np.full((4, 8), float(r + 1), np.float32))
        (dp(x) ** 2).mean().backward()
        # expected: mean over ranks of each rank's grad; compute rank
        # grads locally for the oracle
        grads = {}
        for world_r in (0, 1):
            paddle.seed(0)
            net2 = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                 nn.Linear(16, 4))
            x2 = paddle.to_tensor(
                np.full((4, 8), float(world_r + 1), np.float32))
            (net2(x2) ** 2).mean().backward()
            for name, p in net2.named_parameters():
                grads.setdefault(name, []).append(p.grad.numpy())
        dp.apply_collective_grads()
        for name, p in net.named_parameters():
            exp = np.mean(grads[name], axis=0)
            np.testing.assert_allclose(p.grad.numpy(), exp, rtol=1e-4,
                                       atol=1e-6)
        print("BUCKETED_DP_OK rank", r)
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr + "".join(
        (log / f"workerlog.{i}").read_text() for i in (0, 1)
        if (log / f"workerlog.{i}").exists())
    for i in (0, 1):
        assert "BUCKETED_DP_OK" in (log / f"workerlog.{i}").read_text()


def test_bucketed_dp_unused_param_layout_stable(tmp_path):
    """A rank with a missing grad (unused param) must not shift the
    fused bucket layout (review regression: zeros substitute, layout is
    rank-invariant, every rank joins every collective)."""
    proc, log = _run_launch(tmp_path, """
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.distributed as dist

        dist.init_parallel_env()
        r = dist.get_rank()
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(4, 4)
                self.b = nn.Linear(4, 4)   # used only on rank 0
                self.c = nn.Linear(4, 4)
            def forward(self, x, use_b):
                h = self.a(x)
                if use_b:
                    h = self.b(h)
                return self.c(h)

        net = Net()
        dp = paddle.DataParallel(net, comm_buffer_size=1,
                                 find_unused_parameters=True)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        (dp(x, use_b=(r == 0)) ** 2).mean().backward()
        dp.apply_collective_grads()
        # c.weight grads must be finite and identical across ranks
        g = net.c.weight.grad.numpy()
        assert np.isfinite(g).all()
        out = []
        t = paddle.to_tensor(g.reshape(-1))
        dist.all_gather(out, t)
        np.testing.assert_allclose(out[0].numpy(), out[1].numpy(),
                                   rtol=1e-6)
        if r == 1:
            assert net.b.weight.grad is None   # never written back
        print("UNUSED_OK rank", r)
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr + "".join(
        (log / f"workerlog.{i}").read_text() for i in (0, 1)
        if (log / f"workerlog.{i}").exists())
    for i in (0, 1):
        assert "UNUSED_OK" in (log / f"workerlog.{i}").read_text()
