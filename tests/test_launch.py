"""Launcher CLI tests (ref: the reference tests its launcher by shelling
out, test/collective/test_communication_api_base.py:58-79)."""
import os
import subprocess
import sys
import textwrap

import pytest


def _run_launch(tmp_path, script_body, extra=(), env=None):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log"), *extra, str(script)]
    e = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env=e, cwd="/root/repo"), tmp_path / "log"


def test_rank_env_injection(tmp_path):
    proc, log = _run_launch(tmp_path, """
        import os
        print("rank", os.environ["PADDLE_TRAINER_ID"],
              "of", os.environ["PADDLE_TRAINERS_NUM"])
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr
    logs = sorted(os.listdir(log))
    assert logs == ["workerlog.0", "workerlog.1"]
    body0 = (log / "workerlog.0").read_text()
    body1 = (log / "workerlog.1").read_text()
    assert "rank 0 of 2" in body0
    assert "rank 1 of 2" in body1


def test_failure_propagates(tmp_path):
    proc, _ = _run_launch(tmp_path, """
        import sys
        sys.exit(3)
    """)
    assert proc.returncode != 0
    assert "failed with exit code 3" in proc.stderr


def test_elastic_restart(tmp_path):
    """Worker exits 101 once, then succeeds after restart
    (ref: elastic/manager.py restart protocol)."""
    proc, log = _run_launch(tmp_path, """
        import os, sys
        marker = os.environ["MARKER"]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(101)
        print("resumed ok")
    """, extra=["--elastic_retries", "1"],
        env={"MARKER": str(tmp_path / "marker")})
    assert proc.returncode == 0, proc.stderr
    assert "resumed ok" in (log / "workerlog.0").read_text()
