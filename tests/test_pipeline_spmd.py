"""Compiled SPMD pipeline tests: GPipe-in-one-jit over the 'pp' mesh axis.

Parity gate mirrors the reference's PP tests (ref: test/collective/fleet
hybrid_parallel_pp_*: pipeline loss == single-process loss)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.parallel import spmd_pipeline, stack_layer_params


def _mesh(shape=(2, 4), names=("dp", "pp")):
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()).reshape(*shape), names)


class TestSpmdPipeline:
    def test_mlp_stage_parity(self, rng):
        import jax.numpy as jnp
        S, M, B, H = 4, 8, 2, 16
        per_layer = [
            {"w": jnp.asarray(rng.normal(size=(H, H)) * 0.1, jnp.float32),
             "b": jnp.asarray(rng.normal(size=(H,)) * 0.1, jnp.float32)}
            for _ in range(S)]

        def stage_fn(p, x):
            return x + jnp.tanh(x @ p["w"] + p["b"])

        mb = jnp.asarray(rng.normal(size=(M, B, H)), jnp.float32)
        ref = jnp.stack([functools_reduce(stage_fn, per_layer, mb[m])
                         for m in range(M)])
        out = spmd_pipeline(stage_fn, stack_layer_params(per_layer), mb,
                            _mesh(), axis="pp", batch_axes=("dp",))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_gradients_flow_all_stages(self, rng):
        import jax
        import jax.numpy as jnp
        M, B, H = 4, 2, 8

        def stage_fn(p, x):
            return x + jnp.tanh(x @ p["w"])

        mb = jnp.asarray(rng.normal(size=(M, B, H)), jnp.float32)
        mesh = _mesh((1, 8), ("dp", "pp"))
        per_layer8 = [
            {"w": jnp.asarray(rng.normal(size=(H, H)) * 0.1, jnp.float32)}
            for _ in range(8)]
        stacked8 = stack_layer_params(per_layer8)
        g = jax.grad(lambda sp: (spmd_pipeline(
            stage_fn, sp, mb, mesh, "pp", ("dp",)) ** 2).sum())(stacked8)
        gw = np.asarray(g["w"])
        assert gw.shape[0] == 8
        assert (np.abs(gw).reshape(8, -1).sum(axis=1) > 0).all()

    def test_multiple_layers_per_stage(self, rng):
        """8 stacked layers on pp=4: each stage runs 2 consecutive layers
        (regression: extra layers used to be silently dropped)."""
        import jax.numpy as jnp
        M, B, H = 4, 2, 8
        per_layer = [
            {"w": jnp.asarray(rng.normal(size=(H, H)) * 0.1, jnp.float32)}
            for _ in range(8)]

        def stage_fn(p, x):
            return x + jnp.tanh(x @ p["w"])

        mb = jnp.asarray(rng.normal(size=(M, B, H)), jnp.float32)
        ref = jnp.stack([functools_reduce(stage_fn, per_layer, mb[m])
                         for m in range(M)])
        out = spmd_pipeline(stage_fn, stack_layer_params(per_layer), mb,
                            _mesh((2, 4), ("dp", "pp")), axis="pp",
                            batch_axes=("dp",))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_indivisible_layer_count_raises(self, rng):
        import jax.numpy as jnp
        per_layer = [{"w": jnp.zeros((4, 4), jnp.float32)}] * 3
        mb = jnp.zeros((2, 2, 4), jnp.float32)
        with pytest.raises(ValueError, match="multiple of"):
            spmd_pipeline(lambda p, x: x, stack_layer_params(per_layer),
                          mb, _mesh((2, 4), ("dp", "pp")), axis="pp")

    def test_llama_decoder_stage_pipeline(self, rng):
        """Pipeline of real LlamaDecoderLayers == running them serially."""
        import jax.numpy as jnp
        from paddle_tpu.jit.api import functionalize
        from paddle_tpu.models.llama import LlamaConfig, LlamaDecoderLayer

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        S, M, B, L = 4, 4, 2, 16
        paddle.seed(0)
        layers = [LlamaDecoderLayer(cfg) for _ in range(S)]
        applies = [functionalize(l) for l in layers]
        apply0 = applies[0][0]

        def stage_fn(p, x):
            out, _ = apply0(p, {}, x)
            return out

        per_layer = [a[1] for a in applies]
        h = jnp.asarray(rng.normal(size=(M, B, L, cfg.hidden_size)),
                        jnp.float32)
        # serial reference
        ref = []
        for m in range(M):
            x = h[m]
            for p in per_layer:
                x = stage_fn(p, x)
            ref.append(x)
        out = spmd_pipeline(stage_fn, stack_layer_params(per_layer), h,
                            _mesh((2, 4), ("dp", "pp")), axis="pp",
                            batch_axes=("dp",))
        np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.stack(ref)),
                                   atol=2e-5)


def functools_reduce(stage_fn, per_layer, x):
    for p in per_layer:
        x = stage_fn(p, x)
    return x
