"""Zero-bubble (ZB-H1) pipeline schedule tests (ref: distributed/passes/
pipeline_scheduler_pass/pipeline_zero_bubble.py): bubble-count reduction
vs 1F1B under the dependency simulator, loss/grad equivalence of the
split-B/W programs, and the multi-process runtime end-to-end."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import (one_f_one_b_schedule,
                                          simulate_schedule,
                                          zb_h1_schedule)


class TestScheduleBubble:
    def test_zb_reduces_bubble_vs_1f1b(self):
        """With unit costs the ZB-H1 bubble must be strictly below
        1F1B's on every non-trivial stage, and the theoretical ~1/3
        total reduction must show."""
        S, M = 4, 8
        f1b = {s: one_f_one_b_schedule(S, s, M) for s in range(S)}
        zb = {s: zb_h1_schedule(S, s, M) for s in range(S)}
        idle_1f1b = simulate_schedule(f1b, fused_bw=True)
        idle_zb = simulate_schedule(zb, fused_bw=False)
        tot_1f1b = sum(idle_1f1b.values())
        tot_zb = sum(idle_zb.values())
        assert tot_zb < tot_1f1b, (idle_zb, idle_1f1b)
        # taking W off the cooldown critical path saves >= t_W per
        # non-last stage at unit costs (memory-neutral H1 deferral);
        # every stage must be no worse
        assert tot_zb <= tot_1f1b - (S - 1), (tot_zb, tot_1f1b)
        for s in range(S):
            assert idle_zb[s] <= idle_1f1b[s], (s, idle_zb, idle_1f1b)
        # heavier W (common in practice: dW matmuls dominate) widens the
        # gap — the deferral scales with t_W
        idle_zb_w2 = sum(simulate_schedule(
            zb, t_w=2, fused_bw=False).values())
        idle_f1b_w2 = sum(simulate_schedule(
            f1b, t_w=2, fused_bw=True).values())
        assert idle_f1b_w2 - idle_zb_w2 >= 2 * (S - 1)

    def test_zb_schedule_defers_cooldown_w(self):
        """Event counts must balance (every F has one B and one W) and
        every cooldown B must precede ALL deferred W's — the W-free
        B-chain is the zero-bubble property."""
        S, M = 4, 8
        for s in range(S):
            ev = zb_h1_schedule(S, s, M)
            kinds = [k for k, _ in ev]
            assert kinds.count("F") == M
            assert kinds.count("B") == M
            assert kinds.count("W") == M
            last_b = max(i for i, k in enumerate(kinds) if k == "B")
            # the last stage has no cooldown: its tail is the final
            # steady slot's own W
            n_tail = max(min(S - 1 - s, M), 1)
            tail_ws = [k for k in kinds[last_b + 1:]]
            assert tail_ws == ["W"] * n_tail, (s, ev)

    def test_zb_memory_highwater_matches_1f1b(self):
        """H1's defining property: no extra activation memory vs 1F1B.
        Stash count grows at F (activation kept) and shrinks at W
        (released after weight grads) — the schedule-level high-water
        must not exceed 1F1B's (the pipeline memory gate for zb)."""
        S, M = 4, 8

        def highwater(ev):
            live = hw = 0
            for kind, _ in ev:
                if kind == "F":
                    live += 1
                    hw = max(hw, live)
                elif kind == "W":
                    live -= 1
            return hw

        for s in range(S):
            hw_zb = highwater(zb_h1_schedule(S, s, M))
            hw_1f1b = highwater(one_f_one_b_schedule(S, s, M))
            assert hw_zb == hw_1f1b, (s, hw_zb, hw_1f1b)

    def test_simulator_detects_deadlock(self):
        bad = {0: [("B", 0), ("F", 0), ("W", 0)],
               1: [("F", 0), ("B", 0), ("W", 0)]}
        with pytest.raises(RuntimeError, match="deadlock"):
            simulate_schedule(bad)


def _loss_fn(out, label):
    return ((out - label) ** 2).mean()


class TestSplitBWEquivalence:
    def test_zb_single_controller_matches_1f1b(self):
        """Same model + data through the 1F1B runtime and the ZB runtime
        (split B/W programs): identical loss and parameter grads."""
        from paddle_tpu.distributed.fleet import (PipelineLayer, LayerDesc)
        from paddle_tpu.distributed.fleet.pipeline_parallel import (
            PipelineParallel)
        from paddle_tpu.distributed.fleet.pipeline_zero_bubble import (
            PipelineParallelZeroBubble)

        class Block(nn.Layer):
            def __init__(self, i):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return paddle.tanh(self.fc(x))

        class FakeHcg:
            def get_pipe_parallel_world_size(self):
                return 1

            def get_stage_id(self):
                return 0

        class Strat:
            pipeline_configs = {"accumulate_steps": 4,
                                "micro_batch_size": 2}

        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 8)).astype(np.float32)
        y = rng.standard_normal((8, 8)).astype(np.float32)

        def run(cls):
            paddle.seed(0)
            pl = PipelineLayer([LayerDesc(Block, i) for i in range(3)],
                               loss_fn=_loss_fn)
            runtime = cls(pl, FakeHcg(), Strat())
            loss = runtime.forward_backward_pipeline(
                (paddle.to_tensor(x), paddle.to_tensor(y)))
            grads = {k: np.asarray(p.grad._data)
                     for k, p in dict(pl.named_parameters()).items()
                     if p.grad is not None}
            return float(loss), grads

        l1, g1 = run(PipelineParallel)
        l2, g2 = run(PipelineParallelZeroBubble)
        np.testing.assert_allclose(l1, l2, rtol=1e-5)
        assert set(g1) == set(g2) and g1
        for k in g1:
            np.testing.assert_allclose(g1[k], g2[k], rtol=1e-4,
                                       atol=1e-6)

    def test_zb_single_records_deferred_schedule(self):
        from paddle_tpu.distributed.fleet import (PipelineLayer, LayerDesc)
        from paddle_tpu.distributed.fleet.pipeline_zero_bubble import (
            PipelineParallelZeroBubble)

        class Block(nn.Layer):
            def __init__(self, i):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        class FakeHcg:
            def get_pipe_parallel_world_size(self):
                return 1

            def get_stage_id(self):
                return 0

        class Strat:
            pipeline_configs = {"accumulate_steps": 3,
                                "micro_batch_size": 1}

        paddle.seed(0)
        pl = PipelineLayer([LayerDesc(Block, 0)], loss_fn=_loss_fn)
        rt = PipelineParallelZeroBubble(pl, FakeHcg(), Strat())
        x = np.zeros((3, 4), np.float32)
        rt.forward_backward_pipeline((paddle.to_tensor(x),
                                      paddle.to_tensor(x)))
        kinds = [k for k, _ in rt.last_schedule]
        # all W strictly after all B (true deferral on the single path)
        assert kinds.index("W") > max(i for i, k in enumerate(kinds)
                                      if k == "B")


def _run_launch(tmp_path, script_body, extra=()):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--log_dir", str(tmp_path / "log"), *extra, str(script)]
    e = dict(os.environ, PYTHONPATH="/root/repo", JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=240, env=e,
                          cwd="/root/repo"), tmp_path / "log"


def test_zb_multiproc_matches_single_process(tmp_path):
    """2-stage ZB-H1 over real subprocesses: loss matches the
    single-process oracle and grads flow on both stages
    (the reference tests PP runtimes with launched workers,
    test/collective/fleet)."""
    proc, log = _run_launch(tmp_path, """
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import (PipelineLayer,
                                                  LayerDesc)

        M = 4

        class Block(nn.Layer):
            def __init__(self, i):
                super().__init__()
                self.fc = nn.Linear(8, 8)
            def forward(self, x):
                return paddle.tanh(self.fc(x))

        def loss_fn(out, label):
            return ((out - label) ** 2).mean()

        dist.init_parallel_env()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": M,
                                     "micro_batch_size": 2,
                                     "schedule_mode": "ZB-H1"}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        pl = PipelineLayer([LayerDesc(Block, i) for i in range(4)],
                           loss_fn=loss_fn)
        from paddle_tpu.distributed.fleet.pipeline_zero_bubble import (
            PipelineParallelZeroBubble)
        model = fleet.distributed_model(pl)
        assert isinstance(model, PipelineParallelZeroBubble), type(model)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 8)).astype(np.float32)
        y = rng.standard_normal((8, 8)).astype(np.float32)
        loss = model.forward_backward_pipeline(
            (paddle.to_tensor(x), paddle.to_tensor(y)))

        # single-process oracle mirroring per-rank init: each rank
        # constructs its 2 local blocks from seed 0, so stage-1's
        # blocks have the same weights as stage-0's
        paddle.seed(0)
        s0 = [Block(i) for i in range(2)]
        paddle.seed(0)
        s1 = [Block(i) for i in range(2)]
        blocks = nn.Sequential(*(s0 + s1))
        total = None
        for xm, ym in zip(np.split(x, M), np.split(y, M)):
            out = blocks(paddle.to_tensor(xm))
            l = loss_fn(out, paddle.to_tensor(ym))
            (l * (1.0 / M)).backward()
            total = l if total is None else total + l
        exp = float(total.numpy()) / M
        np.testing.assert_allclose(float(loss), exp, rtol=1e-5)

        # this rank's stage grads match the oracle's matching blocks
        r = dist.get_rank()
        got = {k: p.grad.numpy() for k, p in
               dict(model._layers.named_parameters()).items()
               if p.grad is not None}
        assert got, "no grads on stage"
        oracle = {k: p.grad.numpy() for k, p in
                  dict(blocks.named_parameters()).items()}
        for k, gv in got.items():
            parts = k.split(".")
            while parts and not parts[0].isdigit():
                parts = parts[1:]  # strip container prefixes
            idx = int(parts[0]) + (2 if r == 1 else 0)
            ok = oracle[f"{idx}." + ".".join(parts[1:])]
            np.testing.assert_allclose(gv, ok, rtol=1e-4, atol=1e-6)
        print("ZB_PP_OK rank", r)
    """, extra=["--nproc_per_node", "2"])
    assert proc.returncode == 0, proc.stderr + "".join(
        (log / f"workerlog.{i}").read_text() for i in (0, 1)
        if (log / f"workerlog.{i}").exists())
    for i in (0, 1):
        assert "ZB_PP_OK" in (log / f"workerlog.{i}").read_text()
