"""API-surface tests: fft/signal/distribution/sparse/quantization/
regularizer (SURVEY §2.3 Python-side components)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestFFT:
    def test_fft_roundtrip(self, rng):
        x = paddle.to_tensor(rng.normal(size=(4, 32)).astype(np.float32))
        back = paddle.fft.ifft(paddle.fft.fft(x))
        np.testing.assert_allclose(back.numpy().real, x.numpy(), atol=1e-5)

    def test_rfft_matches_numpy(self, rng):
        x_np = rng.normal(size=(16,)).astype(np.float32)
        out = paddle.fft.rfft(paddle.to_tensor(x_np)).numpy()
        np.testing.assert_allclose(out, np.fft.rfft(x_np), atol=1e-4)

    def test_fft2_and_shift(self, rng):
        x = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
        s = paddle.fft.fftshift(paddle.fft.fft2(x))
        assert s.shape == [8, 8]

    def test_fft_grad(self, rng):
        x = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32),
                             stop_gradient=False)
        y = paddle.fft.fft(x)
        (y.abs() ** 2).sum().backward()
        assert x.grad is not None

    def test_fft_invalid_norm_raises(self, rng):
        x = paddle.to_tensor(rng.normal(size=(8,)).astype(np.float32))
        with pytest.raises(ValueError, match="norm"):
            paddle.fft.fft(x, norm="orthogonal")


class TestSignal:
    def test_stft_istft_roundtrip(self, rng):
        x_np = rng.normal(size=(2, 512)).astype(np.float32)
        x = paddle.to_tensor(x_np)
        spec = paddle.signal.stft(x, n_fft=64, hop_length=16)
        assert spec.shape[0] == 2 and spec.shape[1] == 33
        back = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                   length=512)
        np.testing.assert_allclose(back.numpy(), x_np, atol=1e-3)

    def test_frame_overlap_add(self, rng):
        x = paddle.to_tensor(np.arange(32, dtype=np.float32))
        f = paddle.signal.frame(x, frame_length=8, hop_length=8)
        assert f.shape == [8, 4]
        back = paddle.signal.overlap_add(f, hop_length=8)
        np.testing.assert_allclose(back.numpy(), x.numpy())

    def test_frame_axis0(self):
        x = paddle.to_tensor(np.arange(32, dtype=np.float32))
        f = paddle.signal.frame(x, frame_length=8, hop_length=8, axis=0)
        assert f.shape == [4, 8]
        np.testing.assert_allclose(f.numpy()[1], np.arange(8, 16))
        back = paddle.signal.overlap_add(f, hop_length=8, axis=0)
        np.testing.assert_allclose(back.numpy(), x.numpy())

    def test_win_length_without_window(self, rng):
        """window=None with win_length < n_fft must apply a centered
        rectangular win_length window (regression: spanned all n_fft)."""
        x_np = rng.normal(size=(256,)).astype(np.float32)
        x = paddle.to_tensor(x_np)
        spec = paddle.signal.stft(x, n_fft=64, win_length=32,
                                  hop_length=16, center=False)
        # manual frame 0: zero outside the centered 32-sample window
        w = np.zeros(64, np.float32)
        w[16:48] = 1.0
        ref0 = np.fft.rfft(x_np[:64] * w)
        np.testing.assert_allclose(spec.numpy()[:, 0], ref0, atol=1e-4)

    def test_istft_return_complex(self, rng):
        x = paddle.to_tensor(rng.normal(size=(256,)).astype(np.float32))
        spec = paddle.signal.stft(x, n_fft=64, hop_length=16,
                                  onesided=False)
        out = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                  onesided=False, return_complex=True,
                                  length=256)
        assert np.iscomplexobj(out.numpy())
        with pytest.raises(ValueError):
            paddle.signal.istft(spec, n_fft=64, onesided=True,
                                return_complex=True)


class TestDistribution:
    def test_normal_log_prob_entropy_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        p = Normal(0.0, 1.0)
        q = Normal(1.0, 2.0)
        lp = float(p.log_prob(paddle.to_tensor(0.0)))
        np.testing.assert_allclose(lp, -0.9189385, atol=1e-5)
        np.testing.assert_allclose(float(p.entropy()), 1.4189385, atol=1e-5)
        kl = float(kl_divergence(p, q))
        # closed form: log(2) + (1 + 1)/8 - 0.5
        np.testing.assert_allclose(kl, np.log(2) + 2 / 8 - 0.5, atol=1e-5)

    def test_sampling_deterministic_under_seed(self):
        from paddle_tpu.distribution import Normal
        paddle.seed(123)
        a = Normal(0.0, 1.0).sample([4]).numpy()
        paddle.seed(123)
        b = Normal(0.0, 1.0).sample([4]).numpy()
        np.testing.assert_array_equal(a, b)

    def test_categorical_and_bernoulli(self, rng):
        from paddle_tpu.distribution import Bernoulli, Categorical
        c = Categorical(paddle.to_tensor(np.zeros(4, np.float32)))
        s = c.sample([100]).numpy()
        assert s.min() >= 0 and s.max() <= 3
        np.testing.assert_allclose(float(c.entropy()), np.log(4), atol=1e-5)
        b = Bernoulli(0.3)
        np.testing.assert_allclose(float(b.mean), 0.3, atol=1e-6)

    def test_rsample_grad_flows(self):
        """Reparameterization: gradients reach loc/scale (regression: params
        used to be detached at construction)."""
        from paddle_tpu.distribution import Normal
        loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        scale = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
        n = Normal(loc, scale)
        s = n.rsample([8])
        s.sum().backward()
        # d sum(loc + scale*eps) / d loc = 8
        np.testing.assert_allclose(float(loc.grad), 8.0, atol=1e-5)
        assert scale.grad is not None

    def test_log_prob_grad_flows(self):
        from paddle_tpu.distribution import Normal
        loc = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
        n = Normal(loc, 1.0)
        lp = n.log_prob(paddle.to_tensor(np.float32(1.0)))
        lp.backward()
        # d log N(1; loc, 1) / d loc = (1 - loc) = 1
        np.testing.assert_allclose(float(loc.grad), 1.0, atol=1e-5)

    def test_kl_exact_dispatch_rejects_subclass_mix(self):
        from paddle_tpu.distribution import (LogNormal, Normal,
                                             kl_divergence)
        with pytest.raises(NotImplementedError):
            kl_divergence(Normal(0.0, 1.0), LogNormal(0.0, 1.0))
        # but same-class LogNormal pairs work (= underlying normals' KL)
        kl = float(kl_divergence(LogNormal(0.0, 1.0), LogNormal(1.0, 1.0)))
        np.testing.assert_allclose(kl, 0.5, atol=1e-5)


class TestSparse:
    def test_coo_roundtrip(self):
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        val = np.array([1.0, 2.0, 3.0], np.float32)
        s = paddle.sparse.sparse_coo_tensor(idx, val, (3, 3))
        assert s.nnz == 3
        dense = s.to_dense().numpy()
        expect = np.zeros((3, 3), np.float32)
        expect[idx[0], idx[1]] = val
        np.testing.assert_allclose(dense, expect)

    def test_csr(self):
        s = paddle.sparse.sparse_csr_tensor(
            [0, 1, 2], [0, 1], [5.0, 6.0], (2, 2))
        np.testing.assert_allclose(s.to_dense().numpy(),
                                   [[5.0, 0], [0, 6.0]])

    def test_spmm(self, rng):
        idx = np.array([[0, 1], [1, 0]])
        s = paddle.sparse.sparse_coo_tensor(
            idx, np.array([2.0, 3.0], np.float32), (2, 2))
        d = paddle.to_tensor(np.eye(2, dtype=np.float32))
        out = paddle.sparse.matmul(s, d).numpy()
        np.testing.assert_allclose(out, [[0, 2.0], [3.0, 0]])

    def test_sparse_relu(self):
        idx = np.array([[0, 1], [0, 1]])
        s = paddle.sparse.sparse_coo_tensor(
            idx, np.array([-1.0, 2.0], np.float32), (2, 2))
        out = paddle.sparse.relu(s)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   [[0, 0], [0, 2.0]])


class TestQuantization:
    def test_fake_quant_ste(self, rng):
        from paddle_tpu.quantization import fake_quantize_abs_max
        x = paddle.to_tensor(rng.normal(size=(16,)).astype(np.float32),
                             stop_gradient=False)
        y = fake_quantize_abs_max(x, bits=8)
        # quantization error bounded by scale/2
        scale = np.abs(x.numpy()).max() / 127
        assert np.abs(y.numpy() - x.numpy()).max() <= scale * 0.5 + 1e-6
        (y * y).sum().backward()
        # straight-through: grad == 2*y (as if identity through quant)
        np.testing.assert_allclose(x.grad.numpy(), 2 * y.numpy(), atol=1e-5)

    def test_qat_swaps_linears(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import QAT, QuantConfig, QuantedLinear
        m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
        q = QAT(QuantConfig()).quantize(m)
        kinds = [type(l).__name__ for l in q.sublayers()]
        assert kinds.count("QuantedLinear") == 2
        x = paddle.to_tensor(rng.normal(size=(2, 8)).astype(np.float32))
        assert q(x).shape == [2, 4]

    def test_ptq_calibrate_convert(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ
        m = nn.Sequential(nn.Linear(8, 4))
        ptq = PTQ()
        observed = ptq.quantize(m)
        x = paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32))
        observed(x)  # calibration pass
        assert ptq._observers and ptq._observers[0]._max > 0
        final = ptq.convert(observed)
        assert final(x).shape == [4, 4]
        # the calibrated scale is FROZEN into the converted layer
        # (regression: convert used to fall back to dynamic absmax)
        ql = [l for l in final.sublayers()
              if type(l).__name__ == "QuantedLinear"][0]
        assert ql.act_quanter.static_scale is not None
        np.testing.assert_allclose(ql.act_quanter.static_scale,
                                   ptq._observers[0].scale())
        # an outlier batch must NOT change the quantization step: inputs
        # within calibration range quantize identically either way
        y_cal = final(x).numpy()
        big = x.numpy().copy()
        big[0, 0] = 100.0
        final(paddle.to_tensor(big))
        np.testing.assert_allclose(final(x).numpy(), y_cal)

    def test_quant_config_layer_types(self, rng):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import (FakeQuantAbsMax, QAT,
                                             QuantConfig)
        cfg = QuantConfig(activation=FakeQuantAbsMax(4),
                          weight=FakeQuantAbsMax(4))
        m = QAT(cfg).quantize(nn.Sequential(nn.Linear(4, 4)))
        ql = [l for l in m.sublayers()
              if type(l).__name__ == "QuantedLinear"][0]
        assert ql.weight_quanter.quant_bits == 4
        assert ql.act_quanter.quant_bits == 4


class TestRegularizer:
    def test_l1_l2(self):
        from paddle_tpu.regularizer import L1Decay, L2Decay
        import jax.numpy as jnp
        p = jnp.asarray([1.0, -2.0])
        g = jnp.zeros(2)
        np.testing.assert_allclose(np.asarray(L2Decay(0.1)(p, g)),
                                   [0.1, -0.2], atol=1e-6)
        np.testing.assert_allclose(np.asarray(L1Decay(0.1)(p, g)),
                                   [0.1, -0.1], atol=1e-6)

    def test_l1_applied_as_l1_in_optimizer(self):
        """Regression: L1Decay used to be coerced to an L2 coefficient."""
        from paddle_tpu.regularizer import L1Decay
        p = paddle.Parameter(np.array([2.0, -3.0], np.float32))
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p],
                                   weight_decay=L1Decay(0.5))
        p.grad = paddle.to_tensor(np.zeros(2, np.float32))
        opt.step()
        # pure L1: p -= lr * coeff * sign(p) -> [1.5, -2.5]
        np.testing.assert_allclose(p.numpy(), [1.5, -2.5], atol=1e-6)

    def test_l1_applied_in_adamw_step(self):
        """Regression: AdamW.step() override missed _apply_regularizer."""
        from paddle_tpu.regularizer import L1Decay
        p = paddle.Parameter(np.array([2.0, -3.0], np.float32))
        opt = paddle.optimizer.AdamW(learning_rate=0.1, parameters=[p],
                                     weight_decay=L1Decay(0.5))
        p.grad = paddle.to_tensor(np.zeros(2, np.float32))
        before = p.numpy().copy()
        opt.step()
        after = p.numpy()
        # L1 penalty must move both entries toward zero
        assert abs(after[0]) < abs(before[0])
        assert abs(after[1]) < abs(before[1])
