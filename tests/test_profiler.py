"""First coverage for paddle_tpu.profiler: summary table, chrome export
(incl. the merged step-timeline counter events), RecordEvent nesting,
ProfileStep spans from step(), timer_only, and the empty-buffer
summary."""
from __future__ import annotations

import json
import os
import time

import pytest

import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent
from paddle_tpu._native import lib as _lib

pytestmark = pytest.mark.skipif(
    _lib is None, reason="native runtime unavailable (no compiler)")


def _span_names(path):
    data = json.load(open(path))
    return [e["name"] for e in data["traceEvents"]]


class TestFusionCompileSpans:
    def test_fused_compile_lands_as_span(self, tmp_path):
        """A fused program's first (trace+compile) execution inside a
        profiling window emits a fusion_compile[kind] span, so step
        traces attribute the first-call spike (Fusion II satellite)."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.core import fusion
        from paddle_tpu.core.flags import get_flags, set_flags

        prev = get_flags(["FLAGS_eager_fusion", "FLAGS_eager_fusion_reduce"])
        try:
            set_flags({"FLAGS_eager_fusion": 1,
                       "FLAGS_eager_fusion_reduce": 1})
            fusion.clear_cache()  # force a fresh sighting + compile
            x = paddle.to_tensor(
                np.random.default_rng(3).standard_normal((5, 3))
                .astype(np.float32))
            with Profiler():
                for _ in range(3):  # sight -> compile -> steady
                    float(paddle.mean(
                        paddle.cosh(paddle.multiply(x, 0.5))).numpy())
                out = str(tmp_path / "fc.json")
                profiler.export_chrome_tracing(out)
        finally:
            set_flags(prev)
        assert "fusion_compile[reduce]" in _span_names(out)


class TestRecordEvent:
    def test_context_manager_records_span(self, tmp_path):
        with Profiler():
            with RecordEvent("ctx_span"):
                time.sleep(0.001)
            out = str(tmp_path / "t.json")
            profiler.export_chrome_tracing(out)
        assert "ctx_span" in _span_names(out)

    def test_reentrant_begin_end_keeps_both_spans(self, tmp_path):
        ev = RecordEvent("nested")
        with Profiler():
            ev.begin()
            time.sleep(0.002)
            ev.begin()          # before the first end(): must NOT drop
            time.sleep(0.001)   # the first span's start
            ev.end()
            ev.end()
            out = str(tmp_path / "t.json")
            profiler.export_chrome_tracing(out)
        data = json.load(open(out))
        spans = [e for e in data["traceEvents"] if e["name"] == "nested"]
        assert len(spans) == 2
        durs = sorted(float(s["dur"]) for s in spans)
        # LIFO pairing: the inner span is strictly shorter
        assert durs[0] < durs[1]
        assert durs[1] >= 3000  # µs: outer covers both sleeps

    def test_unbalanced_end_is_harmless(self):
        ev = RecordEvent("lonely")
        with Profiler():
            ev.end()  # no begin: no crash, no span recorded


class TestProfilerStep:
    def test_step_emits_profile_step_spans(self, tmp_path):
        prof = Profiler().start()
        time.sleep(0.001)
        prof.step()
        time.sleep(0.001)
        prof.step()
        out = str(tmp_path / "t.json")
        profiler.export_chrome_tracing(out)
        prof.stop()
        names = _span_names(out)
        assert "ProfileStep#1" in names and "ProfileStep#2" in names

    def test_step_windows_are_consecutive(self, tmp_path):
        prof = Profiler().start()
        prof.step()
        prof.step()
        out = str(tmp_path / "t.json")
        profiler.export_chrome_tracing(out)
        prof.stop()
        data = json.load(open(out))
        spans = {e["name"]: e for e in data["traceEvents"]}
        s1, s2 = spans["ProfileStep#1"], spans["ProfileStep#2"]
        assert s2["ts"] == pytest.approx(s1["ts"] + s1["dur"], abs=50)

    def test_timer_only_skips_device_trace(self):
        prof = Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.TPU],
                        timer_only=True)
        prof.start()
        prof.step()
        assert prof._device_dir is None  # device plane never started
        prof.stop()


class TestSummary:
    def test_table_columns_and_aggregation(self):
        with Profiler() as prof:
            for _ in range(3):
                with RecordEvent("agg_span"):
                    time.sleep(0.001)
            table = prof.summary()
        lines = table.splitlines()
        header = lines[0]
        for col in ("name", "calls", "total_ms", "avg_ms", "max_ms",
                    "min_ms", "ratio"):
            assert col in header
        row = next(ln for ln in lines if ln.startswith("agg_span"))
        cells = row.split()
        assert cells[1] == "3"              # calls
        assert float(cells[3]) >= 1.0       # avg >= 1ms
        assert "inf" not in table

    def test_time_units(self):
        with Profiler() as prof:
            with RecordEvent("u"):
                pass
            assert "total_us" in prof.summary(time_unit="us")
            with pytest.raises(ValueError):
                prof.summary(time_unit="fortnights")

    def test_empty_buffer_friendly_message(self):
        prof = Profiler()
        prof.start()
        prof.stop()
        # fresh start cleared the buffer; no spans were recorded after
        prof2 = Profiler().start()
        msg = prof2.summary()
        prof2.stop()
        assert "no events recorded" in msg
        assert "inf" not in msg


class TestChromeExport:
    def test_export_creates_dirs_and_valid_json(self, tmp_path):
        with Profiler():
            with RecordEvent("x"):
                pass
            out = str(tmp_path / "deep" / "dir" / "trace.json")
            profiler.export_chrome_tracing(out)
        assert os.path.exists(out)
        data = json.load(open(out))
        assert isinstance(data["traceEvents"], list)

    def test_step_timer_counters_merged(self, tmp_path):
        from paddle_tpu.observability.timeline import StepTimer
        with Profiler() as prof:
            t = StepTimer("proftest")
            with t.phase("forward"):
                time.sleep(0.001)
            t.step()
            with RecordEvent("span_next_to_counter"):
                pass
            out = str(tmp_path / "merged.json")
            prof.export(out)
        data = json.load(open(out))
        counters = [e for e in data["traceEvents"]
                    if e.get("ph") == "C" and e["name"].startswith(
                        "proftest")]
        spans = [e for e in data["traceEvents"]
                 if e["name"] == "span_next_to_counter"]
        assert counters and spans, "one trace carries spans AND counters"
        assert counters[-1]["args"]["forward"] >= 1.0  # ms
        # counter timestamps share the span clock (same monotonic base)
        assert abs(counters[-1]["ts"] - spans[0]["ts"]) < 60e6

    def test_summary_ignores_merged_counters(self):
        from paddle_tpu.observability.timeline import StepTimer
        with Profiler() as prof:
            t = StepTimer("sumtest")
            with t.phase("fwd"):
                pass
            t.step()
            with RecordEvent("real_span"):
                pass
            table = prof.summary()
        assert "real_span" in table
