"""Every runtime flag must be documented in README.md.

PRs 1/3/4/5 each added FLAGS_* switches; the README's flags reference
is the only place a user can discover them, and it drifts silently.
This test pins the two together: a flag registered anywhere (core
definitions in core/flags.py plus late definitions like
framework/checkpoint.py's checkpoint_fsync) must appear as
``FLAGS_<name>`` somewhere in README.md.
"""
import os
import re

import paddle_tpu  # noqa: F401 — loads every module that defines flags
from paddle_tpu.core.flags import _registry

README = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "README.md")


def test_every_flag_documented_in_readme():
    with open(README, encoding="utf-8") as f:
        readme = f.read()
    documented = set(re.findall(r"FLAGS_([a-z0-9_]+)", readme))
    missing = sorted(set(_registry) - documented)
    assert not missing, (
        f"flags missing from README.md: "
        f"{', '.join('FLAGS_' + m for m in missing)} — document each "
        f"flag (a row in the flags reference table is enough)")


def test_no_stale_flags_in_readme():
    """The reverse direction: README must not document flags that no
    longer exist (renames leave dead docs behind)."""
    with open(README, encoding="utf-8") as f:
        readme = f.read()
    documented = set(re.findall(r"FLAGS_([a-z0-9_]+)", readme))
    stale = sorted(documented - set(_registry))
    assert not stale, (
        f"README.md documents unknown flags: "
        f"{', '.join('FLAGS_' + s for s in stale)}")
