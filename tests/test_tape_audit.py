"""Namespace-wide autograd-tape audit.

The diag/cummax bugs (round 4) were SILENT: a differentiable op built
its output Tensor directly instead of dispatching through apply_op, so
gradients vanished with no error. This audit sweeps every public
single-tensor callable: any float-valued output of a float input must
either carry a tape node or be an explicitly known non-differentiable /
creation op. A new op added without tape dispatch fails here by name.
"""
import inspect

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import autograd as ag
from paddle_tpu.core.tensor import Tensor

# ops whose float output is legitimately detached from the tape
KNOWN_DETACHED = {
    # creation / sampling (output independent of the input's VALUE path
    # or drawn from RNG)
    "bernoulli", "empty", "empty_like", "full_like", "normal", "ones",
    "ones_like", "rand", "randn", "randint_like", "standard_normal",
    "uniform", "zeros", "zeros_like", "to_tensor", "clone_detached",
    "poisson", "multinomial", "rand_like",
    # value-independent / zero-derivative by contract
    "sign", "round", "floor", "ceil", "trunc", "floor_divide",
    "floor_mod",
    # set-returning (membership, not a smooth map)
    "unique", "unique_consecutive",
    # data-dependent binning: edges/counts are piecewise-constant in the
    # input (the reference's histogram has no grad kernel either)
    "histogram", "histogram_bin_edges", "histogramdd",
}

# never call these in an audit loop: they switch global modes, touch
# files/devices, or consume the argument destructively
DENYLIST_SUBSTRINGS = (
    "static", "grad", "save", "load", "seed", "set_", "device",
    "flags", "jit", "compile", "summary", "flops", "backward",
    "assign_", "hub", "iinfo", "finfo", "dtype",
)


def _candidates():
    import paddle_tpu.linalg as linalg_ns
    import paddle_tpu.nn.functional as F_ns
    out = []
    seen = set()
    for prefix, ns in (("", paddle), ("linalg.", linalg_ns),
                       ("F.", F_ns)):
        for name in sorted(dir(ns)):
            if name.startswith("_"):
                continue
            if any(s in name for s in DENYLIST_SUBSTRINGS):
                continue
            fn = getattr(ns, name)
            if not callable(fn) or inspect.isclass(fn):
                continue
            if id(fn) in seen:  # re-exports audit once
                continue
            seen.add(id(fn))
            out.append((prefix + name, fn))
    return out


def _sweep(arity):
    base = np.abs(np.random.default_rng(0).normal(size=(4, 4))) \
        .astype(np.float32) + 0.5
    flagged = []
    for name, fn in _candidates():
        if name.endswith("_"):
            continue  # in-place variants mutate their argument
        args = [paddle.to_tensor(base.copy(), stop_gradient=False)
                for _ in range(arity)]
        grad_mode = ag._state.enabled
        recorder = ag._op_recorder
        try:
            out = fn(*args)
        except Exception:
            continue
        finally:
            # a mode-switching callable that slipped the denylist must
            # not poison the rest of the sweep
            ag._state.enabled = grad_mode
            ag._op_recorder = recorder
        outs = out if isinstance(out, (tuple, list)) else [out]
        for o in outs:
            if not isinstance(o, Tensor):
                continue
            if not np.issubdtype(o.dtype, np.floating):
                continue
            bare = name.split(".", 1)[-1]
            if o.stop_gradient and bare not in KNOWN_DETACHED:
                flagged.append(name)
            break
    return sorted(set(flagged))


@pytest.mark.parametrize("arity", [1, 2])
def test_no_silent_tape_drops(arity):
    flagged = _sweep(arity)
    assert not flagged, (
        f"float outputs silently detached from the autograd tape "
        f"(arity {arity}): {flagged} — dispatch through apply_op, or "
        f"add to KNOWN_DETACHED with a justification")
