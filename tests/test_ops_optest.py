"""Op-level tests through the OpTest harness (ref: the per-op tests in
test/legacy_test/test_*_op.py, e.g. test_matmul_v2_op.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


def _rng():
    return np.random.default_rng(0)


class TestMatmulOp(OpTest):
    op_fn = staticmethod(paddle.matmul)
    ref_fn = staticmethod(lambda x, y: x @ y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 8)).astype(np.float32),
                "y": r.normal(size=(8, 6)).astype(np.float32)}


class TestMatmulBatchedOp(OpTest):
    op_fn = staticmethod(paddle.matmul)
    ref_fn = staticmethod(lambda x, y: x @ y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 4, 8)).astype(np.float32),
                "y": r.normal(size=(2, 8, 3)).astype(np.float32)}


class TestAddOp(OpTest):
    op_fn = staticmethod(paddle.add)
    ref_fn = staticmethod(np.add)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}


class TestMulBroadcastOp(OpTest):
    op_fn = staticmethod(paddle.multiply)
    ref_fn = staticmethod(np.multiply)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 1, 4)).astype(np.float32),
                "y": r.normal(size=(5, 1)).astype(np.float32)}


class TestExpOp(OpTest):
    op_fn = staticmethod(paddle.exp)
    ref_fn = staticmethod(np.exp)

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestTanhOp(OpTest):
    op_fn = staticmethod(paddle.tanh)
    ref_fn = staticmethod(np.tanh)

    def inputs(self):
        return {"x": _rng().normal(size=(16,)).astype(np.float32)}


class TestSigmoidOp(OpTest):
    op_fn = staticmethod(F.sigmoid)
    ref_fn = staticmethod(lambda x: 1 / (1 + np.exp(-x)))

    def inputs(self):
        return {"x": _rng().normal(size=(8, 3)).astype(np.float32)}


class TestSoftmaxOp(OpTest):
    op_fn = staticmethod(F.softmax)
    ref_fn = staticmethod(
        lambda x: np.exp(x - x.max(-1, keepdims=True)) /
        np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 7)).astype(np.float32)}


class TestMeanOp(OpTest):
    op_fn = staticmethod(paddle.mean)
    ref_fn = staticmethod(np.mean)

    def inputs(self):
        return {"x": _rng().normal(size=(5, 6)).astype(np.float32)}


class TestSumAxisOp(OpTest):
    op_fn = staticmethod(paddle.sum)
    ref_fn = staticmethod(lambda x, axis: np.sum(x, axis=axis))
    attrs = {"axis": 1}

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5, 2)).astype(np.float32)}


class TestTransposeOp(OpTest):
    op_fn = staticmethod(paddle.transpose)
    ref_fn = staticmethod(lambda x, perm: np.transpose(x, perm))
    attrs = {"perm": [1, 0, 2]}

    def inputs(self):
        return {"x": _rng().normal(size=(3, 4, 2)).astype(np.float32)}


class TestReshapeOp(OpTest):
    op_fn = staticmethod(paddle.reshape)
    ref_fn = staticmethod(lambda x, shape: np.reshape(x, shape))
    attrs = {"shape": [8, 3]}

    def inputs(self):
        return {"x": _rng().normal(size=(4, 6)).astype(np.float32)}


class TestConcatOp(OpTest):
    op_fn = staticmethod(lambda x, y, axis=0: paddle.concat([x, y], axis))
    ref_fn = staticmethod(
        lambda x, y, axis=0: np.concatenate([x, y], axis))
    attrs = {"axis": 1}

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3)).astype(np.float32),
                "y": r.normal(size=(2, 5)).astype(np.float32)}


class TestLayerNormOp(OpTest):
    op_fn = staticmethod(
        lambda x, w, b: F.layer_norm(x, [6], weight=w, bias=b))

    @staticmethod
    def ref_fn(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    dtypes = ("float32",)  # bf16 layernorm tolerance is model-level

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 6)).astype(np.float32),
                "w": r.normal(size=(6,)).astype(np.float32),
                "b": r.normal(size=(6,)).astype(np.float32)}


class TestGeluOp(OpTest):
    op_fn = staticmethod(F.gelu)

    @staticmethod
    def ref_fn(x):
        from scipy.special import erf  # pragma: no cover - fallback below
        return 0.5 * x * (1 + erf(x / np.sqrt(2)))

    def inputs(self):
        return {"x": _rng().normal(size=(10,)).astype(np.float32)}

    def test_check_output(self):
        try:
            import scipy  # noqa: F401
        except ImportError:
            import math
            type(self).ref_fn = staticmethod(
                lambda x: np.asarray([0.5 * v * (1 + math.erf(v / 2 ** 0.5))
                                      for v in x.reshape(-1)],
                                     np.float32).reshape(x.shape))
        super().test_check_output()


class TestWhereOp(OpTest):
    op_fn = staticmethod(paddle.where)
    ref_fn = staticmethod(np.where)
    grad_inputs = ["x", "y"]

    def inputs(self):
        r = _rng()
        return {"cond": r.random(size=(4, 4)) > 0.5,
                "x": r.normal(size=(4, 4)).astype(np.float32),
                "y": r.normal(size=(4, 4)).astype(np.float32)}
