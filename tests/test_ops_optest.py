"""Op-level tests through the OpTest harness (ref: the per-op tests in
test/legacy_test/test_*_op.py, e.g. test_matmul_v2_op.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


def _rng():
    return np.random.default_rng(0)


class TestMatmulOp(OpTest):
    op_fn = staticmethod(paddle.matmul)
    ref_fn = staticmethod(lambda x, y: x @ y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 8)).astype(np.float32),
                "y": r.normal(size=(8, 6)).astype(np.float32)}


class TestMatmulBatchedOp(OpTest):
    op_fn = staticmethod(paddle.matmul)
    ref_fn = staticmethod(lambda x, y: x @ y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 4, 8)).astype(np.float32),
                "y": r.normal(size=(2, 8, 3)).astype(np.float32)}


class TestAddOp(OpTest):
    op_fn = staticmethod(paddle.add)
    ref_fn = staticmethod(np.add)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}


class TestMulBroadcastOp(OpTest):
    op_fn = staticmethod(paddle.multiply)
    ref_fn = staticmethod(np.multiply)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 1, 4)).astype(np.float32),
                "y": r.normal(size=(5, 1)).astype(np.float32)}


class TestExpOp(OpTest):
    op_fn = staticmethod(paddle.exp)
    ref_fn = staticmethod(np.exp)

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestTanhOp(OpTest):
    op_fn = staticmethod(paddle.tanh)
    ref_fn = staticmethod(np.tanh)

    def inputs(self):
        return {"x": _rng().normal(size=(16,)).astype(np.float32)}


class TestSigmoidOp(OpTest):
    op_fn = staticmethod(F.sigmoid)
    ref_fn = staticmethod(lambda x: 1 / (1 + np.exp(-x)))

    def inputs(self):
        return {"x": _rng().normal(size=(8, 3)).astype(np.float32)}


class TestSoftmaxOp(OpTest):
    op_fn = staticmethod(F.softmax)
    ref_fn = staticmethod(
        lambda x: np.exp(x - x.max(-1, keepdims=True)) /
        np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 7)).astype(np.float32)}


class TestMeanOp(OpTest):
    op_fn = staticmethod(paddle.mean)
    ref_fn = staticmethod(np.mean)

    def inputs(self):
        return {"x": _rng().normal(size=(5, 6)).astype(np.float32)}


class TestSumAxisOp(OpTest):
    op_fn = staticmethod(paddle.sum)
    ref_fn = staticmethod(lambda x, axis: np.sum(x, axis=axis))
    attrs = {"axis": 1}

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5, 2)).astype(np.float32)}


class TestTransposeOp(OpTest):
    op_fn = staticmethod(paddle.transpose)
    ref_fn = staticmethod(lambda x, perm: np.transpose(x, perm))
    attrs = {"perm": [1, 0, 2]}

    def inputs(self):
        return {"x": _rng().normal(size=(3, 4, 2)).astype(np.float32)}


class TestReshapeOp(OpTest):
    op_fn = staticmethod(paddle.reshape)
    ref_fn = staticmethod(lambda x, shape: np.reshape(x, shape))
    attrs = {"shape": [8, 3]}

    def inputs(self):
        return {"x": _rng().normal(size=(4, 6)).astype(np.float32)}


class TestConcatOp(OpTest):
    op_fn = staticmethod(lambda x, y, axis=0: paddle.concat([x, y], axis))
    ref_fn = staticmethod(
        lambda x, y, axis=0: np.concatenate([x, y], axis))
    attrs = {"axis": 1}

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3)).astype(np.float32),
                "y": r.normal(size=(2, 5)).astype(np.float32)}


class TestLayerNormOp(OpTest):
    op_fn = staticmethod(
        lambda x, w, b: F.layer_norm(x, [6], weight=w, bias=b))

    @staticmethod
    def ref_fn(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    dtypes = ("float32",)  # bf16 layernorm tolerance is model-level

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 6)).astype(np.float32),
                "w": r.normal(size=(6,)).astype(np.float32),
                "b": r.normal(size=(6,)).astype(np.float32)}


class TestGeluOp(OpTest):
    op_fn = staticmethod(F.gelu)

    @staticmethod
    def ref_fn(x):
        from scipy.special import erf  # pragma: no cover - fallback below
        return 0.5 * x * (1 + erf(x / np.sqrt(2)))

    def inputs(self):
        return {"x": _rng().normal(size=(10,)).astype(np.float32)}

    def test_check_output(self):
        try:
            import scipy  # noqa: F401
        except ImportError:
            import math
            type(self).ref_fn = staticmethod(
                lambda x: np.asarray([0.5 * v * (1 + math.erf(v / 2 ** 0.5))
                                      for v in x.reshape(-1)],
                                     np.float32).reshape(x.shape))
        super().test_check_output()


class TestWhereOp(OpTest):
    op_fn = staticmethod(paddle.where)
    ref_fn = staticmethod(np.where)
    grad_inputs = ["x", "y"]

    def inputs(self):
        r = _rng()
        return {"cond": r.random(size=(4, 4)) > 0.5,
                "x": r.normal(size=(4, 4)).astype(np.float32),
                "y": r.normal(size=(4, 4)).astype(np.float32)}


# ---------------------------------------------------------------------------
# round-4 depth expansion (VERDICT r3 weak item 6): conv/pool/norm/
# embedding/index/reduce/shape ops through the same dual-mode
# (eager + jit) fp32+bf16 check_output / full finite-difference
# check_grad harness
# ---------------------------------------------------------------------------


def _np_conv2d(x, w, stride=1, pad=0):
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


class TestConv2dOp(OpTest):
    op_fn = staticmethod(lambda x, w: F.conv2d(x, w, stride=1, padding=1))
    ref_fn = staticmethod(lambda x, w: _np_conv2d(x, w, 1, 1))
    # central differences through a 27-tap contraction accumulate FD
    # noise; the reference white-lists conv thresholds the same way
    # (op_threshold_white_list.py)
    grad_rtol = 0.15
    # f32 FD rounding on the O(100) quadratic loss dominates at
    # eps=1e-3 (isolated-run flake: loss*eps_mach/eps ~ rel err
    # 0.2); the wider step cuts the cancellation noise 10x, the
    # TestConv1dOp precedent
    grad_eps = 1e-2

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3, 5, 5)).astype(np.float32),
                "w": r.normal(size=(4, 3, 3, 3)).astype(np.float32)}


class TestConv2dStridedOp(OpTest):
    op_fn = staticmethod(lambda x, w: F.conv2d(x, w, stride=2, padding=0))
    ref_fn = staticmethod(lambda x, w: _np_conv2d(x, w, 2, 0))
    grad_rtol = 0.15
    grad_eps = 1e-2

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(1, 2, 6, 6)).astype(np.float32),
                "w": r.normal(size=(3, 2, 2, 2)).astype(np.float32)}


def _np_maxpool(x, k, s):
    n, c, h, w = x.shape
    oh, ow = (h - k) // s + 1, (w - k) // s + 1
    out = np.full((n, c, oh, ow), -np.inf, np.float32)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * s:i * s + k,
                                j * s:j * s + k].max(axis=(2, 3))
    return out


class TestMaxPool2dOp(OpTest):
    op_fn = staticmethod(lambda x: F.max_pool2d(x, 2, stride=2))
    ref_fn = staticmethod(lambda x: _np_maxpool(x, 2, 2))
    grad_inputs = ()  # FD at max ties is ill-defined; value check only

    def inputs(self):
        return {"x": _rng().normal(size=(2, 2, 6, 6))
                .astype(np.float32)}


class TestAvgPool2dOp(OpTest):
    op_fn = staticmethod(lambda x: F.avg_pool2d(x, 2, stride=2))

    @staticmethod
    def ref_fn(x):
        n, c, h, w = x.shape
        return x.reshape(n, c, h // 2, 2, w // 2, 2).mean(axis=(3, 5))

    def inputs(self):
        return {"x": _rng().normal(size=(2, 2, 6, 6))
                .astype(np.float32)}


class TestLayerNormNoAffineOp(OpTest):
    # weight=None/bias=None: the NO-affine branch
    op_fn = staticmethod(lambda x: F.layer_norm(x, 8))

    @staticmethod
    def ref_fn(x):
        m_ = x.mean(-1, keepdims=True)
        v_ = x.var(-1, keepdims=True)
        return (x - m_) / np.sqrt(v_ + 1e-5)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 8)).astype(np.float32)}


class TestGroupNormOp(OpTest):
    op_fn = staticmethod(lambda x: F.group_norm(x, num_groups=2))

    @staticmethod
    def ref_fn(x):
        n, c, h, w = x.shape
        g = x.reshape(n, 2, c // 2, h, w)
        mu = g.mean(axis=(2, 3, 4), keepdims=True)
        var = g.var(axis=(2, 3, 4), keepdims=True)
        return ((g - mu) / np.sqrt(var + 1e-5)).reshape(n, c, h, w)

    def inputs(self):
        return {"x": _rng().normal(size=(2, 4, 3, 3))
                .astype(np.float32)}


class TestEmbeddingOp(OpTest):
    op_fn = staticmethod(lambda ids, w: F.embedding(ids, w))
    ref_fn = staticmethod(lambda ids, w: w[ids])
    grad_inputs = ("w",)

    def inputs(self):
        r = _rng()
        return {"ids": r.integers(0, 10, (3, 4)).astype(np.int64),
                "w": r.normal(size=(10, 6)).astype(np.float32)}


class TestGatherOp(OpTest):
    op_fn = staticmethod(lambda x, idx: paddle.gather(x, idx))
    ref_fn = staticmethod(lambda x, idx: x[idx])

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(6, 3)).astype(np.float32),
                "idx": np.array([4, 0, 2], np.int64)}


class TestIndexSelectOp(OpTest):
    op_fn = staticmethod(lambda x, idx: paddle.index_select(x, idx,
                                                            axis=1))
    ref_fn = staticmethod(lambda x, idx: x[:, idx])

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 5)).astype(np.float32),
                "idx": np.array([1, 3], np.int64)}


class TestCumsumOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.cumsum(x, axis=1))
    ref_fn = staticmethod(lambda x: np.cumsum(x, axis=1))

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestTopkValuesOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.topk(x, k=3)[0])
    ref_fn = staticmethod(lambda x: -np.sort(-x, axis=-1)[..., :3])
    grad_inputs = ()  # ties make FD ill-defined

    def inputs(self):
        return {"x": _rng().normal(size=(4, 7)).astype(np.float32)}


class TestSortOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.sort(x, axis=-1))
    ref_fn = staticmethod(lambda x: np.sort(x, axis=-1))
    grad_inputs = ()

    def inputs(self):
        return {"x": _rng().normal(size=(3, 6)).astype(np.float32)}


class TestPadOp(OpTest):
    # full per-dim pair form (len == 2*ndim); the short spatial form is
    # for 3+D NCHW-style inputs
    op_fn = staticmethod(lambda x: paddle.nn.functional.pad(
        x, [1, 2, 0, 1], value=0.5))
    ref_fn = staticmethod(lambda x: np.pad(
        x, ((1, 2), (0, 1)), constant_values=0.5))

    def inputs(self):
        return {"x": _rng().normal(size=(2, 4)).astype(np.float32)}


class TestConcatAxis1Op(OpTest):
    op_fn = staticmethod(lambda x, y: paddle.concat([x, y], axis=1))
    ref_fn = staticmethod(lambda x, y: np.concatenate([x, y], axis=1))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3)).astype(np.float32),
                "y": r.normal(size=(2, 2)).astype(np.float32)}


class TestMeanAxisKeepdimOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.mean(x, axis=1, keepdim=True))
    ref_fn = staticmethod(lambda x: x.mean(axis=1, keepdims=True))

    def inputs(self):
        return {"x": _rng().normal(size=(3, 4, 2)).astype(np.float32)}


class TestLogsumexpOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.logsumexp(x, axis=-1))

    @staticmethod
    def ref_fn(x):
        m = x.max(-1, keepdims=True)
        return (m + np.log(np.exp(x - m).sum(-1, keepdims=True)))[..., 0]

    def inputs(self):
        return {"x": _rng().normal(size=(4, 6)).astype(np.float32)}


class TestClipOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.clip(x, -0.5, 0.5))
    ref_fn = staticmethod(lambda x: np.clip(x, -0.5, 0.5))
    grad_inputs = ()  # FD straddles the clamp kinks

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestWhereDerivedCondOp(OpTest):
    """where with a condition derived from an operand (the original
    TestWhereOp covers an explicit bool cond input)."""
    op_fn = staticmethod(lambda x, y: paddle.where(x > 0, x, y))
    ref_fn = staticmethod(lambda x, y: np.where(x > 0, x, y))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}


class TestMatmulTransposeOp(OpTest):
    op_fn = staticmethod(lambda x, y: paddle.matmul(
        x, y, transpose_x=False, transpose_y=True))
    ref_fn = staticmethod(lambda x, y: x @ y.T)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 6)).astype(np.float32),
                "y": r.normal(size=(5, 6)).astype(np.float32)}


class TestLinearOp(OpTest):
    op_fn = staticmethod(lambda x, w, b: F.linear(x, w, b))
    ref_fn = staticmethod(lambda x, w, b: x @ w + b)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "w": r.normal(size=(4, 5)).astype(np.float32),
                "b": r.normal(size=(5,)).astype(np.float32)}


class TestGeluTanhOp(OpTest):
    op_fn = staticmethod(F.gelu)
    attrs = {"approximate": True}

    @staticmethod
    def ref_fn(x, approximate=True):
        return 0.5 * x * (1.0 + np.tanh(
            np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 5)).astype(np.float32)}


class TestLogSoftmaxOp(OpTest):
    op_fn = staticmethod(lambda x: F.log_softmax(x, axis=-1))

    @staticmethod
    def ref_fn(x):
        m = x.max(-1, keepdims=True)
        lse = m + np.log(np.exp(x - m).sum(-1, keepdims=True))
        return x - lse

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestSquaredL2DistanceishOp(OpTest):
    """p-norm over an axis (ref test_p_norm_op)."""
    op_fn = staticmethod(lambda x: paddle.linalg.norm(x, p=2, axis=1))
    ref_fn = staticmethod(lambda x: np.sqrt((x * x).sum(axis=1)))

    def inputs(self):
        return {"x": _rng().normal(size=(3, 6)).astype(np.float32)}


class TestInterpolateNearestOp(OpTest):
    op_fn = staticmethod(lambda x: F.interpolate(x, scale_factor=2,
                                                 mode="nearest"))
    ref_fn = staticmethod(lambda x: x.repeat(2, axis=2).repeat(2, axis=3))

    def inputs(self):
        return {"x": _rng().normal(size=(1, 2, 3, 3))
                .astype(np.float32)}


class TestCrossEntropySmallOp(OpTest):
    op_fn = staticmethod(lambda lg, lb: F.cross_entropy(lg, lb))
    grad_inputs = ("logits",)

    @staticmethod
    def ref_fn(lg, lb):
        m = lg.max(-1, keepdims=True)
        logp = lg - (m + np.log(np.exp(lg - m).sum(-1, keepdims=True)))
        return np.array(
            -logp[np.arange(lg.shape[0]), lb].mean(), np.float32)

    def inputs(self):
        r = _rng()
        return {"logits": r.normal(size=(6, 5)).astype(np.float32),
                "labels": r.integers(0, 5, (6,)).astype(np.int64)}


# ---------------------------------------------------------------------------
# round-4 second batch: transpose-conv/depthwise, batched matmul,
# shape/index manipulation, activations, losses
# ---------------------------------------------------------------------------


class TestConvTranspose2dOp(OpTest):
    op_fn = staticmethod(lambda x, w: F.conv2d_transpose(
        x, w, stride=2, padding=0))
    grad_rtol = 0.15
    grad_eps = 1e-2

    @staticmethod
    def ref_fn(x, w):
        # w: [cin, cout, kh, kw]; scatter each input pixel's kernel
        n, cin, h, wd = x.shape
        _, cout, kh, kw = w.shape
        oh, ow = (h - 1) * 2 + kh, (wd - 1) * 2 + kw
        out = np.zeros((n, cout, oh, ow), np.float32)
        for i in range(h):
            for j in range(wd):
                out[:, :, i * 2:i * 2 + kh, j * 2:j * 2 + kw] += \
                    np.einsum("nc,cokl->nokl", x[:, :, i, j], w)
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(1, 2, 3, 3)).astype(np.float32),
                "w": r.normal(size=(2, 3, 2, 2)).astype(np.float32)}


class TestDepthwiseConv2dOp(OpTest):
    op_fn = staticmethod(lambda x, w: F.conv2d(x, w, stride=1,
                                               padding=0, groups=2))
    grad_rtol = 0.15
    grad_eps = 1e-2

    @staticmethod
    def ref_fn(x, w):
        # groups=2: channels split in half, each half convolved with its
        # own filter bank
        halves = []
        for g in range(2):
            xg = x[:, g:g + 1]
            wg = w[g:g + 1, :]
            halves.append(_np_conv2d(xg, wg, 1, 0))
        return np.concatenate(halves, axis=1)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(1, 2, 5, 5)).astype(np.float32),
                "w": r.normal(size=(2, 1, 3, 3)).astype(np.float32)}


class TestBmmOp(OpTest):
    op_fn = staticmethod(paddle.bmm)
    ref_fn = staticmethod(lambda x, y: np.einsum("bij,bjk->bik", x, y))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4, 5)).astype(np.float32),
                "y": r.normal(size=(3, 5, 2)).astype(np.float32)}


class TestStackOp(OpTest):
    op_fn = staticmethod(lambda x, y: paddle.stack([x, y], axis=1))
    ref_fn = staticmethod(lambda x, y: np.stack([x, y], axis=1))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}


class TestFlipRollOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.roll(paddle.flip(x, axis=[1]),
                                               shifts=2, axis=0))
    ref_fn = staticmethod(lambda x: np.roll(np.flip(x, axis=1), 2,
                                            axis=0))

    def inputs(self):
        return {"x": _rng().normal(size=(5, 4)).astype(np.float32)}


class TestTrilOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.tril(x, diagonal=-1))
    ref_fn = staticmethod(lambda x: np.tril(x, k=-1))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestDiagOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.diag(x))
    ref_fn = staticmethod(np.diag)

    def inputs(self):
        return {"x": _rng().normal(size=(6,)).astype(np.float32)}


class TestTakeAlongAxisOp(OpTest):
    op_fn = staticmethod(lambda x, idx: paddle.take_along_axis(
        x, idx, axis=1))
    ref_fn = staticmethod(lambda x, idx: np.take_along_axis(x, idx, 1))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 5)).astype(np.float32),
                "idx": r.integers(0, 5, (3, 2)).astype(np.int64)}


class TestExpandOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.expand(x, [4, 3, 5]))
    ref_fn = staticmethod(lambda x: np.broadcast_to(x, (4, 3, 5)))

    def inputs(self):
        return {"x": _rng().normal(size=(1, 3, 5)).astype(np.float32)}


class TestPreluOp(OpTest):
    op_fn = staticmethod(lambda x, a: F.prelu(x, a))
    ref_fn = staticmethod(lambda x, a: np.where(x > 0, x, a * x))
    grad_inputs = ("x",)  # FD at the kink for x entries near 0 is fine
    # with the chosen data; alpha grads are exact linear sums

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32) + 0.05,
                "a": np.array([0.25], np.float32)}


class TestSiluOp(OpTest):
    op_fn = staticmethod(F.silu)
    ref_fn = staticmethod(lambda x: x / (1 + np.exp(-x)))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestSoftplusOp(OpTest):
    op_fn = staticmethod(F.softplus)
    ref_fn = staticmethod(lambda x: np.log1p(np.exp(-np.abs(x)))
                          + np.maximum(x, 0))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 4)).astype(np.float32)}


class TestMseLossOp(OpTest):
    op_fn = staticmethod(lambda x, y: F.mse_loss(x, y))
    ref_fn = staticmethod(
        lambda x, y: np.array(((x - y) ** 2).mean(), np.float32))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 5)).astype(np.float32),
                "y": r.normal(size=(4, 5)).astype(np.float32)}


class TestKLDivOp(OpTest):
    op_fn = staticmethod(lambda lp, t: F.kl_div(lp, t,
                                                reduction="sum"))
    ref_fn = staticmethod(
        lambda lp, t: np.array((t * (np.log(t) - lp)).sum(), np.float32))
    grad_inputs = ("logp",)

    def inputs(self):
        r = _rng()
        t = np.abs(r.normal(size=(3, 4))).astype(np.float32) + 0.1
        t = t / t.sum(-1, keepdims=True)
        return {"logp": r.normal(size=(3, 4)).astype(np.float32),
                "target": t}


class TestOuterOp(OpTest):
    op_fn = staticmethod(paddle.outer)
    ref_fn = staticmethod(np.outer)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(5,)).astype(np.float32),
                "y": r.normal(size=(4,)).astype(np.float32)}


class TestKronOp(OpTest):
    op_fn = staticmethod(paddle.kron)
    ref_fn = staticmethod(np.kron)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3)).astype(np.float32),
                "y": r.normal(size=(3, 2)).astype(np.float32)}


# ---------------------------------------------------------------------------
# round-4 third batch: shape manipulation, fused linear forms,
# normalization, pixel ops
# ---------------------------------------------------------------------------


class TestSqueezeUnsqueezeOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.unsqueeze(
        paddle.squeeze(x, axis=1), axis=0))
    ref_fn = staticmethod(lambda x: np.expand_dims(np.squeeze(x, 1), 0))

    def inputs(self):
        return {"x": _rng().normal(size=(3, 1, 4)).astype(np.float32)}


class TestTileOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.tile(x, [2, 3]))
    ref_fn = staticmethod(lambda x: np.tile(x, (2, 3)))

    def inputs(self):
        return {"x": _rng().normal(size=(2, 4)).astype(np.float32)}


class TestChunkFirstOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.chunk(x, 3, axis=1)[0])
    ref_fn = staticmethod(lambda x: x[:, :2])

    def inputs(self):
        return {"x": _rng().normal(size=(3, 6)).astype(np.float32)}


class TestAddmmOp(OpTest):
    op_fn = staticmethod(lambda inp, a, b: paddle.addmm(
        inp, a, b, beta=0.5, alpha=2.0))
    ref_fn = staticmethod(lambda inp, a, b: 0.5 * inp + 2.0 * (a @ b))

    def inputs(self):
        r = _rng()
        return {"inp": r.normal(size=(3, 5)).astype(np.float32),
                "a": r.normal(size=(3, 4)).astype(np.float32),
                "b": r.normal(size=(4, 5)).astype(np.float32)}


class TestPutAlongAxisOp(OpTest):
    op_fn = staticmethod(lambda x, idx, v: paddle.put_along_axis(
        x, idx, v, axis=1))

    @staticmethod
    def ref_fn(x, idx, v):
        out = x.copy()
        np.put_along_axis(out, idx, v, axis=1)
        return out

    def inputs(self):
        # seeded indices have no within-row duplicates, so the
        # scatter-overwrite gradient (zero at overwritten x positions,
        # pass-through for v) is FD-checkable
        r = _rng()
        return {"x": r.normal(size=(3, 5)).astype(np.float32),
                "idx": r.integers(0, 5, (3, 2)).astype(np.int64),
                "v": r.normal(size=(3, 2)).astype(np.float32)}


class TestInstanceNormOp(OpTest):
    op_fn = staticmethod(lambda x: F.instance_norm(x))

    @staticmethod
    def ref_fn(x):
        mu = x.mean(axis=(2, 3), keepdims=True)
        var = x.var(axis=(2, 3), keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5)

    def inputs(self):
        return {"x": _rng().normal(size=(2, 3, 4, 4))
                .astype(np.float32)}


class TestHardswishOp(OpTest):
    op_fn = staticmethod(F.hardswish)
    ref_fn = staticmethod(
        lambda x: x * np.clip(x + 3, 0, 6) / 6)
    # seeded samples all sit > grad_eps from the ±3 kinks, so central
    # differences are well-defined

    def inputs(self):
        return {"x": _rng().normal(size=(4, 5)).astype(np.float32) * 3}


class TestMishOp(OpTest):
    op_fn = staticmethod(F.mish)

    @staticmethod
    def ref_fn(x):
        sp = np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)
        return x * np.tanh(sp)

    def inputs(self):
        return {"x": _rng().normal(size=(4, 5)).astype(np.float32)}


class TestPixelShuffleOp(OpTest):
    op_fn = staticmethod(lambda x: F.pixel_shuffle(x, 2))

    @staticmethod
    def ref_fn(x):
        n, c, h, w = x.shape
        oc = c // 4
        y = x.reshape(n, oc, 2, 2, h, w)
        y = y.transpose(0, 1, 4, 2, 5, 3)
        return y.reshape(n, oc, h * 2, w * 2)

    def inputs(self):
        return {"x": _rng().normal(size=(1, 8, 3, 3))
                .astype(np.float32)}


class TestEinsumContractionOp(OpTest):
    op_fn = staticmethod(lambda x, y: paddle.einsum("ij,jk->ik", x, y))
    ref_fn = staticmethod(lambda x, y: np.einsum("ij,jk->ik", x, y))

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(4, 5)).astype(np.float32)}


# ---------------------------------------------------------------------------
# Batch 4 (r5, VERDICT r4 #9): conv variants, pooling edge cases, pad
# modes, index ops, norm family, math/reduction long tail — the
# reference's most-tested op families (test/legacy_test/test_*_op.py).

class TestConv1dOp(OpTest):
    op_fn = staticmethod(F.conv1d)
    attrs = {"stride": 1, "padding": 1}

    @staticmethod
    def ref_fn(x, w, stride=1, padding=1):
        import numpy as np
        n, c, l = x.shape
        o, _, k = w.shape
        xp = np.pad(x, ((0, 0), (0, 0), (padding, padding)))
        lo = (l + 2 * padding - k) // stride + 1
        out = np.zeros((n, o, lo), np.float32)
        for i in range(lo):
            seg = xp[:, :, i * stride:i * stride + k]
            out[:, :, i] = np.einsum("ncK,ocK->no", seg, w)
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3, 8)).astype(np.float32),
                "w": r.normal(size=(4, 3, 3)).astype(np.float32)}


class TestConv2dGroupsOp(OpTest):
    op_fn = staticmethod(F.conv2d)
    attrs = {"groups": 2}
    grad_eps = 1e-2  # f32 FD noise at 1e-3 on the quadratic loss

    @staticmethod
    def ref_fn(x, w, groups=2):
        import numpy as np
        n, c, h, ww = x.shape
        o, cg, kh, kw = w.shape
        og = o // groups
        out = np.zeros((n, o, h - kh + 1, ww - kw + 1), np.float32)
        for g in range(groups):
            xs = x[:, g * cg:(g + 1) * cg]
            for i in range(out.shape[2]):
                for j in range(out.shape[3]):
                    seg = xs[:, :, i:i + kh, j:j + kw]
                    out[:, g * og:(g + 1) * og, i, j] = np.einsum(
                        "nchw,ochw->no", seg, w[g * og:(g + 1) * og])
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 4, 5, 5)).astype(np.float32),
                "w": r.normal(size=(4, 2, 3, 3)).astype(np.float32)}


class TestConv2dDilationOp(OpTest):
    op_fn = staticmethod(F.conv2d)
    attrs = {"dilation": 2}

    @staticmethod
    def ref_fn(x, w, dilation=2):
        import numpy as np
        n, c, h, ww = x.shape
        o, _, kh, kw = w.shape
        eh, ew = (kh - 1) * dilation + 1, (kw - 1) * dilation + 1
        out = np.zeros((n, o, h - eh + 1, ww - ew + 1), np.float32)
        for i in range(out.shape[2]):
            for j in range(out.shape[3]):
                seg = x[:, :, i:i + eh:dilation, j:j + ew:dilation]
                out[:, :, i, j] = np.einsum("nchw,ochw->no", seg, w)
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(1, 2, 7, 7)).astype(np.float32),
                "w": r.normal(size=(3, 2, 2, 2)).astype(np.float32)}


class TestConv3dOp(OpTest):
    op_fn = staticmethod(F.conv3d)
    grad_eps = 1e-2  # same f32 FD-noise deflake as TestConv2dOp

    @staticmethod
    def ref_fn(x, w):
        import numpy as np
        n, c, d, h, ww = x.shape
        o, _, kd, kh, kw = w.shape
        out = np.zeros((n, o, d - kd + 1, h - kh + 1, ww - kw + 1),
                       np.float32)
        for a in range(out.shape[2]):
            for i in range(out.shape[3]):
                for j in range(out.shape[4]):
                    seg = x[:, :, a:a + kd, i:i + kh, j:j + kw]
                    out[:, :, a, i, j] = np.einsum(
                        "ncdhw,ocdhw->no", seg, w)
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(1, 2, 4, 4, 4)).astype(np.float32),
                "w": r.normal(size=(3, 2, 2, 2, 2)).astype(np.float32)}


class TestMaxPool1dOp(OpTest):
    op_fn = staticmethod(F.max_pool1d)
    attrs = {"kernel_size": 2, "stride": 2}

    @staticmethod
    def ref_fn(x, kernel_size=2, stride=2):
        n, c, l = x.shape
        lo = (l - kernel_size) // stride + 1
        return np.stack([x[:, :, i * stride:i * stride + kernel_size]
                         .max(-1) for i in range(lo)], axis=-1)

    def inputs(self):
        return {"x": _rng().normal(size=(2, 3, 8)).astype(np.float32)}


class TestMaxPool2dStridedOp(OpTest):
    op_fn = staticmethod(F.max_pool2d)
    attrs = {"kernel_size": 3, "stride": 2}

    @staticmethod
    def ref_fn(x, kernel_size=3, stride=2):
        n, c, h, w = x.shape
        ho = (h - kernel_size) // stride + 1
        wo = (w - kernel_size) // stride + 1
        out = np.zeros((n, c, ho, wo), np.float32)
        for i in range(ho):
            for j in range(wo):
                out[:, :, i, j] = x[:, :, i*2:i*2+3, j*2:j*2+3].max((2, 3))
        return out

    def inputs(self):
        return {"x": _rng().normal(size=(2, 2, 7, 7)).astype(np.float32)}


class TestAvgPool2dPaddedOp(OpTest):
    op_fn = staticmethod(F.avg_pool2d)
    attrs = {"kernel_size": 2, "stride": 2, "padding": 1}
    grad_inputs = ()  # padding-boundary FD is ragged; output check only

    @staticmethod
    def ref_fn(x, kernel_size=2, stride=2, padding=1):
        # exclusive=True (the paddle default): the divisor counts only
        # NON-PAD elements in each window
        n, c, h, w = x.shape
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        cnt = np.pad(np.ones_like(x), ((0, 0), (0, 0), (1, 1), (1, 1)))
        ho = (h + 2 - kernel_size) // stride + 1
        wo = (w + 2 - kernel_size) // stride + 1
        out = np.zeros((n, c, ho, wo), np.float32)
        for i in range(ho):
            for j in range(wo):
                s = xp[:, :, i*2:i*2+2, j*2:j*2+2].sum((2, 3))
                d = cnt[:, :, i*2:i*2+2, j*2:j*2+2].sum((2, 3))
                out[:, :, i, j] = s / d
        return out

    def inputs(self):
        return {"x": _rng().normal(size=(1, 2, 6, 6)).astype(np.float32)}


class TestAdaptiveAvgPool2dOp(OpTest):
    op_fn = staticmethod(F.adaptive_avg_pool2d)
    attrs = {"output_size": 2}

    @staticmethod
    def ref_fn(x, output_size=2):
        n, c, h, w = x.shape
        out = np.zeros((n, c, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                out[:, :, i, j] = x[:, :, i*(h//2):(i+1)*(h//2),
                                    j*(w//2):(j+1)*(w//2)].mean((2, 3))
        return out

    def inputs(self):
        return {"x": _rng().normal(size=(2, 3, 4, 4)).astype(np.float32)}


class TestAdaptiveMaxPool2dOp(OpTest):
    op_fn = staticmethod(F.adaptive_max_pool2d)
    attrs = {"output_size": 2}

    @staticmethod
    def ref_fn(x, output_size=2):
        n, c, h, w = x.shape
        out = np.zeros((n, c, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                out[:, :, i, j] = x[:, :, i*(h//2):(i+1)*(h//2),
                                    j*(w//2):(j+1)*(w//2)].max((2, 3))
        return out

    def inputs(self):
        return {"x": _rng().normal(size=(2, 3, 4, 4)).astype(np.float32)}


class TestPadReflectOp(OpTest):
    op_fn = staticmethod(F.pad)
    attrs = {"pad": [1, 1, 2, 0], "mode": "reflect"}

    @staticmethod
    def ref_fn(x, pad=None, mode=None):
        return np.pad(x, ((0, 0), (0, 0), (2, 0), (1, 1)),
                      mode="reflect")

    def inputs(self):
        return {"x": _rng().normal(size=(1, 2, 4, 5)).astype(np.float32)}


class TestPadReplicateOp(OpTest):
    op_fn = staticmethod(F.pad)
    attrs = {"pad": [2, 1, 1, 1], "mode": "replicate"}

    @staticmethod
    def ref_fn(x, pad=None, mode=None):
        return np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 1)), mode="edge")

    def inputs(self):
        return {"x": _rng().normal(size=(1, 2, 4, 5)).astype(np.float32)}


class TestPadCircularOp(OpTest):
    op_fn = staticmethod(F.pad)
    attrs = {"pad": [1, 1, 1, 1], "mode": "circular"}

    @staticmethod
    def ref_fn(x, pad=None, mode=None):
        return np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="wrap")

    def inputs(self):
        return {"x": _rng().normal(size=(1, 2, 4, 4)).astype(np.float32)}


class TestPadConstantValueOp(OpTest):
    # full-rank pad (len == 2*ndim): per-dim (before, after) pairs
    op_fn = staticmethod(F.pad)
    attrs = {"pad": [0, 1, 1, 2], "mode": "constant", "value": 2.5}

    @staticmethod
    def ref_fn(x, pad=None, mode=None, value=2.5):
        return np.pad(x, ((0, 1), (1, 2)), constant_values=2.5)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 4)).astype(np.float32)}


class TestScatterOp(OpTest):
    op_fn = staticmethod(paddle.scatter)
    grad_inputs = ("x",)

    @staticmethod
    def ref_fn(x, index, updates):
        out = x.copy()
        out[index] = updates
        return out

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(6, 3)).astype(np.float32),
                "index": np.array([1, 4], np.int64),
                "updates": r.normal(size=(2, 3)).astype(np.float32)}


class TestGatherNdOp(OpTest):
    op_fn = staticmethod(paddle.gather_nd)

    @staticmethod
    def ref_fn(x, index):
        return x[tuple(index.T)] if index.shape[-1] == x.ndim else \
            x[tuple(np.moveaxis(index, -1, 0))]

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 5)).astype(np.float32),
                "index": np.array([[0, 1], [3, 2]], np.int64)}


class TestIndexSampleOp(OpTest):
    op_fn = staticmethod(paddle.index_sample)

    @staticmethod
    def ref_fn(x, index):
        return np.take_along_axis(x, index, axis=1)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 6)).astype(np.float32),
                "index": np.array([[0, 2], [1, 1], [5, 0]], np.int64)}


class TestOneHotOp(OpTest):
    op_fn = staticmethod(F.one_hot)
    attrs = {"num_classes": 5}
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, num_classes=5):
        return np.eye(num_classes, dtype=np.float32)[x]

    def inputs(self):
        return {"x": np.array([0, 3, 1, 4], np.int64)}


class TestRollMultiAxisOp(OpTest):
    op_fn = staticmethod(paddle.roll)
    attrs = {"shifts": [1, -2], "axis": [0, 1]}

    @staticmethod
    def ref_fn(x, shifts=None, axis=None):
        return np.roll(x, (1, -2), axis=(0, 1))

    def inputs(self):
        return {"x": _rng().normal(size=(4, 5)).astype(np.float32)}


class TestBatchNormEvalOp(OpTest):
    op_fn = staticmethod(
        lambda x, rm, rv, w, b: F.batch_norm(x, rm, rv, w, b,
                                             training=False))
    grad_inputs = ("x",)

    @staticmethod
    def ref_fn(x, rm, rv, w, b):
        xn = (x - rm[None, :, None, None]) / np.sqrt(
            rv[None, :, None, None] + 1e-5)
        return xn * w[None, :, None, None] + b[None, :, None, None]

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(2, 3, 4, 4)).astype(np.float32),
                "rm": r.normal(size=(3,)).astype(np.float32),
                "rv": np.abs(r.normal(size=(3,))).astype(np.float32) + 1,
                "w": r.normal(size=(3,)).astype(np.float32),
                "b": r.normal(size=(3,)).astype(np.float32)}


class TestBatchNormTrainOp(OpTest):
    """Training BN with the r5 anchored one-pass stats — output parity
    against the straight two-pass NumPy reference."""
    op_fn = staticmethod(
        lambda x, w, b: F.batch_norm(
            paddle.to_tensor(x) if not hasattr(x, "_data") else x,
            paddle.to_tensor(np.zeros(3, np.float32)),
            paddle.to_tensor(np.ones(3, np.float32)),
            w, b, training=True))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, w, b):
        m = x.mean((0, 2, 3), keepdims=True)
        v = x.var((0, 2, 3), keepdims=True)
        xn = (x - m) / np.sqrt(v + 1e-5)
        return xn * w[None, :, None, None] + b[None, :, None, None]

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 3, 4, 4)).astype(np.float32),
                "w": r.normal(size=(3,)).astype(np.float32),
                "b": r.normal(size=(3,)).astype(np.float32)}


class TestRmsNormOp(OpTest):
    op_fn = staticmethod(F.rms_norm)

    @staticmethod
    def ref_fn(x, w):
        v = np.mean(x * x, axis=-1, keepdims=True)
        return x / np.sqrt(v + 1e-6) * w

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 8)).astype(np.float32),
                "w": r.normal(size=(8,)).astype(np.float32)}


class TestNormalizeOp(OpTest):
    op_fn = staticmethod(F.normalize)
    attrs = {"axis": 1}

    @staticmethod
    def ref_fn(x, axis=1):
        n = np.sqrt((x * x).sum(axis=1, keepdims=True))
        return x / np.maximum(n, 1e-12)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestLocalResponseNormOp(OpTest):
    op_fn = staticmethod(F.local_response_norm)
    attrs = {"size": 3}
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, size=3):
        # reference formula: avg_pool of squares over the channel
        # window with ZERO padding -> alpha * sum / size at every
        # position (the denominator stays `size` at the edges)
        n, c, h, w = x.shape
        sq = x * x
        acc = np.zeros_like(x)
        half = size // 2
        for i in range(c):
            lo, hi = max(0, i - half), min(c, i + half + 1)
            acc[:, i] = sq[:, lo:hi].sum(1)
        return x / np.power(1.0 + (1e-4 / size) * acc, 0.75)

    def inputs(self):
        return {"x": _rng().normal(size=(2, 5, 3, 3)).astype(np.float32)}


class TestFloorDivideOp(OpTest):
    op_fn = staticmethod(paddle.floor_divide)
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, y):
        return np.floor_divide(x, y)

    def inputs(self):
        r = _rng()
        return {"x": (r.normal(size=(4, 4)) * 5).astype(np.float32),
                "y": (np.abs(r.normal(size=(4, 4))) + 0.5)
                .astype(np.float32)}


class TestRemainderOp(OpTest):
    op_fn = staticmethod(paddle.remainder)
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, y):
        return np.mod(x, y)

    def inputs(self):
        r = _rng()
        return {"x": (r.normal(size=(4, 4)) * 5).astype(np.float32),
                "y": (np.abs(r.normal(size=(4, 4))) + 0.5)
                .astype(np.float32)}


class TestFmaxFminOp(OpTest):
    op_fn = staticmethod(lambda x, y: paddle.fmax(x, y) +
                         paddle.fmin(x, y))

    @staticmethod
    def ref_fn(x, y):
        return np.fmax(x, y) + np.fmin(x, y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}


class TestTruncFracOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.trunc(x) + paddle.frac(x))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x):
        return np.trunc(x) + (x - np.trunc(x))

    def inputs(self):
        return {"x": (_rng().normal(size=(4, 4)) * 3)
                .astype(np.float32)}


class TestLerpOp(OpTest):
    op_fn = staticmethod(paddle.lerp)

    @staticmethod
    def ref_fn(x, y, w):
        return x + w * (y - x)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32),
                "w": np.abs(r.normal(size=(3, 4))).astype(np.float32)}


class TestHeavisideOp(OpTest):
    op_fn = staticmethod(paddle.heaviside)
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, y):
        return np.heaviside(x, y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 4)).astype(np.float32),
                "y": r.normal(size=(4, 4)).astype(np.float32)}


class TestAtan2Op(OpTest):
    op_fn = staticmethod(paddle.atan2)

    @staticmethod
    def ref_fn(x, y):
        return np.arctan2(x, y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": (np.abs(r.normal(size=(3, 4))) + 0.5)
                .astype(np.float32)}


class TestExpm1Log1pOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.expm1(x) + paddle.log1p(x))

    @staticmethod
    def ref_fn(x):
        return np.expm1(x) + np.log1p(x)

    def inputs(self):
        return {"x": np.abs(_rng().normal(size=(4, 4)))
                .astype(np.float32)}


class TestCopysignOp(OpTest):
    op_fn = staticmethod(paddle.copysign)
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, y):
        return np.copysign(x, y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(4, 4)).astype(np.float32),
                "y": r.normal(size=(4, 4)).astype(np.float32)}


class TestHypotOp(OpTest):
    op_fn = staticmethod(paddle.hypot)

    @staticmethod
    def ref_fn(x, y):
        return np.hypot(x, y)

    def inputs(self):
        r = _rng()
        return {"x": (np.abs(r.normal(size=(3, 4))) + 0.5)
                .astype(np.float32),
                "y": (np.abs(r.normal(size=(3, 4))) + 0.5)
                .astype(np.float32)}


class TestAmaxAminOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.amax(x, axis=1) +
                         paddle.amin(x, axis=1))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x):
        return x.max(1) + x.min(1)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestNanReductionsOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.nansum(x, axis=0) +
                         paddle.nanmean(x, axis=0))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x):
        return np.nansum(x, 0) + np.nanmean(x, 0)

    def inputs(self):
        x = _rng().normal(size=(4, 5)).astype(np.float32)
        x[1, 2] = np.nan
        x[3, 0] = np.nan
        return {"x": x}


class TestProdAxisOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.prod(x, axis=1))

    @staticmethod
    def ref_fn(x):
        return np.prod(x, axis=1)

    def inputs(self):
        return {"x": (_rng().normal(size=(3, 4)) * 0.5 + 1.0)
                .astype(np.float32)}


class TestStdVarOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.std(x, axis=1) +
                         paddle.var(x, axis=1))

    @staticmethod
    def ref_fn(x):
        return x.std(1, ddof=1) + x.var(1, ddof=1)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 6)).astype(np.float32)}


class TestMedianOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.median(x, axis=1))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x):
        return np.median(x, axis=1)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestCumprodOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.cumprod(x, dim=1))

    @staticmethod
    def ref_fn(x):
        return np.cumprod(x, axis=1)

    def inputs(self):
        return {"x": (_rng().normal(size=(3, 4)) * 0.5 + 1.2)
                .astype(np.float32)}


class TestCummaxValuesOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.cummax(x, axis=1)[0])
    grad_inputs = ()

    @staticmethod
    def ref_fn(x):
        return np.maximum.accumulate(x, axis=1)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestIscloseSignOp(OpTest):
    op_fn = staticmethod(
        lambda x, y: paddle.cast(paddle.isclose(x, y), "float32") +
        paddle.sign(x))
    grad_inputs = ()

    @staticmethod
    def ref_fn(x, y):
        return np.isclose(x, y).astype(np.float32) + np.sign(x)

    def inputs(self):
        r = _rng()
        x = r.normal(size=(3, 4)).astype(np.float32)
        y = x.copy()
        y[0, 0] += 1.0
        return {"x": x, "y": y}


class TestFlattenRangeOp(OpTest):
    op_fn = staticmethod(
        lambda x: paddle.flatten(x, start_axis=1, stop_axis=2))

    @staticmethod
    def ref_fn(x):
        return x.reshape(x.shape[0], -1, x.shape[3])

    def inputs(self):
        return {"x": _rng().normal(size=(2, 3, 4, 5)).astype(np.float32)}


class TestSplitSectionsOp(OpTest):
    op_fn = staticmethod(
        lambda x: paddle.split(x, [2, 3], axis=1)[1])

    @staticmethod
    def ref_fn(x):
        return x[:, 2:]

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestUnbindOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.unbind(x, axis=0)[1])

    @staticmethod
    def ref_fn(x):
        return x[1]

    def inputs(self):
        return {"x": _rng().normal(size=(3, 4)).astype(np.float32)}


class TestDiffOp(OpTest):
    op_fn = staticmethod(lambda x: paddle.diff(x, axis=1))

    @staticmethod
    def ref_fn(x):
        return np.diff(x, axis=1)

    def inputs(self):
        return {"x": _rng().normal(size=(3, 5)).astype(np.float32)}


class TestLogaddexpOp(OpTest):
    op_fn = staticmethod(paddle.logaddexp)

    @staticmethod
    def ref_fn(x, y):
        return np.logaddexp(x, y)

    def inputs(self):
        r = _rng()
        return {"x": r.normal(size=(3, 4)).astype(np.float32),
                "y": r.normal(size=(3, 4)).astype(np.float32)}
