"""Aux-subsystem behavior tests: NaN-check mode (SURVEY §5 race-detection
analog), AMP-adjacent numerics tooling."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestNanCheck:
    def test_nan_raises_when_enabled(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            with pytest.raises(FloatingPointError, match="NaN or Inf"):
                _ = x / x  # 0/0 -> NaN
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_clean_ops_pass(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
            y = (x * x).sum()
            assert float(y) == 5.0
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_disabled_by_default(self):
        x = paddle.to_tensor(np.array([0.0], np.float32))
        z = x / x  # NaN, but no flag -> no raise
        assert np.isnan(z.numpy()).all()

    def test_skipped_under_jit(self):
        """The scan is eager-only: tracing with the flag on must not crash
        (regression: tracers passed the isinstance(jax.Array) check)."""
        import jax
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))

            def f(arr):
                import paddle_tpu
                t = paddle_tpu.Tensor(arr)
                return (t * t)._data

            out = jax.jit(f)(x._data)
            assert float(out.sum()) == 5.0
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})


class TestNanCheckBatched:
    def test_batched_flush_names_op(self):
        """Batched NaN checks: device flags accumulate, one host fetch at
        the stride/flush point names the offending (op, output)."""
        import paddle_tpu as paddle
        from paddle_tpu.core import autograd as ag
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 64})
        try:
            x = paddle.to_tensor([1.0, 0.0])
            y = x / x  # 0/0 -> NaN, but no host sync yet
            assert ag._nan_pending, "flag should be pending, not fetched"
            with pytest.raises(FloatingPointError, match="divide"):
                ag.flush_nan_checks()
            assert not ag._nan_pending
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
            ag._nan_pending.clear()

    def test_stride_one_is_synchronous(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 1})
        try:
            x = paddle.to_tensor([0.0])
            with pytest.raises(FloatingPointError):
                x / x
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_backward_flushes(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import autograd as ag
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 64})
        try:
            x = paddle.to_tensor([0.0], stop_gradient=False)
            y = (x / x).sum()
            with pytest.raises(FloatingPointError):
                y.backward()
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
            ag._nan_pending.clear()


class TestAutoTunerRunner:
    """VERDICT round-1 weak item 9: the tuner measures — compiled trials
    with a compile-time memory gate (ref: auto_tuner/tuner.py:21 +
    prune.py OOM pruning)."""

    def _runner(self, hbm=None):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.api import shard_parameter
        from paddle_tpu.distributed.auto_tuner.runner import \
            build_trial_runner

        def make_model():
            paddle.seed(0)
            return paddle.nn.Sequential(
                paddle.nn.Linear(16, 32), paddle.nn.Tanh(),
                paddle.nn.Linear(32, 16))

        def shard_model(model, mesh, cfg):
            for p in model.parameters():
                shard_parameter(p, mesh)

        def make_optimizer(model):
            return paddle.optimizer.SGD(learning_rate=0.01,
                                        parameters=model.parameters())

        def make_batch(cfg):
            rng = np.random.default_rng(0)
            return (rng.standard_normal((16, 16)).astype(np.float32),
                    rng.standard_normal((16, 16)).astype(np.float32))

        def loss_fn(out, label):
            return ((out - label) ** 2).mean()

        return build_trial_runner(make_model, shard_model, make_optimizer,
                                  loss_fn, make_batch,
                                  mesh_axes=("dp", "mp"), steps=2,
                                  hbm_bytes=hbm)

    def test_tuner_measures_compiled_trials(self):
        from paddle_tpu.distributed.auto_tuner import (AutoTuner,
                                                       SearchSpace)
        space = SearchSpace(num_devices=8,
                            dp_degree=[1, 2, 4], mp_degree=[1, 2],
                            global_batch_size=16, num_layers=2)
        tuner = AutoTuner(space, self._runner(), max_trials=4)
        best = tuner.tune()
        assert best is not None and best["metric"] > 0
        measured = [h for h in tuner.recorder.history
                    if h["metric"] is not None]
        assert len(measured) >= 2  # real measurements, multiple configs

    def test_memory_budget_prunes(self):
        trial = self._runner(hbm=1)  # 1 byte: everything over budget
        from paddle_tpu.distributed.auto_tuner.runner import \
            MemoryBudgetExceeded
        with pytest.raises(MemoryBudgetExceeded, match="exceeds budget"):
            trial({"dp_degree": 2, "mp_degree": 1})

    def test_compile_stats_api(self):
        from paddle_tpu.distributed.dist_train import DistTrainStep
        paddle.seed(0)
        net = paddle.nn.Linear(8, 8)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        step = DistTrainStep(net, lambda o, l: ((o - l) ** 2).mean(), opt)
        x = np.ones((4, 8), np.float32)
        mem = step.compile_stats(x, x)
        assert mem.argument_size_in_bytes > 0


def test_trial_runner_times_pipeline_configs():
    """planner v2 pp candidates reach measured trials: a pp_degree>1
    config routes to the compiled-GPipe PipelineTrainStep and returns
    a real throughput (the reference's auto-tuner times pipeline
    configs through its scheduler passes the same way)."""
    import jax
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.distributed.auto_tuner.runner import \
        build_trial_runner

    def make_model():
        paddle.seed(0)
        blocks = [paddle.nn.Sequential(paddle.nn.Linear(16, 16),
                                       paddle.nn.Tanh())
                  for _ in range(4)]
        return paddle.nn.Sequential(*blocks)

    def shard_model(model, mesh, cfg):
        from paddle_tpu.distributed.api import shard_parameter
        for p in model.parameters():
            shard_parameter(p, mesh)

    def make_optimizer(model):
        return paddle.optimizer.SGD(learning_rate=0.01,
                                    parameters=model.parameters())

    def make_batch(cfg):
        rng = np.random.default_rng(0)
        return (rng.standard_normal((16, 16)).astype(np.float32),
                rng.standard_normal((16, 16)).astype(np.float32))

    trial = build_trial_runner(
        make_model, shard_model, make_optimizer,
        lambda out, label: ((out - label) ** 2).mean(), make_batch,
        mesh_axes=("dp",), steps=2)
    flat = trial({"dp_degree": 4})
    piped = trial({"dp_degree": 4, "pp_degree": 2,
                   "pp_schedule": "gpipe"})
    assert flat > 0 and piped > 0
    # unrealizable configs record as FAILED trials, not mislabeled
    # measurements: pp with tensor parallelism, or a schedule the
    # GPipe executor can't deliver
    import pytest as _pytest
    trial_mp = build_trial_runner(
        make_model, shard_model, make_optimizer,
        lambda out, label: ((out - label) ** 2).mean(), make_batch,
        mesh_axes=("dp", "mp"), steps=1)
    with _pytest.raises(ValueError, match="unrealizable"):
        trial_mp({"dp_degree": 2, "mp_degree": 2, "pp_degree": 2})
    with _pytest.raises(ValueError, match="GPipe"):
        trial({"dp_degree": 4, "pp_degree": 2, "pp_schedule": "zb_h1"})
    # pre-execution OOM gate holds for pipeline trials too
    from paddle_tpu.distributed.auto_tuner.runner import \
        MemoryBudgetExceeded
    tight = build_trial_runner(
        make_model, shard_model, make_optimizer,
        lambda out, label: ((out - label) ** 2).mean(), make_batch,
        mesh_axes=("dp",), steps=1, hbm_bytes=1)
    with _pytest.raises(MemoryBudgetExceeded):
        tight({"dp_degree": 4, "pp_degree": 2,
               "pp_schedule": "gpipe"})
