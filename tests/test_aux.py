"""Aux-subsystem behavior tests: NaN-check mode (SURVEY §5 race-detection
analog), AMP-adjacent numerics tooling."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestNanCheck:
    def test_nan_raises_when_enabled(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            with pytest.raises(FloatingPointError, match="NaN or Inf"):
                _ = x / x  # 0/0 -> NaN
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_clean_ops_pass(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
            y = (x * x).sum()
            assert float(y) == 5.0
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_disabled_by_default(self):
        x = paddle.to_tensor(np.array([0.0], np.float32))
        z = x / x  # NaN, but no flag -> no raise
        assert np.isnan(z.numpy()).all()

    def test_skipped_under_jit(self):
        """The scan is eager-only: tracing with the flag on must not crash
        (regression: tracers passed the isinstance(jax.Array) check)."""
        import jax
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))

            def f(arr):
                import paddle_tpu
                t = paddle_tpu.Tensor(arr)
                return (t * t)._data

            out = jax.jit(f)(x._data)
            assert float(out.sum()) == 5.0
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})


class TestNanCheckBatched:
    def test_batched_flush_names_op(self):
        """Batched NaN checks: device flags accumulate, one host fetch at
        the stride/flush point names the offending (op, output)."""
        import paddle_tpu as paddle
        from paddle_tpu.core import autograd as ag
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 64})
        try:
            x = paddle.to_tensor([1.0, 0.0])
            y = x / x  # 0/0 -> NaN, but no host sync yet
            assert ag._nan_pending, "flag should be pending, not fetched"
            with pytest.raises(FloatingPointError, match="divide"):
                ag.flush_nan_checks()
            assert not ag._nan_pending
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
            ag._nan_pending.clear()

    def test_stride_one_is_synchronous(self):
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 1})
        try:
            x = paddle.to_tensor([0.0])
            with pytest.raises(FloatingPointError):
                x / x
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_backward_flushes(self):
        import paddle_tpu as paddle
        from paddle_tpu.core import autograd as ag
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_stride": 64})
        try:
            x = paddle.to_tensor([0.0], stop_gradient=False)
            y = (x / x).sum()
            with pytest.raises(FloatingPointError):
                y.backward()
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})
            ag._nan_pending.clear()
