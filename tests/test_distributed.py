"""DTensor / collective / checkpoint tests on the 8-device CPU mesh.

Mirrors the reference's reshard + semi-auto tests
(ref: test/auto_parallel/reshard_p_to_r.py ... reshard_s_to_s.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh2x4():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])


def test_process_mesh_accessors(mesh2x4):
    assert mesh2x4.shape == [2, 4]
    assert mesh2x4.ndim == 2
    assert mesh2x4.dim_names == ["dp", "mp"]
    assert mesh2x4.process_ids == list(range(8))
    assert mesh2x4.get_dim_size("mp") == 4
    jm = mesh2x4.to_jax_mesh()
    assert jm.axis_names == ("dp", "mp")


def test_shard_tensor_placements(mesh2x4):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
    assert xs._dist_attr is not None
    # value preserved
    np.testing.assert_allclose(np.asarray(xs._data),
                               np.arange(64).reshape(8, 8))
    # actually distributed over 8 devices
    assert len(xs._data.sharding.device_set) == 8


@pytest.mark.parametrize("src,dst", [
    ([0, None], [None, 0]),      # s -> s (different axis) = alltoall-ish
    ([0, None], [None, None]),   # s -> r = allgather
    ([None, None], [0, 1]),      # r -> s = slice
])
def test_reshard_lattice(mesh2x4, src, dst):
    def to_placements(spec):
        return [dist.Shard(d) if d is not None else dist.Replicate()
                for d in spec]
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    a = dist.shard_tensor(x, mesh2x4, to_placements(src))
    b = dist.reshard(a, mesh2x4, to_placements(dst))
    np.testing.assert_allclose(np.asarray(b._data),
                               np.arange(64).reshape(8, 8))


def test_partial_to_replicate_psum(mesh2x4):
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    p = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Replicate()])
    p._dist_attr.placements = [dist.Shard(0), dist.Partial()]
    out = dist.reshard(p, mesh2x4, [dist.Replicate(), dist.Replicate()])
    # partial over the size-4 mp axis sums 4 identical local shards
    np.testing.assert_allclose(np.asarray(out._data), 4.0)


def test_unshard_dtensor(mesh2x4):
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0)])
    xu = dist.unshard_dtensor(xs)
    assert xu._dist_attr is None
    np.testing.assert_allclose(np.asarray(xu._data),
                               np.arange(32).reshape(8, 4))


def test_collectives_single_controller():
    t = paddle.to_tensor(np.ones(4, np.float32))
    task = dist.all_reduce(t)
    task.wait()
    np.testing.assert_allclose(np.asarray(t._data), 1.0)
    out = []
    dist.all_gather(out, t)
    assert len(out) == dist.get_world_size()
    dist.broadcast(t, src=0)
    dist.barrier()


def test_group_bookkeeping():
    g = dist.new_group([0])
    assert g.nranks == 1
    assert g.rank == 0
    assert g.get_group_rank(0) == 0


def test_sharded_checkpoint_roundtrip(tmp_path, mesh2x4):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
    dist.save_state_dict({"w": xs}, str(tmp_path))
    # reshard-on-load: target has a different placement
    tgt = dist.shard_tensor(
        paddle.to_tensor(np.zeros((8, 8), np.float32)), mesh2x4,
        [dist.Replicate(), dist.Shard(0)])
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt._data),
                               np.arange(64).reshape(8, 8))


def test_shard_layer(mesh2x4):
    import paddle_tpu.nn as nn
    layer = nn.Linear(8, 8)
    dist.shard_layer(layer, mesh2x4)
    assert layer.weight._dist_attr is not None
