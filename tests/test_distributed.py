"""DTensor / collective / checkpoint tests on the 8-device CPU mesh.

Mirrors the reference's reshard + semi-auto tests
(ref: test/auto_parallel/reshard_p_to_r.py ... reshard_s_to_s.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture
def mesh2x4():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])


def test_process_mesh_accessors(mesh2x4):
    assert mesh2x4.shape == [2, 4]
    assert mesh2x4.ndim == 2
    assert mesh2x4.dim_names == ["dp", "mp"]
    assert mesh2x4.process_ids == list(range(8))
    assert mesh2x4.get_dim_size("mp") == 4
    jm = mesh2x4.to_jax_mesh()
    assert jm.axis_names == ("dp", "mp")


def test_shard_tensor_placements(mesh2x4):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
    assert xs._dist_attr is not None
    # value preserved
    np.testing.assert_allclose(np.asarray(xs._data),
                               np.arange(64).reshape(8, 8))
    # actually distributed over 8 devices
    assert len(xs._data.sharding.device_set) == 8


@pytest.mark.parametrize("src,dst", [
    ([0, None], [None, 0]),      # s -> s (different axis) = alltoall-ish
    ([0, None], [None, None]),   # s -> r = allgather
    ([None, None], [0, 1]),      # r -> s = slice
])
def test_reshard_lattice(mesh2x4, src, dst):
    def to_placements(spec):
        return [dist.Shard(d) if d is not None else dist.Replicate()
                for d in spec]
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    a = dist.shard_tensor(x, mesh2x4, to_placements(src))
    b = dist.reshard(a, mesh2x4, to_placements(dst))
    np.testing.assert_allclose(np.asarray(b._data),
                               np.arange(64).reshape(8, 8))


def test_partial_to_replicate_psum(mesh2x4):
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    p = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Replicate()])
    p._dist_attr.placements = [dist.Shard(0), dist.Partial()]
    out = dist.reshard(p, mesh2x4, [dist.Replicate(), dist.Replicate()])
    # partial over the size-4 mp axis sums 4 identical local shards
    np.testing.assert_allclose(np.asarray(out._data), 4.0)


def test_unshard_dtensor(mesh2x4):
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0)])
    xu = dist.unshard_dtensor(xs)
    assert xu._dist_attr is None
    np.testing.assert_allclose(np.asarray(xu._data),
                               np.arange(32).reshape(8, 4))


def test_collectives_single_controller():
    t = paddle.to_tensor(np.ones(4, np.float32))
    task = dist.all_reduce(t)
    task.wait()
    np.testing.assert_allclose(np.asarray(t._data), 1.0)
    out = []
    dist.all_gather(out, t)
    assert len(out) == dist.get_world_size()
    dist.broadcast(t, src=0)
    dist.barrier()


def test_group_bookkeeping():
    g = dist.new_group([0])
    assert g.nranks == 1
    assert g.rank == 0
    assert g.get_group_rank(0) == 0


def test_sharded_checkpoint_roundtrip(tmp_path, mesh2x4):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh2x4, [dist.Shard(0), dist.Shard(1)])
    dist.save_state_dict({"w": xs}, str(tmp_path))
    # reshard-on-load: target has a different placement
    tgt = dist.shard_tensor(
        paddle.to_tensor(np.zeros((8, 8), np.float32)), mesh2x4,
        [dist.Replicate(), dist.Shard(0)])
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt._data),
                               np.arange(64).reshape(8, 8))


def test_checkpoint_reshard_to_changed_mesh(tmp_path):
    """Save on a 2x4 mesh, load onto a 1-D 8-mesh with different placement
    (ref: test/auto_parallel/semi_auto_parallel_checkpoint_dedup_tensor.py —
    load must reshard to whatever the destination declares)."""
    src_mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["x", "y"])
    dst_mesh = dist.ProcessMesh(np.arange(8), ["w"])
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, src_mesh, [dist.Shard(0), dist.Shard(1)])
    dist.save_state_dict({"w": xs}, str(tmp_path))
    tgt = dist.shard_tensor(
        paddle.to_tensor(np.zeros((8, 8), np.float32)), dst_mesh,
        [dist.Shard(1)])
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt._data),
                               np.arange(64).reshape(8, 8))


def test_checkpoint_training_resume(tmp_path, rng):
    """Full resume flow: train sharded, save, rebuild on a different mesh,
    load, continue — loss sequence must continue, not restart."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.dist_train import DistTrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion, shard_llama)

    ids = rng.integers(0, 64, (4, 16)).astype(np.int32)
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, use_flash_attention=False)
    crit = LlamaPretrainingCriterion()

    def make(mesh_arr, names, tp):
        mesh = dist.ProcessMesh(mesh_arr, names)
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny(**kw))
        shard_llama(m, mesh, tp_axis=tp, fsdp_axis=None)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        sharding = NamedSharding(mesh.to_jax_mesh(),
                                 P(names[0], None))
        return m, DistTrainStep(m, lambda lg, lb: crit(lg, lb), opt,
                                data_sharding=sharding)

    # reference run: 4 steps straight through
    m_ref, step_ref = make(np.arange(8).reshape(2, 4), ["dp", "mp"], "mp")
    ref_losses = [float(step_ref(ids, ids)) for _ in range(4)]

    # checkpointed run: 2 steps, save (params + opt state), rebuild on a
    # 4x2 mesh, load, 2 more
    m1, step1 = make(np.arange(8).reshape(2, 4), ["dp", "mp"], "mp")
    l1 = [float(step1(ids, ids)) for _ in range(2)]
    dist.save_state_dict({"model": m1.state_dict(),
                          "opt": step1.state_dict()}, str(tmp_path))
    m2, step2 = make(np.arange(8).reshape(4, 2), ["dp", "mp"], "mp")
    opt_sd = step2.state_dict()
    dist.load_state_dict({"model": m2.state_dict(), "opt": opt_sd},
                         str(tmp_path))
    step2.set_state_dict(opt_sd)
    l2 = [float(step2(ids, ids)) for _ in range(2)]
    np.testing.assert_allclose(l1 + l2, ref_losses, rtol=2e-4)


def test_shard_layer(mesh2x4):
    import paddle_tpu.nn as nn
    layer = nn.Linear(8, 8)
    dist.shard_layer(layer, mesh2x4)
    assert layer.weight._dist_attr is not None
