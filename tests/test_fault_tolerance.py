"""Fault-injection harness semantics + TCPStore retry/backoff/deadline
(ISSUE 2: store client ops survive transient transport failures; the
injection utility itself must behave predictably since every robustness
test in the suite leans on it)."""
import socket
import time

import pytest

from paddle_tpu.distributed import TCPStore
from paddle_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def store():
    st = TCPStore("127.0.0.1", _free_port(), is_master=True,
                  world_size=1, backoff=0.01, backoff_max=0.05)
    yield st
    st.shutdown()


class TestFaultInjection:
    def test_unarmed_site_is_noop(self):
        fi.fire("nothing.armed")  # must not raise

    def test_times_and_clear(self):
        fi.inject("x", times=2)
        with pytest.raises(fi.InjectedFault):
            fi.fire("x")
        with pytest.raises(fi.InjectedFault):
            fi.fire("x")
        fi.fire("x")  # exhausted -> disarmed
        fi.inject("x")
        fi.clear("x")
        fi.fire("x")

    def test_skip_arms_the_nth_passage(self):
        fi.inject("x", skip=2, times=1)
        fi.fire("x")
        fi.fire("x")
        with pytest.raises(fi.InjectedFault):
            fi.fire("x")

    def test_kill_point_is_not_an_exception(self):
        fi.inject("x", kill=True)
        with pytest.raises(fi.KillPoint):
            try:
                fi.fire("x")
            except Exception:  # noqa: BLE001 — the point of the test
                pytest.fail("KillPoint must not be caught as Exception")

    def test_write_bytes_truncates(self, tmp_path):
        p = tmp_path / "f"
        fi.inject("w", truncate_at=3)
        with pytest.raises(fi.InjectedFault):
            with open(p, "wb") as f:
                fi.write_bytes("w", f, b"abcdef")
        assert p.read_bytes() == b"abc"

    def test_injected_context_manager_disarms(self):
        with fi.injected("x", times=99):
            with pytest.raises(fi.InjectedFault):
                fi.fire("x")
        fi.fire("x")  # disarmed on exit

    def test_stats_accumulate(self):
        before = fi.stats().get("y", 0)
        fi.inject("y", times=3)
        for _ in range(3):
            with pytest.raises(fi.InjectedFault):
                fi.fire("y")
        assert fi.stats()["y"] == before + 3


class TestStoreRetry:
    def test_transient_failures_absorbed(self, store):
        """The acceptance path: ops under injected transient failures
        succeed via retry/backoff within the deadline."""
        store.set("k", b"v")
        fi.inject("store.get_nowait", exc=ConnectionResetError("flake"),
                  times=3)
        assert store.get_nowait("k") == b"v"
        assert store.op_retries >= 3

    def test_all_ops_retry(self, store):
        store.set("seed", b"1")
        for op, call in [
            ("set", lambda: store.set("a", b"1")),
            ("add", lambda: store.add("cnt", 2)),
            ("get", lambda: store.get("a")),
            ("get_nowait", lambda: store.get_nowait("a")),
            ("delete", lambda: store.delete("a")),
        ]:
            fi.inject(f"store.{op}", exc=BrokenPipeError("flake"),
                      times=2)
            call()  # must succeed through the retries
        assert store.op_retries >= 10

    def test_retry_budget_exhausts_with_clear_error(self, store):
        fi.inject("store.add", exc=ConnectionResetError("flake"),
                  times=999)
        with pytest.raises(ConnectionError,
                           match="retry budget exhausted"):
            store.add("c", 1)

    def test_deadline_exhausts_with_clear_error(self):
        st = TCPStore("127.0.0.1", _free_port(), is_master=True,
                      world_size=1, max_retries=10_000, backoff=0.05,
                      op_deadline=0.4)
        try:
            fi.inject("store.add", exc=ConnectionResetError("flake"),
                      times=10 ** 6)
            t0 = time.monotonic()
            with pytest.raises(ConnectionError,
                               match="deadline exceeded"):
                st.add("c", 1)
            assert time.monotonic() - t0 < 5.0
        finally:
            fi.clear()
            st.shutdown()

    def test_backoff_is_exponential_and_capped(self, store):
        """Four retries at backoff=0.01 cap 0.05 sleep ~0.01+0.02+0.04
        +0.05 — the op takes noticeably longer than a clean one but far
        less than 4x the cap. Jitter is disabled so the deterministic
        schedule stays pinned (the jittered path has its own test)."""
        import paddle_tpu as paddle
        paddle.set_flags({"FLAGS_backoff_full_jitter": 0})
        try:
            fi.inject("store.add", exc=ConnectionResetError("flake"),
                      times=4)
            t0 = time.monotonic()
            store.add("c", 1)
            dt = time.monotonic() - t0
            assert 0.05 < dt < 2.0, dt
        finally:
            paddle.set_flags({"FLAGS_backoff_full_jitter": 1})

    def test_backoff_full_jitter_spreads_retries(self, store):
        """With jitter on (the default) each sleep draws uniform(0,
        bound): a seeded run totals strictly LESS than the
        deterministic 0.12s schedule yet the op still succeeds after
        the same four injected failures."""
        from paddle_tpu.utils import backoff as bk
        bk.seed(1234)
        fi.inject("store.add", exc=ConnectionResetError("flake"),
                  times=4)
        t0 = time.monotonic()
        store.add("c", 1)
        dt = time.monotonic() - t0
        # deterministic schedule is 0.01+0.02+0.04+0.05 = 0.12s before
        # syscall overhead; a jittered run undercuts it w.h.p. and the
        # worst case never exceeds it
        assert dt < 0.5, dt
        # the draw sequence is reproducible after re-seeding
        bk.seed(1234)
        a = [bk.full_jitter(0.05) for _ in range(4)]
        bk.seed(1234)
        b = [bk.full_jitter(0.05) for _ in range(4)]
        assert a == b and all(0.0 <= x <= 0.05 for x in a)

    def test_blocking_get_fails_bounded_on_shutdown(self, store):
        """A blocking get interrupted by server shutdown fails within
        the bounded retry budget (abort or connection error depending
        on who wins the race) — it must never hang the caller."""
        import threading
        threading.Timer(0.3, store.shutdown).start()
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            store.get("never-set-key")
        assert time.monotonic() - t0 < 10.0

    def test_dead_server_fails_within_budget(self, store):
        """Ops against a gone server exhaust the bounded retry budget
        with a clear error instead of hanging."""
        store.shutdown()
        t0 = time.monotonic()
        with pytest.raises(ConnectionError, match="failed after"):
            store.get("k")
        assert time.monotonic() - t0 < 5.0

    def test_barrier_still_works_under_flakes(self, store):
        fi.inject("store.add", exc=ConnectionResetError("flake"),
                  times=2)
        store.barrier("b")  # world_size=1: arrive-and-release
        assert store.op_retries >= 2
