"""Unified telemetry runtime: instrument semantics, registry snapshot
nesting, Prometheus exposition validity, kill switch, the /metrics HTTP
endpoint, the step timeline, and the cross-subsystem integration
(dispatch / fusion / checkpoint / serving counters all landing in ONE
snapshot)."""
from __future__ import annotations

import json
import re
import tempfile
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability.metrics import (
    Counter, Gauge, Histogram, Registry, DEFAULT_BUCKETS)


# ---------------------------------------------------------------------------
# instrument semantics (fresh private registries: no cross-test state)
# ---------------------------------------------------------------------------

class TestInstruments:
    def test_counter_unlabeled(self):
        r = Registry()
        c = r.counter("x.total", "help")
        assert c.value() == 0
        c.inc()
        c.inc(2.5)
        assert c.value() == 3.5

    def test_counter_labeled_cells_are_independent(self):
        c = Registry().counter("ops.total")
        c.inc(op="add")
        c.inc(2, op="mul")
        c.inc(op="add")
        c.inc()  # unlabeled cell is separate
        assert c.value(op="add") == 2
        assert c.value(op="mul") == 2
        assert c.value() == 1

    def test_counter_label_values_keep_python_type(self):
        c = Registry().counter("chain.length")
        c.inc(**{"len": 12})
        series = c.series()
        (key, v), = series.items()
        assert key == (("len", 12),) and v == 1
        assert isinstance(key[0][1], int)  # fusion view needs int back

    def test_gauge_set_inc_dec(self):
        g = Registry().gauge("depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_gauge_pull_function(self):
        g = Registry().gauge("cache.size")
        g.set_function(lambda: 42)
        assert g.value() == 42
        # a dying pull fn degrades to 0, never raises at snapshot time
        g.set_function(lambda: 1 / 0)
        assert g.value() == 0

    def test_histogram_buckets_and_moments(self):
        h = Registry().histogram("lat", buckets=[0.001, 0.01, 0.1, 1.0])
        for v in (0.0005, 0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        d = h.value()
        assert d["count"] == 5
        assert d["min"] == pytest.approx(0.0005)
        assert d["max"] == pytest.approx(5.0)
        assert d["sum"] == pytest.approx(5.5555)
        # per-bucket (non-cumulative) counts: one value per bucket + +Inf
        assert d["buckets"] == {"0.001": 1, "0.01": 1, "0.1": 1,
                                "1": 1, "+Inf": 1}

    def test_histogram_default_buckets_log_spaced(self):
        assert DEFAULT_BUCKETS[0] == pytest.approx(1e-6)
        assert DEFAULT_BUCKETS[-1] == pytest.approx(100.0)
        ratios = {round(b2 / b1, 3) for b1, b2 in
                  zip(DEFAULT_BUCKETS, DEFAULT_BUCKETS[1:])}
        assert ratios == {round(10 ** 0.5, 3)}  # fixed half-decade steps

    def test_histogram_labeled(self):
        h = Registry().histogram("phase.s", buckets=[1.0])
        h.observe(0.5, phase="fwd")
        h.observe(2.0, phase="bwd")
        assert h.value(phase="fwd")["count"] == 1
        assert h.value(phase="bwd")["max"] == 2.0
        assert h.value()["count"] == 0  # unlabeled cell untouched

    def test_get_or_create_idempotent_and_type_checked(self):
        r = Registry()
        a = r.counter("x")
        assert r.counter("x") is a
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_scope_prefixes(self):
        r = Registry()
        s = r.scope("serving")
        c = s.counter("admitted_total")
        assert c.name == "serving.admitted_total"
        assert r.get("serving.admitted_total") is c
        assert s.scope("sub").gauge("g").name == "serving.sub.g"


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------

class TestKillSwitch:
    def test_disabled_instruments_do_not_move(self):
        r = Registry()
        c, g, h = r.counter("c"), r.gauge("g"), r.histogram("h")
        paddle.set_flags({"FLAGS_metrics": 0})
        try:
            c.inc(100)
            c.inc(op="x")
            g.set(9)
            h.observe(1.0)
            assert c.value() == 0 and c.value(op="x") == 0
            assert g.value() == 0
            assert h.value()["count"] == 0
        finally:
            paddle.set_flags({"FLAGS_metrics": 1})
        c.inc()
        assert c.value() == 1  # re-enabled

    def test_enabled_reflects_flag(self):
        assert obs.enabled()
        paddle.set_flags({"FLAGS_metrics": 0})
        try:
            assert not obs.enabled()
        finally:
            paddle.set_flags({"FLAGS_metrics": 1})


# ---------------------------------------------------------------------------
# snapshot nesting + collectors
# ---------------------------------------------------------------------------

class TestSnapshot:
    def test_nested_by_dotted_name(self):
        r = Registry()
        r.counter("serving.admitted_total").inc(3)
        r.gauge("serving.queue_depth").set(2)
        r.counter("a.b.c_total").inc()
        snap = r.snapshot()
        assert snap["serving"]["admitted_total"] == 3
        assert snap["serving"]["queue_depth"] == 2
        assert snap["a"]["b"]["c_total"] == 1

    def test_labeled_series_nest_as_dicts(self):
        r = Registry()
        c = r.counter("ops.by_name")
        c.inc(op="add")
        c.inc(2, op="mul")
        assert r.snapshot()["ops"]["by_name"] == {"add": 1, "mul": 2}

    def test_collector_merged_at_snapshot_time(self):
        r = Registry()
        calls = []

        def collect():
            calls.append(1)
            return {"faults.injected_total": {"store.add": 2},
                    "faults.scalar": 7}

        r.register_collector("faults", collect)
        assert not calls  # pull-based: nothing until snapshot
        snap = r.snapshot()
        assert snap["faults"]["injected_total"] == {"store.add": 2}
        assert snap["faults"]["scalar"] == 7

    def test_broken_collector_is_skipped(self):
        r = Registry()
        r.counter("ok.total").inc()
        r.register_collector("bad", lambda: 1 / 0)
        assert r.snapshot()["ok"]["total"] == 1

    def test_snapshot_is_json_serializable(self):
        r = Registry()
        r.histogram("h").observe(0.01, phase="fwd")
        r.counter("c").inc(**{"len": 3})
        json.dumps(r.snapshot())


# ---------------------------------------------------------------------------
# prometheus exposition golden checks
# ---------------------------------------------------------------------------

_LABEL_VAL = r'"(?:\\.|[^"\\])*"'  # escaped \" \\ \n stay in-line
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    rf"(\{{[a-zA-Z_][a-zA-Z0-9_]*={_LABEL_VAL}"       # first label
    rf"(,[a-zA-Z_][a-zA-Z0-9_]*={_LABEL_VAL})*\}})?"  # more labels
    r" [-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|\+Inf|NaN)$")


def _parse_exposition(text):
    """Minimal exposition-format checker: every line is a HELP/TYPE
    comment or a valid sample; returns {metric_name: [(labels, value)]}."""
    samples = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert len(parts) >= 3, line
            continue
        assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        labels = ""
        if "{" in line:
            labels = line[line.index("{"):line.rindex("}") + 1]
        samples.setdefault(name, []).append(
            (labels, float(line.rsplit(" ", 1)[1])))
    return samples


class TestPrometheus:
    def _registry(self):
        r = Registry()
        c = r.counter("serving.admitted_total", "Requests admitted")
        c.inc(3)
        r.gauge("serving.queue_depth", "Queued").set(2)
        h = r.histogram("rt.seconds", "latency", buckets=[0.01, 0.1, 1.0])
        h.observe(0.005)
        h.observe(0.5)
        h.observe(50.0)
        lc = r.counter("ops.total")
        lc.inc(op="add")
        lc.inc(op='we"ird\nname')  # must be escaped, stay one line
        return r

    def test_every_line_parses(self):
        _parse_exposition(self._registry().render_prometheus())

    def test_names_sanitized_and_typed(self):
        text = self._registry().render_prometheus()
        assert "# TYPE serving_admitted_total counter" in text
        assert "# TYPE serving_queue_depth gauge" in text
        assert "# TYPE rt_seconds histogram" in text
        assert "# HELP serving_admitted_total Requests admitted" in text
        assert "serving_admitted_total 3" in text
        assert "." not in [ln.split(" ")[0] for ln in text.splitlines()
                           if ln and not ln.startswith("#")][0]

    def test_histogram_invariants(self):
        samples = _parse_exposition(
            self._registry().render_prometheus())
        buckets = samples["rt_seconds_bucket"]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "cumulative buckets monotone"
        inf = [v for lbl, v in buckets if 'le="+Inf"' in lbl]
        assert inf == [samples["rt_seconds_count"][0][1]] == [3.0]
        assert samples["rt_seconds_sum"][0][1] == pytest.approx(50.505)

    def test_label_escaping(self):
        text = self._registry().render_prometheus()
        line = next(ln for ln in text.splitlines() if "we" in ln)
        assert '\\"' in line and "\\n" in line

    def test_default_registry_renders(self):
        _parse_exposition(obs.render_prometheus())


class TestPrometheusEdgeCases:
    """Exposition corners the mini-parser didn't pin before ISSUE 8:
    hostile label values and the histogram +Inf/_count invariant
    across labeled, multi-label and empty cells."""

    def test_backslash_and_trailing_backslash_label_values(self):
        r = Registry()
        c = r.counter("edge.total")
        c.inc(path="C:\\tmp\\x")       # interior backslashes
        c.inc(path="trailing\\")       # a trailing backslash must not
        c.inc(path='quote"inside')     # escape the closing quote
        c.inc(path="multi\nline\\mix\"")
        text = r.render_prometheus()
        samples = _parse_exposition(text)  # every line stays valid
        assert len(samples["edge_total"]) == 4
        # escaping is per spec: \ -> \\, newline -> \n, " -> \"
        assert 'path="C:\\\\tmp\\\\x"' in text
        assert 'path="trailing\\\\"' in text
        assert 'path="quote\\"inside"' in text
        assert 'path="multi\\nline\\\\mix\\""' in text
        assert "\n\n" not in text  # no raw newline leaked into a line

    def test_label_roundtrip_distinct_cells(self):
        """Two values that would collide if escaping were sloppy
        ('a\\' + 'b' vs 'a' + '\\b') must render as distinct series."""
        r = Registry()
        c = r.counter("collide.total")
        c.inc(2, k="a\\", j="b")
        c.inc(3, k="a", j="\\b")
        samples = _parse_exposition(r.render_prometheus())
        vals = sorted(v for _, v in samples["collide_total"])
        assert vals == [2.0, 3.0]
        labels = {lbl for lbl, _ in samples["collide_total"]}
        assert len(labels) == 2

    def test_labeled_histogram_inf_bucket_equals_count(self):
        """For EVERY cell of a labeled histogram: the cumulative +Inf
        bucket == its _count, and bucket counts are monotone within
        that cell (the invariant scrapers rely on for quantiles)."""
        r = Registry()
        h = r.histogram("lab.seconds", buckets=[0.01, 1.0])
        for v, phase in [(0.005, "fwd"), (0.5, "fwd"), (50.0, "fwd"),
                         (2.0, "bwd")]:
            h.observe(v, phase=phase)
        samples = _parse_exposition(r.render_prometheus())
        counts = {lbl: v for lbl, v in samples["lab_seconds_count"]}
        for phase, expect in [("fwd", 3.0), ("bwd", 1.0)]:
            cell = [(lbl, v) for lbl, v in samples["lab_seconds_bucket"]
                    if f'phase="{phase}"' in lbl]
            vals = [v for _, v in cell]
            assert vals == sorted(vals), "per-cell buckets monotone"
            inf = [v for lbl, v in cell if 'le="+Inf"' in lbl]
            assert inf == [expect]
            (count_lbl,) = [lbl for lbl in counts
                            if f'phase="{phase}"' in lbl]
            assert counts[count_lbl] == expect
            # every bucket line carries BOTH the cell label and le
            assert all('le="' in lbl for lbl, _ in cell)

    def test_empty_histogram_renders_consistent_zero_series(self):
        """A registered-but-never-observed histogram still exposes a
        full bucket ladder with +Inf == _count == 0 (scrapers must see
        the series exist, not a hole)."""
        r = Registry()
        r.histogram("never.seconds", buckets=[0.1, 1.0])
        samples = _parse_exposition(r.render_prometheus())
        assert samples["never_seconds_count"] == [("", 0.0)]
        assert samples["never_seconds_sum"] == [("", 0.0)]
        buckets = samples["never_seconds_bucket"]
        assert [v for _, v in buckets] == [0.0, 0.0, 0.0]
        assert any('le="+Inf"' in lbl for lbl, _ in buckets)


# ---------------------------------------------------------------------------
# /metrics HTTP endpoint
# ---------------------------------------------------------------------------

class TestHTTPEndpoint:
    def test_round_trip(self):
        r = Registry()
        r.counter("demo.hits_total", "demo").inc(5)
        from paddle_tpu.observability.http import start_metrics_server
        with start_metrics_server(registry=r) as srv:
            assert srv.port > 0
            body = urllib.request.urlopen(srv.url, timeout=10).read()
            text = body.decode()
            _parse_exposition(text)
            assert "demo_hits_total 5" in text
            jbody = urllib.request.urlopen(
                srv.url + ".json", timeout=10).read()
            assert json.loads(jbody)["demo"]["hits_total"] == 5
            code = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10).status
            assert code == 200
        with pytest.raises(Exception):
            urllib.request.urlopen(srv.url, timeout=2)

    def test_404(self):
        from paddle_tpu.observability.http import start_metrics_server
        with start_metrics_server(registry=Registry()) as srv:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=10)


# ---------------------------------------------------------------------------
# step timeline
# ---------------------------------------------------------------------------

class TestStepTimer:
    def test_phases_and_step_events(self):
        r = Registry()
        t = obs.StepTimer("traintest", registry=r)
        for _ in range(2):
            with t.phase("forward"):
                time.sleep(0.002)
            with t.phase("optimizer"):
                pass
            phases = t.step()
        assert set(phases) == {"forward", "optimizer"}
        assert phases["forward"] >= 0.002
        snap = r.snapshot()
        assert snap["step"]["steps_total"] == 2
        assert snap["step"]["step_seconds"]["count"] == 2
        assert snap["step"]["phase_seconds"]["forward"]["count"] == 2
        evs = t.chrome_events()
        assert len(evs) == 2
        assert evs[0]["ph"] == "C"
        assert evs[0]["name"] == "traintest.step_phases_ms"
        assert evs[0]["args"]["forward"] >= 2.0  # ms
        # module-level aggregation feeds export_chrome_tracing
        from paddle_tpu.observability import timeline
        assert any(e in timeline.chrome_events() for e in evs)

    def test_repeated_phase_accumulates_within_step(self):
        t = obs.StepTimer("acc", registry=Registry())
        with t.phase("data"):
            pass
        with t.phase("data"):
            pass
        phases = t.step()
        assert list(phases) == ["data"]


# ---------------------------------------------------------------------------
# cross-subsystem integration: one snapshot carries everything
# ---------------------------------------------------------------------------

class FakeEngine:
    """Duck-typed decode engine: just enough surface for
    GenerationServer's host orchestration (no jax compiles)."""

    def __init__(self, slots=2):
        self.max_slots = slots
        self.max_seq = 64
        self.eos_id = None
        self.pos = np.zeros(slots, np.int32)
        self.active = np.zeros(slots, bool)

    def prefill(self, slot, ids):
        self.pos[slot] = len(ids)
        self.active[slot] = True
        return 7

    def step(self):
        out = np.zeros(self.max_slots, np.int64)
        for s in range(self.max_slots):
            if self.active[s]:
                self.pos[s] += 1
                out[s] = 100 + s
        return out

    def release(self, slot):
        self.active[slot] = False
        self.pos[slot] = 0


class TestIntegration:
    def test_dispatch_metrics_move(self):
        snap0 = obs.snapshot()["dispatch"]
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        (x + x).numpy()
        snap1 = obs.snapshot()["dispatch"]
        assert snap1["ops_total"] > snap0["ops_total"]
        assert sum(snap1["ops_dispatched_total"].values()) >= \
            sum(snap0.get("ops_dispatched_total", {}).values())

    def test_fusion_stats_is_view_of_registry(self):
        from paddle_tpu.core import fusion
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        paddle.add(paddle.multiply(x, 2.0), 1.0).numpy()
        s = fusion.stats()
        snap = obs.snapshot()["fusion"]
        assert s["chains_flushed"] == snap["chains_flushed_total"]
        assert s["cache_hits"] == snap["cache_hits_total"]
        assert s["flush_reasons"] == snap.get("flushes_total",
                                              s["flush_reasons"])
        # chain-length keys come back as ints through the view
        assert all(isinstance(k, int) for k in s["chain_length_hist"])

    def test_checkpoint_metrics_move(self):
        from paddle_tpu.framework.checkpoint import CheckpointManager
        before = obs.snapshot()["checkpoint"]
        with tempfile.TemporaryDirectory() as d:
            m = CheckpointManager(d, keep_n=1)
            m.save({"w": paddle.to_tensor(np.ones(4, np.float32))}, step=0)
            m.restore()
        after = obs.snapshot()["checkpoint"]
        assert after["saves_total"] == before["saves_total"] + 1
        assert after["bytes_written_total"] > before["bytes_written_total"]
        assert after["save_seconds"]["count"] == \
            before["save_seconds"]["count"] + 1
        assert after["loads_total"] == before["loads_total"] + 1

    def test_serving_metrics_and_endpoint(self):
        from paddle_tpu.serving import GenerationServer
        before = obs.snapshot()["serving"]
        srv = GenerationServer(FakeEngine())
        try:
            ep = srv.metrics_endpoint()
            assert srv.metrics_endpoint() is ep  # idempotent
            out = srv.generate([1, 2, 3], max_new_tokens=3, timeout=30)
            assert out[0] == 7 and len(out) == 3
            after = obs.snapshot()["serving"]
            assert after["admitted_total"] == before["admitted_total"] + 1
            assert after["tokens_total"] >= before["tokens_total"] + 3
            assert after["request_seconds"]["count"] > \
                before["request_seconds"]["count"]
            assert after["token_seconds"]["count"] > \
                before["token_seconds"]["count"]
            body = urllib.request.urlopen(ep.url, timeout=10).read()
            assert b"serving_admitted_total" in body
            # idle server: gauges must read 0, not the last mid-step
            # values (a finished request is not "in flight")
            deadline = time.monotonic() + 10
            g_inflight = obs.default_registry().get("serving.in_flight")
            g_queue = obs.default_registry().get("serving.queue_depth")
            while time.monotonic() < deadline and (
                    g_inflight.value() or g_queue.value()):
                time.sleep(0.01)
            assert g_inflight.value() == 0
            assert g_queue.value() == 0
        finally:
            srv.shutdown()
        assert srv._metrics_server is None  # shutdown closes the endpoint

    def test_fault_injection_lands_in_snapshot(self):
        from paddle_tpu.utils import fault_injection as fi
        site = "obs.test.site"
        before = obs.snapshot().get("faults", {}).get(
            "injected_total", {}).get(site, 0)
        with fi.injected(site):
            with pytest.raises(fi.InjectedFault):
                fi.fire(site)
        got = obs.snapshot()["faults"]["injected_total"][site]
        assert got == before + 1
        assert fi.stats()[site] >= 1  # legacy surface intact

    def test_store_retry_counter(self):
        # the counter instrument exists and moves when incremented the
        # way TCPStore._call does (the full retry loop is exercised by
        # test_fault_tolerance against a live store server)
        from paddle_tpu.distributed import store as store_mod
        v0 = store_mod._M_retries.value(op="add")
        store_mod._M_retries.inc(op="add")
        assert store_mod._M_retries.value(op="add") == v0 + 1

    def test_watchdog_span_lands_in_registry(self):
        from paddle_tpu.distributed.watchdog import Watchdog, _M_span_s
        wd = Watchdog(timeout=60.0)
        c0 = _M_span_s.value(name="unit_span")["count"]
        with wd.span("unit_span"):
            pass
        assert _M_span_s.value(name="unit_span")["count"] == c0 + 1
