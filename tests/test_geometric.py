"""paddle.geometric tests (ref: python/paddle/geometric/)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def test_segment_ops():
    data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.],
                                      [7., 8.]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
    np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                               [[4., 6.], [12., 14.]])
    np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                               [[2., 3.], [6., 7.]])
    np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                               [[3., 4.], [7., 8.]])
    np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                               [[1., 2.], [5., 6.]])


def test_segment_empty_segment_zero():
    data = paddle.to_tensor(np.array([[1., 1.]], np.float32))
    ids = paddle.to_tensor(np.array([2], np.int64))
    out = G.segment_max(data, ids, num_segments=4).numpy()
    np.testing.assert_allclose(out[0], [0., 0.])
    np.testing.assert_allclose(out[2], [1., 1.])


def test_segment_sum_gradient():
    data = paddle.to_tensor(np.ones((4, 2), np.float32),
                            stop_gradient=False)
    ids = paddle.to_tensor(np.array([0, 1, 0, 1], np.int64))
    out = G.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 2)))


def test_send_u_recv():
    x = paddle.to_tensor(np.array([[0., 2., 3.], [1., 4., 5.],
                                   [2., 6., 7.]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int64))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int64))
    out = G.send_u_recv(x, src, dst, reduce_op="sum")
    want = np.zeros((3, 3), np.float32)
    for s, d in [(0, 1), (1, 2), (2, 1), (0, 0)]:
        want[d] += x.numpy()[s]
    np.testing.assert_allclose(out.numpy(), want)


def test_send_ue_recv_mul_max():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
    e = paddle.to_tensor(np.array([[2., 2.], [0.5, 0.5], [1., 1.]],
                                  np.float32))
    src = paddle.to_tensor(np.array([0, 1, 0], np.int64))
    dst = paddle.to_tensor(np.array([1, 0, 0], np.int64))
    out = G.send_ue_recv(x, e, src, dst, message_op="mul",
                         reduce_op="max")
    # messages: [2,4]->1, [1.5,2]->0, [1,2]->0 ; max per dst
    np.testing.assert_allclose(out.numpy(), [[1.5, 2.], [2., 4.]])


def test_reindex_graph():
    x = paddle.to_tensor(np.array([0, 5, 9], np.int64))
    neighbors = paddle.to_tensor(np.array([5, 9, 7, 0], np.int64))
    count = paddle.to_tensor(np.array([2, 1, 1], np.int64))
    src, dst, nodes = G.reindex_graph(x, neighbors, count)
    np.testing.assert_array_equal(nodes.numpy(), [0, 5, 9, 7])
    np.testing.assert_array_equal(src.numpy(), [1, 2, 3, 0])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 2])
