"""Round-2 long-tail nn/nn.functional coverage.

Oracles: torch (CPU) where the reference semantics match torch, else
hand-rolled NumPy DPs (ref: test/legacy_test per-op tests)."""
import os

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


@pytest.mark.skipif(not os.path.isdir("/root/reference"),
                    reason="reference checkout absent in this container")
class TestAPISurfaceComplete:
    def _ref_all(self, path):
        import ast
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return [ast.literal_eval(e) for e in node.value.elts]

    def test_nn_all_covered(self):
        ref = self._ref_all("/root/reference/python/paddle/nn/__init__.py")
        missing = [n for n in ref if not hasattr(nn, n)]
        assert missing == [], missing

    def test_functional_all_covered(self):
        ref = self._ref_all(
            "/root/reference/python/paddle/nn/functional/__init__.py")
        missing = [n for n in ref if not hasattr(F, n)]
        assert missing == [], missing


class TestPoolingLongTail:
    def test_max_pool2d_mask_and_unpool_vs_torch(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                 return_mask=True)
        tout, tmask = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, 2, return_indices=True)
        np.testing.assert_allclose(out.numpy(), tout.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(mask.numpy(), tmask.numpy())
        un = F.max_unpool2d(out, mask, 2, 2)
        tun = torch.nn.functional.max_unpool2d(tout, tmask, 2, 2)
        np.testing.assert_allclose(un.numpy(), tun.numpy(), rtol=1e-6)

    def test_max_unpool_1d_3d(self):
        x1 = np.random.randn(2, 3, 8).astype(np.float32)
        o1, m1 = F.max_pool1d(paddle.to_tensor(x1), 2, 2, return_mask=True)
        to1, tm1 = torch.nn.functional.max_pool1d(
            torch.tensor(x1), 2, 2, return_indices=True)
        np.testing.assert_array_equal(m1.numpy(), tm1.numpy())
        np.testing.assert_allclose(
            F.max_unpool1d(o1, m1, 2, 2).numpy(),
            torch.nn.functional.max_unpool1d(to1, tm1, 2, 2).numpy())
        x3 = np.random.randn(2, 2, 4, 4, 4).astype(np.float32)
        o3, m3 = F.max_pool3d(paddle.to_tensor(x3), 2, 2, return_mask=True)
        to3, tm3 = torch.nn.functional.max_pool3d(
            torch.tensor(x3), 2, 2, return_indices=True)
        np.testing.assert_array_equal(m3.numpy(), tm3.numpy())
        np.testing.assert_allclose(
            F.max_unpool3d(o3, m3, 2, 2).numpy(),
            torch.nn.functional.max_unpool3d(to3, tm3, 2, 2).numpy())

    def test_lp_pool_vs_torch(self):
        x = np.abs(np.random.randn(2, 3, 8, 8)).astype(np.float32)
        got = F.lp_pool2d(paddle.to_tensor(x), 3.0, 2, 2).numpy()
        exp = torch.nn.functional.lp_pool2d(
            torch.tensor(x), 3.0, 2, 2).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-5)
        x1 = np.abs(np.random.randn(2, 3, 10)).astype(np.float32)
        got = F.lp_pool1d(paddle.to_tensor(x1), 2.0, 2, 2).numpy()
        exp = torch.nn.functional.lp_pool1d(
            torch.tensor(x1), 2.0, 2, 2).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-5)

    def test_fractional_max_pool(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        out, mask = F.fractional_max_pool2d(
            paddle.to_tensor(x), output_size=5, random_u=0.3,
            return_mask=True)
        assert list(out.shape) == [2, 3, 5, 5]
        flat = x.reshape(2, 3, -1)
        vals = np.take_along_axis(
            flat, mask.numpy().reshape(2, 3, -1), axis=2)
        np.testing.assert_allclose(vals.reshape(out.shape), out.numpy())
        out3 = F.fractional_max_pool3d(
            paddle.to_tensor(np.random.randn(1, 2, 6, 6, 6).astype(
                np.float32)), output_size=3, random_u=0.5)
        assert list(out3.shape) == [1, 2, 3, 3, 3]

    def test_layers(self):
        x = paddle.to_tensor(np.random.randn(2, 3, 8, 8).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        un = nn.MaxUnPool2D(2, 2)(out, mask)
        assert list(un.shape) == [2, 3, 8, 8]
        assert list(nn.LPPool2D(2.0, 2, 2)(x).shape) == [2, 3, 4, 4]
        assert list(nn.FractionalMaxPool2D(4, random_u=0.4)(x).shape) == \
            [2, 3, 4, 4]


class TestVision:
    def test_grid_sample_vs_torch(self):
        x = np.random.randn(2, 3, 6, 7).astype(np.float32)
        grid = (np.random.rand(2, 4, 5, 2).astype(np.float32) * 2.4 - 1.2)
        for mode in ("bilinear", "nearest"):
            for pm in ("zeros", "border", "reflection"):
                for ac in (True, False):
                    got = F.grid_sample(
                        paddle.to_tensor(x), paddle.to_tensor(grid),
                        mode=mode, padding_mode=pm,
                        align_corners=ac).numpy()
                    exp = torch.nn.functional.grid_sample(
                        torch.tensor(x), torch.tensor(grid), mode=mode,
                        padding_mode=pm, align_corners=ac).numpy()
                    np.testing.assert_allclose(
                        got, exp, rtol=1e-4, atol=1e-5,
                        err_msg=f"{mode}/{pm}/ac={ac}")

    def test_grid_sample_5d(self):
        x3 = np.random.randn(2, 2, 4, 5, 6).astype(np.float32)
        g3 = (np.random.rand(2, 3, 4, 5, 3).astype(np.float32) * 2 - 1)
        got = F.grid_sample(paddle.to_tensor(x3), paddle.to_tensor(g3),
                            align_corners=True).numpy()
        exp = torch.nn.functional.grid_sample(
            torch.tensor(x3), torch.tensor(g3), align_corners=True).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)

    def test_affine_grid_vs_torch(self):
        theta = np.random.randn(2, 2, 3).astype(np.float32)
        for ac in (True, False):
            got = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 6],
                                align_corners=ac).numpy()
            exp = torch.nn.functional.affine_grid(
                torch.tensor(theta), (2, 3, 5, 6),
                align_corners=ac).numpy()
            np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)

    def test_grid_sample_grad(self):
        x = paddle.to_tensor(
            np.random.randn(1, 2, 5, 5).astype(np.float32),
            stop_gradient=False)
        g = paddle.to_tensor(
            (np.random.rand(1, 3, 3, 2).astype(np.float32) * 2 - 1))
        F.grid_sample(x, g).sum().backward()
        assert x.grad is not None

    def test_temporal_shift(self):
        xt = np.random.randn(4, 8, 3, 3).astype(np.float32)
        got = F.temporal_shift(paddle.to_tensor(xt), 2, 0.25).numpy()
        r = xt.reshape(2, 2, 8, 3, 3)
        pad = np.pad(r, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        exp = np.concatenate(
            [pad[:, :2, :2], pad[:, 2:, 2:4], pad[:, 1:3, 4:]],
            axis=2).reshape(4, 8, 3, 3)
        np.testing.assert_allclose(got, exp)


class TestExtension:
    def test_sequence_mask(self):
        got = F.sequence_mask(paddle.to_tensor(np.array([1, 3, 2])),
                              maxlen=4).numpy()
        np.testing.assert_array_equal(
            got, [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
        # maxlen inferred
        got = F.sequence_mask(paddle.to_tensor(np.array([2, 1])))
        assert list(got.shape) == [2, 2]

    def test_gather_tree_reference_example(self):
        ids = paddle.to_tensor(np.array(
            [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
            np.int64))
        parents = paddle.to_tensor(np.array(
            [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]],
            np.int64))
        exp = np.array(
            [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])
        np.testing.assert_array_equal(
            F.gather_tree(ids, parents).numpy(), exp)

    def test_sparse_attention_matches_dense(self):
        B, H, M, D = 1, 2, 4, 8
        q = np.random.randn(B, H, M, D).astype(np.float32)
        k = np.random.randn(B, H, M, D).astype(np.float32)
        v = np.random.randn(B, H, M, D).astype(np.float32)
        # full CSR pattern == dense attention
        offset = np.tile(np.arange(0, M * M + 1, M, dtype=np.int32),
                         (B, H, 1))
        cols = np.tile(np.tile(np.arange(M, dtype=np.int32), M), (B, H, 1))
        got = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(offset), paddle.to_tensor(cols)).numpy()
        s = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
        p = torch.softmax(torch.tensor(s), -1).numpy()
        np.testing.assert_allclose(got, p @ v, rtol=1e-4, atol=1e-5)

    def test_class_center_sample(self):
        label = paddle.to_tensor(np.array([1, 5, 1, 7], np.int64))
        remapped, sampled = F.class_center_sample(label, 20, 6, group=False)
        sam = sampled.numpy()
        assert len(sam) == 6
        assert {1, 5, 7}.issubset(set(sam.tolist()))
        rm = remapped.numpy()
        lut = {c: i for i, c in enumerate(sam.tolist())}
        np.testing.assert_array_equal(rm, [lut[1], lut[5], lut[1], lut[7]])


class TestLossLongTail:
    def test_sigmoid_focal_loss(self):
        x = np.random.randn(4, 5).astype(np.float32)
        y = (np.random.rand(4, 5) > 0.5).astype(np.float32)
        tl = torch.tensor(x)
        ty = torch.tensor(y)
        p = torch.sigmoid(tl)
        ce = torch.nn.functional.binary_cross_entropy_with_logits(
            tl, ty, reduction="none")
        pt = p * ty + (1 - p) * (1 - ty)
        exp = (0.25 * ty + 0.75 * (1 - ty)) * ce * (1 - pt) ** 2
        got = F.sigmoid_focal_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                   reduction="none").numpy()
        np.testing.assert_allclose(got, exp.numpy(), rtol=1e-5)

    def test_square_error_and_log_loss(self):
        x = np.random.rand(4, 1).astype(np.float32)
        y = (np.random.rand(4, 1) > 0.5).astype(np.float32)
        np.testing.assert_allclose(
            F.square_error_cost(paddle.to_tensor(x),
                                paddle.to_tensor(y)).numpy(),
            (x - y) ** 2, rtol=1e-6)
        got = F.log_loss(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
        exp = -y * np.log(x + 1e-4) - (1 - y) * np.log(1 - x + 1e-4)
        np.testing.assert_allclose(got, exp, rtol=1e-5)

    def test_dice_loss(self):
        inp = np.random.rand(3, 4, 5).astype(np.float32)
        lbl = np.random.randint(0, 5, (3, 4, 1))
        oh = np.eye(5)[lbl.squeeze(-1)]
        inse = (inp * oh).sum((1, 2))
        den = inp.sum((1, 2)) + oh.sum((1, 2))
        exp = (1 - 2 * inse / (den + 1e-5)).mean()
        got = float(F.dice_loss(paddle.to_tensor(inp),
                                paddle.to_tensor(lbl)))
        np.testing.assert_allclose(got, exp, rtol=1e-5)

    def test_triplet_margin_with_distance_vs_torch(self):
        a = np.random.randn(6, 8).astype(np.float32)
        p = np.random.randn(6, 8).astype(np.float32)
        n = np.random.randn(6, 8).astype(np.float32)
        got = F.triplet_margin_with_distance_loss(
            paddle.to_tensor(a), paddle.to_tensor(p), paddle.to_tensor(n),
            margin=0.7, swap=True).numpy()
        exp = torch.nn.functional.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n),
            margin=0.7, swap=True).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)

    def test_adaptive_log_softmax_vs_torch(self):
        D, n_classes = 16, 20
        tmod = torch.nn.AdaptiveLogSoftmaxWithLoss(
            D, n_classes, cutoffs=[6, 12], div_value=2.0)
        xb = np.random.randn(10, D).astype(np.float32)
        yb = np.random.randint(0, n_classes, (10,))
        tout = tmod(torch.tensor(xb), torch.tensor(yb))
        tails = [(paddle.to_tensor(s[0].weight.detach().numpy().T),
                  paddle.to_tensor(s[1].weight.detach().numpy().T))
                 for s in tmod.tail]
        out, loss = F.adaptive_log_softmax_with_loss(
            paddle.to_tensor(xb), paddle.to_tensor(yb),
            paddle.to_tensor(tmod.head.weight.detach().numpy().T),
            tails, [6, 12])
        np.testing.assert_allclose(out.numpy(),
                                   tout.output.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(loss), float(tout.loss),
                                   rtol=1e-4)

    def test_adaptive_log_softmax_layer(self):
        layer = nn.AdaptiveLogSoftmaxWithLoss(8, 12, [4, 8], div_value=2.0)
        x = paddle.to_tensor(np.random.randn(5, 8).astype(np.float32),
                             stop_gradient=False)
        y = paddle.to_tensor(np.random.randint(0, 12, (5,)))
        out, loss = layer(x, y)
        loss.backward()
        assert layer.head_weight.grad is not None
        lp = layer.log_prob(paddle.to_tensor(
            np.random.randn(3, 8).astype(np.float32)))
        np.testing.assert_allclose(np.exp(lp.numpy()).sum(-1),
                                   np.ones(3), rtol=1e-4)

    def test_rnnt_loss_vs_numpy_dp(self):
        def rnnt_np(acts, labels, T, U, blank=0):
            lp = torch.log_softmax(torch.tensor(acts), dim=-1).numpy()
            alpha = np.full((T, U + 1), -np.inf)
            alpha[0, 0] = 0
            for t in range(T):
                for u in range(U + 1):
                    c = []
                    if t > 0:
                        c.append(alpha[t - 1, u] + lp[t - 1, u, blank])
                    if u > 0:
                        c.append(alpha[t, u - 1] + lp[t, u - 1,
                                                      labels[u - 1]])
                    if c and not (t == 0 and u == 0):
                        mx = max(c)
                        alpha[t, u] = mx + np.log(
                            sum(np.exp(v - mx) for v in c))
            return -(alpha[T - 1, U] + lp[T - 1, U, blank])

        B, T, U, V = 3, 6, 4, 7
        acts = np.random.randn(B, T, U + 1, V).astype(np.float32)
        labels = np.random.randint(1, V, (B, U)).astype(np.int32)
        exp = np.array([rnnt_np(acts[b], labels[b], T, U)
                        for b in range(B)])
        got = F.rnnt_loss(
            paddle.to_tensor(acts), paddle.to_tensor(labels),
            paddle.to_tensor(np.full(B, T, np.int32)),
            paddle.to_tensor(np.full(B, U, np.int32)),
            fastemit_lambda=0.0, reduction="none").numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)
        # layer + grad + mean reduction
        crit = nn.RNNTLoss(fastemit_lambda=0.0)
        a = paddle.to_tensor(acts, stop_gradient=False)
        loss = crit(a, paddle.to_tensor(labels),
                    paddle.to_tensor(np.full(B, T, np.int32)),
                    paddle.to_tensor(np.full(B, U, np.int32)))
        np.testing.assert_allclose(float(loss), exp.mean(), rtol=1e-4)
        loss.backward()
        assert a.grad is not None

    def test_hsigmoid_vs_bitcode_oracle(self):
        N, D, C = 5, 8, 6
        xi = np.random.randn(N, D).astype(np.float32)
        lb = np.random.randint(0, C, (N,))
        w = np.random.randn(C - 1, D).astype(np.float32)
        bi = np.random.randn(C - 1).astype(np.float32)

        def hs_np(x, l):
            c = l + C
            loss = 0.0
            for j in range(int(np.floor(np.log2(c)))):
                node = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                pre = np.clip(w[node] @ x + bi[node], -40, 40)
                loss += np.log1p(np.exp(pre)) - bit * pre
            return loss

        exp = np.array([[hs_np(xi[i], lb[i])] for i in range(N)])
        got = F.hsigmoid_loss(
            paddle.to_tensor(xi), paddle.to_tensor(lb), C,
            paddle.to_tensor(w), paddle.to_tensor(bi)).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)
        # layer form trains
        layer = nn.HSigmoidLoss(D, C)
        x = paddle.to_tensor(xi, stop_gradient=False)
        layer(x, paddle.to_tensor(lb)).sum().backward()
        assert layer.weight.grad is not None

    def test_margin_cross_entropy(self):
        Nc = 8
        feat = np.clip(np.random.randn(4, Nc), -1, 1).astype(np.float32)
        lab = np.random.randint(0, Nc, (4,))
        loss, sm = F.margin_cross_entropy(
            paddle.to_tensor(feat), paddle.to_tensor(lab),
            return_softmax=True, reduction=None, group=False)
        theta = np.arccos(np.clip(feat, -1, 1))
        mod = feat.copy()
        for i in range(4):
            mod[i, lab[i]] = np.cos(theta[i, lab[i]] + 0.5)
        mod *= 64.0
        lsm = mod - mod.max(-1, keepdims=True)
        lsm = lsm - np.log(np.exp(lsm).sum(-1, keepdims=True))
        exp = np.array([[-lsm[i, lab[i]]] for i in range(4)])
        np.testing.assert_allclose(loss.numpy(), exp, rtol=1e-4)

    def test_npair_and_pairwise(self):
        a = np.random.randn(6, 8).astype(np.float32)
        b = np.random.randn(6, 8).astype(np.float32)
        got = F.pairwise_distance(paddle.to_tensor(a), paddle.to_tensor(b),
                                  p=3.0).numpy()
        exp = torch.nn.functional.pairwise_distance(
            torch.tensor(a), torch.tensor(b), p=3.0).numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)
        lb = np.random.randint(0, 3, (6,)).astype(np.float32)
        val = float(F.npair_loss(paddle.to_tensor(a), paddle.to_tensor(b),
                                 paddle.to_tensor(lb)))
        assert np.isfinite(val)


class TestVarlenFlash:
    def test_varlen_matches_per_sequence(self):
        import jax.numpy as jnp
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        H, D = 2, 8
        lens = [5, 3, 6]
        total = sum(lens)
        qkv = np.random.randn(total, 3, H, D).astype(np.float32)
        cu = np.cumsum([0] + lens).astype(np.int32)
        out, _ = F.flash_attn_varlen_qkvpacked(
            paddle.to_tensor(qkv), paddle.to_tensor(cu),
            paddle.to_tensor(cu), max(lens), max(lens),
            scale=1 / np.sqrt(D), causal=True)
        off = 0
        for L in lens:
            q = qkv[off:off + L, 0][None]
            k = qkv[off:off + L, 1][None]
            v = qkv[off:off + L, 2][None]
            exp = np.asarray(_sdpa_reference(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=True, scale=1 / np.sqrt(D)))
            np.testing.assert_allclose(out.numpy()[off:off + L], exp[0],
                                       rtol=2e-4, atol=2e-5)
            off += L

    def test_varlen_grad_no_cross_sequence_leak(self):
        H, D = 1, 4
        lens = [3, 3]
        qkv = np.random.randn(6, 3, H, D).astype(np.float32)
        cu = np.cumsum([0] + lens).astype(np.int32)
        t = paddle.to_tensor(qkv, stop_gradient=False)
        out, _ = F.flash_attn_varlen_qkvpacked(
            t, paddle.to_tensor(cu), paddle.to_tensor(cu), 3, 3,
            scale=0.5, causal=False)
        # loss only on first sequence -> grads on second sequence are zero
        out[:3].sum().backward()
        g = t.grad.numpy()
        assert np.abs(g[:3]).max() > 0
        np.testing.assert_allclose(g[3:], 0.0)

    def test_qkvpacked(self):
        qkv = np.random.randn(2, 6, 3, 2, 8).astype(np.float32)
        out, _ = F.flash_attn_qkvpacked(paddle.to_tensor(qkv), causal=True)
        exp, _ = F.flash_attention(
            paddle.to_tensor(qkv[:, :, 0]), paddle.to_tensor(qkv[:, :, 1]),
            paddle.to_tensor(qkv[:, :, 2]), causal=True)
        np.testing.assert_allclose(out.numpy(), exp.numpy(), rtol=1e-5)

    def test_flashmask_causal_lts(self):
        import jax.numpy as jnp
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        B, H, L, D = 2, 2, 6, 8
        q = np.random.randn(B, L, H, D).astype(np.float32)
        k = np.random.randn(B, L, H, D).astype(np.float32)
        v = np.random.randn(B, L, H, D).astype(np.float32)
        sr = np.random.randint(1, L + 1, (B, 1, L, 1)).astype(np.int32)
        got = F.flashmask_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(sr), causal=True).numpy()
        mask = np.zeros((B, 1, L, L), np.float32)
        for bi in range(B):
            for j in range(L):
                mask[bi, 0, sr[bi, 0, j, 0]:, j] = -1e30
        exp = np.asarray(_sdpa_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            mask=jnp.asarray(mask), causal=True))
        np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-5)


class TestInplaceActivations:
    def test_inplace_contract(self):
        t = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        r = F.relu_(t)
        assert r is t
        assert t.numpy().tolist() == [0.0, 2.0]
        for name in ("tanh_", "elu_", "hardtanh_", "leaky_relu_",
                     "softmax_", "thresholded_relu_"):
            fn = getattr(F, name)
            x = paddle.to_tensor(np.array([0.3, -0.2], np.float32))
            assert fn(x) is x


class TestDecode:
    def _decoder(self, vocab=10, hidden=16, beam=3):
        cell = nn.GRUCell(hidden, hidden)
        emb = nn.Embedding(vocab, hidden)
        proj = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=beam, embedding_fn=emb,
                                   output_fn=proj)
        return dec, hidden

    def test_dynamic_decode_shapes(self):
        paddle.seed(0)
        dec, hidden = self._decoder()
        init = paddle.to_tensor(
            np.random.randn(2, hidden).astype(np.float32))
        outs, final = nn.dynamic_decode(dec, inits=init, max_step_num=5)
        ids = outs.numpy() if hasattr(outs, "numpy") else outs
        assert ids.shape[0] == 2          # batch-major
        assert ids.shape[2] == 3          # beam
        assert ids.shape[1] <= 7

    def test_beam1_matches_greedy(self):
        paddle.seed(1)
        vocab, hidden = 8, 12
        cell = nn.GRUCell(hidden, hidden)
        emb = nn.Embedding(vocab, hidden)
        proj = nn.Linear(hidden, vocab)
        dec = nn.BeamSearchDecoder(cell, 0, 1, 1, embedding_fn=emb,
                                   output_fn=proj)
        init = paddle.to_tensor(
            np.random.randn(1, hidden).astype(np.float32))
        outs, _ = nn.dynamic_decode(dec, inits=init, max_step_num=4)
        # greedy rollout oracle
        h = init
        tok = paddle.to_tensor(np.array([0], np.int64))
        greedy = []
        for _ in range(5):
            o, h = cell(emb(tok), h)
            logits = proj(o).numpy()
            nxt = int(logits.argmax(-1)[0])
            greedy.append(nxt)
            tok = paddle.to_tensor(np.array([nxt], np.int64))
            if nxt == 1:
                break
        ids = outs.numpy()[0, :, 0].tolist()
        assert ids[:len(greedy)] == greedy


class TestNewLayers:
    def test_misc_layers(self):
        x = paddle.to_tensor(np.random.randn(2, 6, 4, 4).astype(np.float32))
        assert list(nn.Softmax2D()(x).shape) == [2, 6, 4, 4]
        np.testing.assert_allclose(
            nn.Softmax2D()(x).numpy().sum(1), np.ones((2, 4, 4)),
            rtol=1e-5)
        u = nn.Unflatten(1, [2, 3])(x)
        assert list(u.shape) == [2, 2, 3, 4, 4]
        z1 = nn.ZeroPad1D(2)(paddle.to_tensor(
            np.ones((1, 2, 3), np.float32)))
        assert list(z1.shape) == [1, 2, 7]
        z3 = nn.ZeroPad3D(1)(paddle.to_tensor(
            np.ones((1, 2, 3, 3, 3), np.float32)))
        assert list(z3.shape) == [1, 2, 5, 5, 5]
        pd = nn.PairwiseDistance()(
            paddle.to_tensor(np.ones((2, 3), np.float32)),
            paddle.to_tensor(np.zeros((2, 3), np.float32)))
        np.testing.assert_allclose(pd.numpy(), np.sqrt([3.0, 3.0]),
                                   rtol=1e-4)
        fa = nn.FeatureAlphaDropout(0.5)
        fa.eval()
        y = fa(x)
        np.testing.assert_allclose(y.numpy(), x.numpy())

    def test_parameter_dict(self):
        pd = nn.ParameterDict({
            "a": paddle.create_parameter([2, 2], "float32"),
            "b": paddle.create_parameter([3], "float32"),
        })
        assert set(pd.keys()) == {"a", "b"}
        assert len(list(pd.parameters())) == 2
        assert "a" in pd
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.params = nn.ParameterDict(
                    {"w": paddle.create_parameter([2], "float32")})
        assert len(M().state_dict()) == 1


class TestReviewRegressions:
    """Fixes from the round-2 code review: ceil_mode/full-form output_size
    on the mask path, NHWC rejection, seeded fractional pooling."""

    def test_mask_path_ceil_mode_and_full_output_size(self):
        x = np.random.randn(2, 3, 7, 7).astype(np.float32)
        o, m = F.max_pool2d(paddle.to_tensor(x), 2, 2, return_mask=True,
                            ceil_mode=True)
        to, tm = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, 2, return_indices=True, ceil_mode=True)
        np.testing.assert_allclose(o.numpy(), to.numpy())
        np.testing.assert_array_equal(m.numpy(), tm.numpy())
        u = F.max_unpool2d(o, m, 2, 2, output_size=[2, 3, 7, 7])
        tu = torch.nn.functional.max_unpool2d(to, tm, 2, 2,
                                              output_size=(7, 7))
        np.testing.assert_allclose(u.numpy(), tu.numpy())

    def test_mask_path_rejects_channel_last(self):
        x = paddle.to_tensor(np.zeros((1, 2, 4, 4), np.float32))
        with pytest.raises(ValueError, match="channel-first"):
            F.max_pool2d(x, 2, 2, return_mask=True, data_format="NHWC")

    def test_fractional_pool_seeded(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        paddle.seed(7)
        a = F.fractional_max_pool2d(paddle.to_tensor(x), 3).numpy()
        paddle.seed(7)
        b = F.fractional_max_pool2d(paddle.to_tensor(x), 3).numpy()
        np.testing.assert_allclose(a, b)

    def test_hsigmoid_path_args_must_pair(self):
        with pytest.raises(ValueError, match="together"):
            F.hsigmoid_loss(
                paddle.to_tensor(np.zeros((2, 3), np.float32)),
                paddle.to_tensor(np.zeros(2, np.int64)), 4,
                paddle.to_tensor(np.zeros((3, 3), np.float32)),
                path_table=paddle.to_tensor(np.zeros((2, 2), np.int64)))


class TestReviewRegressions2:
    def test_int_pooling_mask_exact_above_2_24(self):
        big = np.random.randint(0, 2 ** 30, (1, 1, 6, 6)).astype(np.int32)
        o, m = F.max_pool2d(paddle.to_tensor(big), 2, 2, return_mask=True)
        exp = big.reshape(1, 1, 3, 2, 3, 2).max(3).max(4)
        np.testing.assert_array_equal(o.numpy(), exp)

    def test_flashmask_window_size(self):
        import jax.numpy as jnp
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        L, D = 8, 8
        q = np.random.randn(1, L, 1, D).astype(np.float32)
        sr = np.full((1, 1, L, 1), L, np.int32)
        got = F.flashmask_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            paddle.to_tensor(sr), causal=True, window_size=2).numpy()
        rows = np.arange(L)[:, None]
        cols = np.arange(L)[None, :]
        wmask = np.where((cols < rows - 2) | (cols > rows + 2),
                         -1e30, 0.0)[None, None].astype(np.float32)
        exp = np.asarray(_sdpa_reference(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
            mask=jnp.asarray(wmask), causal=True))
        np.testing.assert_allclose(got, exp, rtol=2e-4, atol=1e-5)


class TestEmbeddingMatmulDgrad:
    def test_big_table_dgrad_matches_native_scatter(self, monkeypatch):
        """The >=256MB-table path (one-hot MXU contraction, chunked over
        tokens) must produce the same dW as jnp.take's native scatter
        VJP; forced reachable here by dropping the threshold to 0."""
        from paddle_tpu.nn.functional import common as C
        rng = np.random.default_rng(0)
        w_np = rng.normal(size=(32, 8)).astype(np.float32)
        # repeated indices exercise the accumulate path
        idx_np = rng.integers(0, 32, (4, 6)).astype(np.int32)
        g_np = rng.normal(size=(4, 6, 8)).astype(np.float32)

        def grads():
            w = paddle.to_tensor(w_np.copy(), stop_gradient=False)
            idx = paddle.to_tensor(idx_np)
            out = paddle.nn.functional.embedding(idx, w)
            (out * paddle.to_tensor(g_np)).sum().backward()
            return w.grad.numpy()

        native = grads()
        monkeypatch.setattr(C, "_EMBED_MATMUL_DGRAD_BYTES", 0)
        matmul_dw = grads()
        np.testing.assert_allclose(matmul_dw, native, rtol=1e-5,
                                   atol=1e-6)
        # tiny chunk floor: 24 tokens -> 3 chunks, exercising the
        # multi-chunk fp32 accumulation loop
        monkeypatch.setattr(C, "_EMBED_CHUNK_FLOOR", 8)
        chunked_dw = grads()
        np.testing.assert_allclose(chunked_dw, native, rtol=1e-5,
                                   atol=1e-6)
