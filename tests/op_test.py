"""OpTest: the reference's op-level test harness, TPU-native.

ref: test/legacy_test/op_test.py:418 (OpTest.check_output :2139 — run the
op, compare to a NumPy reference per dtype with per-dtype thresholds;
check_grad :3129 — compare analytic gradients against central finite
differences). Here the "op" is a framework callable over Tensors; each op
is checked eagerly AND under jit (the dygraph/static dual of the
reference), at fp32/bf16 with scaled tolerances.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

# per-dtype output tolerances (ref: op_accuracy thresholds — fp32 1e-5-ish,
# bf16 ~1e-2 relative)
_ATOL = {np.dtype(np.float32): 2e-5, np.dtype(np.float16): 2e-3,
         np.dtype(jnp.bfloat16): 2e-2}
_RTOL = {np.dtype(np.float32): 2e-5, np.dtype(np.float16): 2e-3,
         np.dtype(jnp.bfloat16): 2e-2}


class OpTest:
    """Subclass and set:
      op_fn(*tensors, **attrs) -> Tensor (the framework op)
      ref_fn(*np_arrays, **attrs) -> np.ndarray (NumPy oracle)
      inputs(): dict name -> np.ndarray (fp32)
      attrs: dict of non-tensor kwargs (default {})
      dtypes: dtypes to run (default fp32 + bf16)
      grad_inputs: names to grad-check (default: all floating inputs)
    """

    op_fn: Callable = None
    ref_fn: Callable = None
    attrs: Dict = {}
    dtypes = ("float32", "bfloat16")
    grad_eps = 1e-3
    grad_rtol = 5e-2  # central differences in fp32 (ref threshold 0.05)

    def inputs(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- check_output (ref: op_test.py:2139) --------------------------------
    def test_check_output(self):
        base = self.inputs()
        for dtype in self.dtypes:
            d = jnp.dtype(dtype)
            arrs = {k: v.astype(d) if np.issubdtype(v.dtype, np.floating)
                    else v for k, v in base.items()}
            expect = type(self).ref_fn(
                *[np.asarray(a, np.float32)
                  if jnp.issubdtype(jnp.dtype(np.asarray(a).dtype),
                                    jnp.floating)  # incl. bfloat16
                  else a for a in arrs.values()], **self.attrs)

            # eager
            tensors = [paddle.to_tensor(a) for a in arrs.values()]
            got = type(self).op_fn(*tensors, **self.attrs)
            self._compare(got.numpy(), expect, d, "eager")

            # jit (the "static graph" leg of the reference's dual runs)
            def raw(*xs):
                return type(self).op_fn(
                    *[Tensor(x) for x in xs], **self.attrs)._data
            got_jit = jax.jit(raw)(*[t._data for t in tensors])
            self._compare(np.asarray(got_jit), expect, d, "jit")

    def _compare(self, got, expect, dtype, mode):
        atol = _ATOL.get(np.dtype(dtype), 2e-5)
        rtol = _RTOL.get(np.dtype(dtype), 2e-5)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(expect, np.float32),
            atol=atol + 1e-8,
            rtol=rtol,
            err_msg=f"{type(self).__name__} {mode} {dtype} mismatch")

    # -- check_grad (ref: op_test.py:3129) ----------------------------------
    def test_check_grad(self):
        base = self.inputs()
        float_names = [k for k, v in base.items()
                       if np.issubdtype(v.dtype, np.floating)]
        names = list(getattr(self, "grad_inputs", float_names))
        if not names:
            return
        # floats to f32 for finite differences; ints (indices) unchanged
        arrs = {k: np.asarray(v, np.float32)
                if np.issubdtype(v.dtype, np.floating) else v
                for k, v in base.items()}

        def scalar_loss(*xs):
            out = type(self).op_fn(
                *[Tensor(jnp.asarray(x)) for x in xs], **self.attrs)
            return float((out * out).sum().numpy() / 2)

        # analytic grads via the framework's eager backward
        tensors = [paddle.to_tensor(arrs[k],
                                    stop_gradient=k not in names)
                   for k in arrs]
        out = type(self).op_fn(*tensors, **self.attrs)
        ((out * out).sum() * 0.5).backward()

        for idx, k in enumerate(arrs):
            if k not in names:
                continue
            analytic = tensors[idx].grad.numpy()
            numeric = self._numeric_grad(scalar_loss, list(arrs.values()),
                                         idx)
            denom = np.maximum(np.abs(numeric), 1.0)
            err = np.abs(analytic - numeric) / denom
            assert err.max() < self.grad_rtol, (
                f"{type(self).__name__} grad({k}): max rel err "
                f"{err.max():.4f} (analytic vs central differences)")

    def _numeric_grad(self, loss, args, idx):
        """Central finite differences (ref: op_test get_numeric_gradient)."""
        x = args[idx]
        g = np.zeros_like(x)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + self.grad_eps
            fp = loss(*args)
            flat[i] = orig - self.grad_eps
            fm = loss(*args)
            flat[i] = orig
            gf[i] = (fp - fm) / (2 * self.grad_eps)
        return g
