"""Context-parallel ring attention tests (above-parity feature;
no reference analog — parity gate is against full attention)."""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture
def qkv(rng):
    import jax.numpy as jnp
    B, L, H, D = 2, 32, 4, 16
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "sp"))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, qkv, causal):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paddle_tpu.distributed.ring_attention import ring_attention
        from paddle_tpu.ops.pallas.flash_attention import _sdpa_xla

        q, k, v = qkv
        mesh = _mesh()
        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        ref = _sdpa_xla(q, k, v, causal=causal)
        out = ring_attention(qs, ks, vs, mesh, "sp", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_gradients_match(self, qkv):
        import jax

        from paddle_tpu.distributed.ring_attention import ring_attention
        from paddle_tpu.ops.pallas.flash_attention import _sdpa_xla

        q, k, v = qkv
        mesh = _mesh()
        g1 = jax.grad(lambda a, b, c: (
            ring_attention(a, b, c, mesh, "sp", True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda a, b, c: (
            _sdpa_xla(a, b, c, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_llama_context_parallel_matches_plain(self, rng):
        """Llama with cp_mesh set == plain Llama (loss + grads)."""
        from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                       LlamaPretrainingCriterion)

        ids = paddle.to_tensor(rng.integers(0, 128, (2, 32)).astype(np.int32))
        crit = LlamaPretrainingCriterion()

        paddle.seed(7)
        plain = LlamaForCausalLM(LlamaConfig.tiny(use_flash_attention=False))
        loss_plain = crit(plain(ids), ids)

        paddle.seed(7)
        cp = LlamaForCausalLM(LlamaConfig.tiny(
            use_flash_attention=False, cp_mesh=_mesh(), cp_axis="sp"))
        loss_cp = crit(cp(ids), ids)
        np.testing.assert_allclose(float(loss_plain), float(loss_cp),
                                   rtol=1e-5)
        loss_cp.backward()
        loss_plain.backward()
        gp = plain.llama.layers[0].self_attn.q_proj.weight.grad.numpy()
        gc = cp.llama.layers[0].self_attn.q_proj.weight.grad.numpy()
        np.testing.assert_allclose(gc, gp, rtol=1e-3, atol=1e-6)
