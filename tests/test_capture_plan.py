"""Static capture planner (ISSUE 7): graph-break analysis (PTC001-004),
shape/dtype abstract interpretation + ops.yaml spec golden runs
(PTC005), and the planner that merges static findings with the dynamic
audit into one ranked, consistency-checked capture plan.

Acceptance pins: one seeded break per PTC rule detected by exact id; a
clean jittable step yields an empty plan (zero false positives); a
llama ``Model.fit`` step's plan is consistent with the dynamic audit
(every host sync / op_boundary flush covered or classified
capture-compatible); the serving decode step's checked-in clean-plan
fixture; the CAPTURE_ALLOWLIST stale-entry contract.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis
from paddle_tpu.analysis import capture, planner, shapes
from paddle_tpu.analysis.capture import scan_source


def _rules(diags):
    return {d.rule for d in diags}


# ---------------------------------------------------------------------------
# static pass: one seeded break per rule, by exact id
# ---------------------------------------------------------------------------

class TestSeededBreaks:
    def test_ptc001_branch_on_tensor(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    if t:\n"
            "        t = paddle.add(t, 1.0)\n"
            "    return t\n")
        assert "PTC001" in _rules(diags)

    def test_ptc001_while_item(self):
        diags = scan_source(
            "def step(x):\n"
            "    while x.item() > 0:\n"
            "        x = paddle.subtract(x, 1.0)\n"
            "    return x\n")
        d = [x for x in diags if x.rule == "PTC001"]
        assert d and "while" in d[0].message

    def test_ptc001_comparison_feeding_branch(self):
        diags = scan_source(
            "def step(x):\n"
            "    loss = paddle.mean(x)\n"
            "    if loss > 0.5:\n"
            "        loss = paddle.add(loss, 1.0)\n"
            "    return loss\n")
        assert "PTC001" in _rules(diags)

    def test_ptc001_builtin_named_tensor_methods_stay_tainted(self):
        # t.sum()/t.abs()/t.max() share builtin names but are tensor
        # ops: the loss/grad-norm check pattern must still flag
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    if t.sum() > 0:\n"
            "        t = paddle.add(t, 1.0)\n"
            "    n = t.abs().max()\n"
            "    if n > 1.0:\n"
            "        t = paddle.divide(t, n)\n"
            "    return t\n")
        assert len([d for d in diags if d.rule == "PTC001"]) == 2
        # ...while the BARE builtins still break taint (host values)
        diags = scan_source(
            "def step(xs):\n"
            "    n = len(xs)\n"
            "    if n > 1:\n"
            "        return paddle.add(xs, 1.0)\n"
            "    return xs\n")
        assert "PTC001" not in _rules(diags)

    def test_ptc001_metadata_branch_not_flagged(self):
        # shape/ndim/dtype are static metadata, not tensor values
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    if t.shape[0] > 1:\n"
            "        t = paddle.add(t, 1.0)\n"
            "    if t is not None:\n"
            "        t = paddle.add(t, 1.0)\n"
            "    return t\n")
        assert "PTC001" not in _rules(diags)

    def test_ptc002_inplace_subscript_store(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    t[0] = 0.0\n"
            "    return t\n")
        assert "PTC002" in _rules(diags)

    def test_ptc002_rng_consumption(self):
        diags = scan_source(
            "def step(x):\n"
            "    noise = paddle.rand([4, 4])\n"
            "    return paddle.add(x, noise)\n")
        d = [x for x in diags if x.rule == "PTC002"]
        assert d and "RNG" in d[0].message

    def test_ptc002_numpy_host_rng_not_flagged(self):
        # host-side data-prep RNG is not device RNG consumption
        diags = scan_source(
            "def step(x):\n"
            "    idx = np.random.uniform(0, 1, (4,))\n"
            "    return paddle.add(x, 1.0)\n")
        assert "PTC002" not in _rules(diags)

    def test_ptc002_self_state_mutation(self):
        diags = scan_source(
            "def step(self, x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    self.history.append(1)\n"
            "    self.count += 1\n"
            "    return t\n", tensor_params=("x",))
        d = [x for x in diags if x.rule == "PTC002"]
        assert len(d) >= 2

    def test_ptc002_host_io(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    print(t)\n"
            "    return t\n")
        d = [x for x in diags if x.rule == "PTC002"]
        assert d and "host I/O" in d[0].message

    def test_ptc003_tail_read_is_hoistable(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    loss = paddle.mean(t)\n"
            "    return loss.item()\n")
        d = [x for x in diags if x.rule == "PTC003"]
        assert d and d[0].data["hoistable"]
        assert "move the fetch after the step" in d[0].hint

    def test_ptc003_midstep_read_needs_guard(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    v = t.numpy()\n"
            "    u = paddle.add(t, 1.0)\n"
            "    return u\n")
        d = [x for x in diags if x.rule == "PTC003"]
        assert d and not d[0].data["hoistable"]

    def test_ptc003_read_in_device_loop_not_hoistable(self):
        # the fetch is the LAST line, but the loop re-enters device work
        diags = scan_source(
            "def step(x):\n"
            "    for i in range(4):\n"
            "        x = paddle.add(x, 1.0)\n"
            "        v = x.item()\n"
            "    return v\n")
        d = [x for x in diags if x.rule == "PTC003"]
        assert d and not d[0].data["hoistable"]

    def test_ptc003_read_before_optimizer_step_not_hoistable(self):
        # the optimizer's update is device work on an untainted
        # receiver: a read before it must NOT be graded hoistable
        diags = scan_source(
            "def step(self, x):\n"
            "    loss = paddle.mean(x)\n"
            "    loss.backward()\n"
            "    v = loss.item()\n"
            "    self.opt.step()\n"
            "    return v\n", tensor_params=("x",))
        d = [x for x in diags if x.rule == "PTC003"]
        assert d and not d[0].data["hoistable"], [x.to_dict()
                                                 for x in d]

    def test_capture_scan_seeds_defaultless_params(self):
        # a live callable's defaultless params are tensor-seeded (the
        # step's data args); params with defaults are config knobs
        def step(x, update=True):
            if x.mean() > 0:
                return x
            if update:
                return x
            return x

        diags, _ = capture.capture_scan(step)
        hits = [d for d in diags if d.rule == "PTC001"]
        assert len(hits) == 1, [d.to_dict() for d in diags]

    def test_loop_carried_taint_chain_reaches_fixpoint(self):
        # a = b; b = c; c = <tensor> around a loop needs one taint
        # pass per hop — the fixpoint loop must find `if a:`
        diags = scan_source(
            "def step(x):\n"
            "    a = 0\n"
            "    b = 0\n"
            "    c = 0\n"
            "    for i in range(3):\n"
            "        if a:\n"
            "            x = paddle.add(x, 1.0)\n"
            "        a = b\n"
            "        b = c\n"
            "        c = paddle.multiply(x, 2.0)\n"
            "    return x\n", tensor_params=("x",))
        assert "PTC001" in _rules(diags)

    def test_ptc003_numpy_host_chain_not_flagged(self):
        diags = scan_source(
            "def step(x):\n"
            "    return np.asarray([1, 2]).item()\n")
        assert "PTC003" not in _rules(diags)

    def test_ptc004_boolean_mask_indexing(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    mask = t > 0.5\n"
            "    return t[mask]\n")
        assert "PTC004" in _rules(diags)

    def test_ptc004_nonzero(self):
        diags = scan_source(
            "def step(x):\n"
            "    return paddle.nonzero(x)\n")
        assert "PTC004" in _rules(diags)

    def test_ptc001_scalar_converter_in_branch(self):
        # `if float(t) > 0:` is data-dependent control flow (PTC001),
        # NOT a hoistable read — a hoist hint here would be wrong
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.mean(x)\n"
            "    if float(t) > 0:\n"
            "        return paddle.add(x, 1.0)\n"
            "    return x\n")
        assert "PTC001" in _rules(diags)
        assert not any(d.rule == "PTC003" and d.data.get("hoistable")
                       for d in diags), [d.to_dict() for d in diags]
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.mean(x)\n"
            "    if bool(t):\n"
            "        return paddle.add(x, 1.0)\n"
            "    return x\n")
        assert "PTC001" in _rules(diags)

    def test_ptc004_integer_gather_not_flagged(self):
        # an integer-tensor gather has the INDEX's static shape; only
        # boolean masks make the result shape data-dependent
        diags = scan_source(
            "def step(x, w, ids):\n"
            "    h = paddle.matmul(x, w)\n"
            "    sel = h[ids]\n"
            "    return paddle.mean(sel)\n")
        assert "PTC004" not in _rules(diags)
        # inline comparison mask still flags
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    return t[t > 0]\n")
        assert "PTC004" in _rules(diags)

    def test_ptc004_static_slicing_not_flagged(self):
        diags = scan_source(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    return t[:, -1]\n")
        assert "PTC004" not in _rules(diags)

    def test_pragma_suppresses_ptc(self, tmp_path):
        p = tmp_path / "step_mod.py"
        p.write_text(
            "def step(x):\n"
            "    t = paddle.multiply(x, 2.0)\n"
            "    print(t)  # lint-allow: PTC002 debug tap\n"
            "    return t\n")
        diags, meta = capture.scan_file_function(str(p), "step", ("x",))
        kept, supp = capture.apply_allowlist(diags, meta["pragmas"])
        assert not [d for d in kept if d.rule == "PTC002"]
        assert any(d.rule == "PTC002" for d, _ in supp)


# ---------------------------------------------------------------------------
# zero false positives: a clean jittable step -> empty plan
# ---------------------------------------------------------------------------

class TestCleanStep:
    def test_clean_step_static_scan_is_empty(self):
        diags = scan_source(
            "def step(x, w):\n"
            "    h = paddle.matmul(x, w)\n"
            "    h = paddle.nn.functional.relu(h)\n"
            "    loss = paddle.mean(paddle.multiply(h, h))\n"
            "    return loss\n")
        assert diags == [], [d.to_dict() for d in diags]

    def test_clean_step_plan_is_empty_and_consistent(self):
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        w = paddle.to_tensor(np.ones((8, 8), np.float32) * 0.1)

        def step():
            h = paddle.matmul(x, w)
            h = paddle.nn.functional.relu(h)
            return paddle.mean(paddle.multiply(h, h))

        plan = analysis.capture_plan(step, warmup=2)
        assert plan.diagnostics == [], \
            [d.to_dict() for d in plan.diagnostics]
        assert plan.consistent()
        bad = [b for b in plan.breaks
               if b["classification"] not in ("compatible",)]
        assert bad == [], bad


# ---------------------------------------------------------------------------
# shape/dtype abstract interpreter (PTC005)
# ---------------------------------------------------------------------------

class TestShapesInterpreter:
    def test_abstract_matches_live_representatives(self):
        from paddle_tpu.core import fusion
        cases = [
            ("add", [((3, 4), "float32"), ((4,), "bfloat16")], None),
            ("exp", [((2, 5), "bfloat16")], None),
            ("sum", [((2, 3, 4), "float32")],
             (("axis", (0, 2)), ("dtype", None), ("keepdim", True))),
            ("mean", [((3, 4), "float32")],
             (("axis", None), ("keepdim", False))),
            ("matmul", [((4, 3), "float32"), ((4, 5), "float32")],
             (("transpose_x", True), ("transpose_y", False))),
            ("linear", [((2, 3, 4), "bfloat16"), ((4, 6), "bfloat16"),
                        ((6,), "bfloat16")], ()),
            ("cast", [((3, 4), "float32")],
             (("dtype", np.dtype("bfloat16")),)),
        ]
        for op, avals, attrs in cases:
            got = shapes.abstract_eval(op, avals, attrs)
            want = fusion.infer_output_aval(op, avals, attrs)
            assert got is not None and want is not None, op
            assert got.shape == tuple(want[0]), (op, got, want)
            assert got.dtype == np.dtype(want[1]), (op, got, want)

    def test_all_declared_specs_pass_the_golden_run(self):
        diags = shapes.validate_specs()
        assert diags == [], "\n".join(d.render() for d in diags)

    def test_seeded_wrong_spec_fires_ptc005(self):
        assert _rules(shapes.validate_op("sum", "elementwise")) == \
            {"PTC005"}
        assert _rules(shapes.validate_op("matmul", "broadcast")) == \
            {"PTC005"}

    def test_spec_vocabulary_matches_registry(self):
        from paddle_tpu.ops.op_registry import SHAPE_SPECS
        assert set(shapes._EVALUATORS) == set(SHAPE_SPECS)

    def test_registry_rejects_unknown_or_missing_spec(self):
        from paddle_tpu.ops.op_registry import _norm_shape_spec
        with pytest.raises(ValueError):
            _norm_shape_spec("demo", "reduceish", True)
        with pytest.raises(ValueError):
            _norm_shape_spec("demo", None, "reduce")  # fusable, no spec
        assert _norm_shape_spec("demo", None, False) is None

    def test_interpret_recorded_signature(self):
        """Capture a real fused-program signature via the program
        observer and replay it abstractly: the interpreter's output
        aval must match the actual output, with no PTC005."""
        from paddle_tpu.core import fusion
        sigs = []
        prev = fusion._program_observer
        fusion._program_observer = lambda sig, event: sigs.append(sig)
        try:
            x = paddle.to_tensor(np.ones((4, 8), np.float32))
            y = paddle.to_tensor(np.full((4, 8), 2.0, np.float32))
            out = paddle.mean(
                paddle.multiply(paddle.add(x, y), y), axis=1)
            got = out.numpy()   # flush
        finally:
            fusion._program_observer = prev
        assert sigs, "no fused program was recorded"
        res = shapes.interpret_signature(sigs[-1])
        assert res["diagnostics"] == [], \
            [d.to_dict() for d in res["diagnostics"]]
        assert any(o is not None and o.shape == got.shape
                   and o.dtype == got.dtype for o in res["outputs"]), \
            (res["outputs"], got.shape, got.dtype)

    def test_bucketed_signatures_bound(self):
        sigs = shapes.bucketed_leaf_signatures(
            (8, 128), {1: "pow2"}, 512)
        assert len(sigs) == 10          # pow2 buckets for 1..512
        sigs = shapes.bucketed_leaf_signatures(
            (8, 128), {1: [64, 128, 256, 512]}, 512)
        assert len(sigs) == 4
        # two dynamic axes: the bound is the product, still finite
        sigs = shapes.bucketed_leaf_signatures(
            (8, 128), {0: [8, 16], 1: "pow2"}, 512)
        assert len(sigs) == 20


# ---------------------------------------------------------------------------
# planner: dynamic cross-checks
# ---------------------------------------------------------------------------

class TestPlannerDynamic:
    def test_seeded_sync_becomes_guard_break(self):
        def step():
            x = paddle.to_tensor(np.ones((4, 4), np.float32))
            y = paddle.add(paddle.multiply(x, 3.0), 1.0)
            y.numpy()                      # mid-step sync
            z = paddle.multiply(y, 2.0)
            return z

        plan = analysis.capture_plan(step, warmup=1)
        assert plan.consistent(), plan.unaccounted()
        rows = [b for b in plan.breaks
                if b["reason"] in ("host_read", "host_sync")
                and b["classification"] in ("guard", "hoist")]
        assert rows, plan.breaks
        assert any(b["rule"] == "PTC003" for b in rows)
        # the mid-step read must NOT be classified hoistable
        assert any(b["classification"] == "guard" for b in rows)

    def test_shape_churn_synthesizes_ptc004_bucket_row(self):
        from paddle_tpu.core import fusion
        fusion.clear_cache()  # earlier tests may have compiled these
        # exact chain structures — churn only shows on a cold cache

        def churn():
            for n in range(3, 9):
                x = paddle.to_tensor(np.ones((n,), np.float32))
                y = paddle.add(paddle.multiply(x, 2.0), 1.0)
                y.numpy()

        try:
            plan = analysis.capture_plan(churn, warmup=1)
        finally:
            # don't leave these structures warm for OTHER churn tests
            # (test_analysis.py uses the same chain/shapes)
            fusion.clear_cache()
        rows = [b for b in plan.breaks
                if b["classification"] == "bucket"]
        assert rows, plan.breaks
        assert any(d.rule == "PTC004" for d in plan.diagnostics)
        assert any("BucketPolicy" in (b["fix"] or "") for b in rows)

    def test_bound_method_step_not_double_scanned(self):
        """The fn scan and the enclosing-origin scan name functions
        differently (__qualname__ vs bare name); dedupe is by source
        span, so a bound-method step is scanned ONCE."""
        from paddle_tpu.hapi import Model
        import paddle_tpu.nn as nn
        net = nn.Linear(4, 4)
        m = Model(net)
        m.prepare(loss=nn.MSELoss())
        x = np.ones((2, 4), np.float32)

        def step():
            m.eval_batch([x], [x])

        plan = analysis.capture_plan(step, warmup=1)
        spans = [(f["file"], tuple(f["span"])) for f in plan.functions]
        assert len(spans) == len(set(spans)), spans
        locs = [d.location for d in plan.static_diags] + \
            [d.location for d, _ in plan.suppressed]
        assert len(locs) == len(set(locs)), locs

    def test_plan_renders_and_dicts(self):
        def step():
            x = paddle.to_tensor(np.ones((4,), np.float32))
            return paddle.add(x, 1.0)

        plan = analysis.capture_plan(step, warmup=1)
        text = plan.render()
        assert "capture plan" in text and "consistent" in text
        d = plan.to_dict()
        assert "breaks" in d and "consistent" in d


# ---------------------------------------------------------------------------
# the acceptance test: llama Model.fit step, static ∪ dynamic consistent
# ---------------------------------------------------------------------------

class TestLlamaPlanConsistency:
    def _fit_model(self):
        from paddle_tpu.hapi import Model
        from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                       LlamaPretrainingCriterion)
        paddle.seed(0)
        net = LlamaForCausalLM(LlamaConfig.tiny())
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=net.parameters()),
            loss=LlamaPretrainingCriterion())
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (2, 16)).astype(np.int64)
        return m, ids

    def test_fit_step_plan_consistent_with_audit(self):
        """The EAGER plan (FLAGS_sot_capture=0): the per-chain path the
        planner audited before Fusion III implemented it. The loss
        fetch is now HOISTED out of train_batch, so the plan has no
        hapi sync row at all — and no allowlist entry carrying it."""
        m, ids = self._fit_model()

        def step():
            m.train_batch([ids], [ids])

        paddle.set_flags({"FLAGS_sot_capture": 0})
        try:
            plan = analysis.capture_plan(step, warmup=3)
        finally:
            paddle.set_flags({"FLAGS_sot_capture": 1})
        # the consistency contract: every PTA001 host sync and every
        # op_boundary flush site is covered by a PTC diagnostic with a
        # fix hint or classified capture-compatible
        assert plan.consistent(), plan.unaccounted()
        assert plan.breaks, "an eager llama train step has break rows"
        for b in plan.breaks:
            assert b["classification"] != "unaccounted", b
            assert b["fix"], b
        # the historical hapi loss fetch is GONE (hoisted to the fit
        # log boundary): no sync row, no PTC003, no allowlist carry
        hapi_rows = [b for b in plan.breaks
                     if "hapi/model.py" in b["site"]
                     and b["reason"] in ("host_sync", "host_read")]
        assert hapi_rows == [], hapi_rows
        assert not any("hapi/model.py" in d.location
                       and d.rule == "PTC003"
                       for d, _ in plan.suppressed)
        # op_boundary rows rank by measured flush cost and are absorbed
        ob = [b for b in plan.breaks if b["reason"] == "op_boundary"]
        assert ob and all(b["classification"] == "compatible"
                          for b in ob)
        assert ob == sorted(ob, key=lambda b: -b["count"])
        # no steady-state churn, so no bucket rows on the clean step
        assert not [b for b in plan.breaks
                    if b["classification"] == "bucket"]

    def test_flash_attention_step_plans_capturable(self):
        """ISSUE 16 satellite (ROADMAP item-3 step-one residue): a
        transformer step routed through the REAL flash-attention entry
        point (LlamaConfig.tiny() defaults use_flash_attention=True,
        so llama_attention dispatches ops.pallas.flash_attention)
        produces a consistent capture plan — and the planner's
        abstract interpreter resolves the attention aval through the
        declared `shape: attention` spec instead of treating the op
        as an opaque boundary."""
        from paddle_tpu.analysis import shapes
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny()
        assert cfg.use_flash_attention
        paddle.seed(0)
        net = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(
            (np.arange(32, dtype=np.int64) % 64).reshape(2, 16))

        def step():
            out = net(ids)
            logits = out[0] if isinstance(out, (tuple, list)) else out
            return paddle.mean(logits)

        plan = analysis.capture_plan(step, warmup=2)
        assert plan.consistent(), plan.unaccounted()
        assert not [b for b in plan.breaks
                    if b["classification"] == "unaccounted"]
        # non-vacuous spec resolution: q/k/v avals in, query aval out
        got = shapes.abstract_eval(
            "flash_attention", [((2, 16, 4, 8), "float32")] * 3, ())
        assert got is not None and got.shape == (2, 16, 4, 8)
        assert str(got.dtype) == "float32"

    def test_captured_fit_step_runs_dispatch_free(self):
        """ISSUE 10 acceptance, audit as the assertion engine: a
        steady-state captured llama train step is ONE executable call
        with ZERO host syncs and ZERO flushes inside the captured
        region, the plan stays CONSISTENT, and the kill switch restores
        eager per-chain fusion (the PR 6 -> 7 -> 10 loop closed)."""
        from paddle_tpu.observability import metrics as om
        m, ids = self._fit_model()

        def step():
            m.train_batch([ids], [ids])

        plan = analysis.capture_plan(step, warmup=3)
        assert plan.consistent(), plan.unaccounted()
        rep = plan.capture
        assert rep.syncs == [], rep.syncs
        assert len(rep.flushes) <= 3, rep.flushes   # a handful, not N
        assert rep.pair_builds == [] and rep.step_builds == []
        assert not [d for d in rep.diagnostics
                    if d.rule in ("PTA001", "PTA002", "PTA003")], \
            [d.to_dict() for d in rep.diagnostics]
        # <= 3 jitted executable calls per step (here: exactly one)
        before = dict(om.snapshot().get("sot", {}))
        m.train_batch([ids], [ids])
        after = dict(om.snapshot().get("sot", {}))
        captured = after.get("captured_steps_total", 0) - \
            before.get("captured_steps_total", 0)
        assert 1 <= captured <= 3, captured
        assert after.get("guard_misses_total", 0) == \
            before.get("guard_misses_total", 0)


# ---------------------------------------------------------------------------
# repo step functions: serving decode clean-plan fixture + allowlist
# ---------------------------------------------------------------------------

class TestRepoStepFixtures:
    def test_serving_decode_impl_is_clean(self):
        """The jitted decode/prefill bodies are the capture regions:
        zero findings, even unallowlisted — for the dense engine AND
        the paged one (block-table walk, streaming attention, pool
        scatter all stay functional)."""
        import os
        from paddle_tpu.analysis.lint import REPO_ROOT
        path = os.path.join(REPO_ROOT, "paddle_tpu", "serving.py")
        for qual, params in [
            ("LlamaDecodeEngine._decode_impl",
             ("params", "k_cache", "v_cache", "last_ids", "pos")),
            ("PagedLlamaDecodeEngine._decode_impl",
             ("params", "kv", "last_ids", "pos", "tables", "act")),
            ("PagedLlamaDecodeEngine._prefill_impl",
             ("params", "kv", "ids", "table_row", "start", "nvalid",
              "true_len")),
            ("PagedLlamaDecodeEngine._propose_impl",
             ("params", "kv", "last_ids", "pos", "tables", "act")),
            ("PagedLlamaDecodeEngine._spec_verify_impl",
             ("params", "kv", "last_ids", "draft_tok", "pos",
              "tables", "act")),
        ]:
            diags, _ = capture.scan_file_function(path, qual, params)
            assert diags == [], (qual, [d.to_dict() for d in diags])

    def test_serving_decode_step_clean_plan_fixture(self):
        """Checked-in expectation for the decode step/window/prefill
        loops (dense AND paged): the ONLY raw findings are the known
        slot/block bookkeeping mutations (PTC002) and the designed
        per-step/window/first-token fetch (PTC003, hoisted to the
        tail) — all allowlisted, so the effective plan is clean.
        Feeds ROADMAP item 2."""
        import os
        from paddle_tpu.analysis.lint import REPO_ROOT
        path = os.path.join(REPO_ROOT, "paddle_tpu", "serving.py")
        expected = {
            "LlamaDecodeEngine.step": {"PTC002": 2, "PTC003": 1},
            "LlamaDecodeEngine.decode_steps": {"PTC002": 1, "PTC003": 1},
            "PagedLlamaDecodeEngine.step": {"PTC002": 2, "PTC003": 1},
            "PagedLlamaDecodeEngine.decode_steps":
                {"PTC002": 1, "PTC003": 1},
            # begin_request: admission bookkeeping only — slot
            # activation (pos/active), prefill staging, and the
            # prefix-sharing hit record; the radix match/alias/COW
            # decision is allocator method calls, not step-state
            # mutation, so it adds NO findings beyond the hit record
            "PagedLlamaDecodeEngine.begin_request": {"PTC002": 4},
            # prefill_chunk: program-cache insert, prompt staging into
            # the padded host buffer, slot activation bookkeeping
            # (pos/active/last_ids), the draft-mirror last_ids seed +
            # the final-chunk first-token fetch (the radix
            # commit_prefix after each chunk is an allocator call —
            # no new finding)
            "PagedLlamaDecodeEngine.prefill_chunk":
                {"PTC002": 6, "PTC003": 1},
            # spec_step: commit bookkeeping (pos/last_ids) between the
            # propose/verify executables + the ONE window fetch
            # (tokens + accepted counts, both hoisted to the tail)
            "PagedLlamaDecodeEngine.spec_step":
                {"PTC002": 2, "PTC003": 2},
        }
        for qual, want in expected.items():
            diags, meta = capture.scan_file_function(path, qual, ())
            got = {}
            for d in diags:
                got[d.rule] = got.get(d.rule, 0) + 1
            assert got == want, (qual, [d.to_dict() for d in diags])
            # every token fetch is already at the tail (hoisted form)
            for d in diags:
                if d.rule == "PTC003":
                    assert d.data["hoistable"], d.to_dict()
            kept, supp = capture.apply_allowlist(
                diags, meta.get("pragmas"))
            assert kept == [], [d.to_dict() for d in kept]

    # (the clean-after-allowlist gate itself lives in
    # tests/test_lint_clean.py::test_repo_step_functions_capture_clean
    # — the tier-1 CI contract; not duplicated here)

    def test_static_repo_plan_consistent(self):
        plan = planner.plan_repo_steps()
        assert plan.consistent()
        assert plan.regions and len(plan.regions) >= 5

    def test_capture_allowlist_entries_all_match(self):
        """Stale-entry contract (the lint allowlist's rule, for PTC):
        every CAPTURE_ALLOWLIST entry must still suppress at least one
        raw finding."""
        import fnmatch
        from paddle_tpu.analysis.allowlist import CAPTURE_ALLOWLIST
        raw = capture.scan_repo_steps(use_allowlist=False)
        for rule, pattern, why in CAPTURE_ALLOWLIST:
            assert len(why.split()) >= 4, (rule, pattern, why)
            hit = any(
                d.rule == rule and (
                    fnmatch.fnmatch(d.location.partition(":")[0],
                                    pattern)
                    or fnmatch.fnmatch(d.location, pattern)
                    or fnmatch.fnmatch(d.message, pattern))
                for d in raw.diagnostics)
            assert hit, (f"CAPTURE_ALLOWLIST entry ({rule}, "
                         f"{pattern!r}) matches no finding — fixed "
                         f"site? delete the entry")

    def test_hapi_loss_fetch_hoisted(self):
        """Fusion III hoisted the hapi loss fetch: train_batch/
        eval_batch scan with ZERO raw findings (no .item() left to
        allowlist — the stale-entry contract forced the entry out),
        and the fetch now lives at the fit/evaluate log boundary."""
        raw = capture.scan_repo_steps(use_allowlist=False)
        hapi = [d for d in raw.diagnostics
                if "hapi/model.py" in d.location]
        assert hapi == [], [d.to_dict() for d in hapi]
        from paddle_tpu.analysis.allowlist import CAPTURE_ALLOWLIST
        assert not any("hapi" in pattern
                       for _, pattern, _ in CAPTURE_ALLOWLIST)


# ---------------------------------------------------------------------------
# CLI + self-check integration
# ---------------------------------------------------------------------------

class TestSurface:
    def test_cli_capture_plan(self, capsys):
        from paddle_tpu.analysis.__main__ import main
        assert main(["--capture-plan"]) == 0
        out = capsys.readouterr().out
        assert "capture plan" in out
        assert main(["--capture-plan", "--json"]) == 0
        import json
        d = json.loads(capsys.readouterr().out)
        assert d["consistent"] is True

    def test_self_check_exercises_ptc_rules(self):
        from paddle_tpu.analysis.report import self_check
        out = self_check()
        assert out["ok"], out
        assert out["checks"].get("capture") is True
        assert out["checks"].get("shapes") is True

    def test_rules_table_has_ptc_family(self):
        from paddle_tpu.analysis.diagnostics import RULES
        for rid in ("PTC001", "PTC002", "PTC003", "PTC004", "PTC005"):
            assert rid in RULES
            assert RULES[rid].analyzer == "capture"

    def test_lazy_exports(self):
        assert callable(analysis.capture_plan)
        assert callable(analysis.capture_scan)
        assert analysis.CapturePlan is planner.CapturePlan
